"""Continuous-batching serving engine over paged KV caches.

The TPU-native counterpart of the reference's serving stack around
block_multihead_attention (python/paddle/incubate/nn/functional/
block_multihead_attention.py over block_multi_head_attention_kernel.cu)
plus its sampling op (python/paddle/tensor/search.py:1362 top_p_sampling):
a fixed pool of KV pages + per-slot block tables, requests admitted into
free slots as others finish — decode compute and cache memory are bounded
by the pool, not by the longest request.

Design (one jitted program per phase, static shapes):
  - ``max_batch`` slots share per-layer page pools sized
    ``max_batch * ceil(max_len / page)`` pages (``_init_paged_caches``).
  - ADMIT: a new request prefills ITS slot only. With ``prompt_buckets`` the
    prompt is right-padded to the nearest bucket (one compilation per bucket):
    the padded chunk fills the cache, then the last REAL token is re-stepped
    at its true position so the first sampled token sees exactly the real
    prompt — pad cache entries sit beyond the attended window and are
    overwritten as decode advances.
  - STEP: ONE fused ``lax.scan`` of ``paged_token_step`` advances EVERY
    active slot — per-row positions flow into the paged decode kernel;
    inactive slots run on a parked dummy row whose output is ignored.
    Without eos the schedule is deterministic, so the engine runs toward the
    next completion event per program (scan lengths block_size·2^k), chains
    the last-token carry device-to-device, and materializes token values
    LAZILY (``_drain_pending``) — zero synchronous host round-trips, like
    ``generate()``'s async dispatch. eos-carrying batches pace at
    ``block_size`` tokens per host sync (early exit needs the values).
  - SAMPLE: per-request temperature / top-p / top-k / seed, applied
    row-vectorized inside the fused step. Keys are stateless:
    ``fold_in(key(seed), token_position)`` — reproducible per request and
    independent of batching/arrival order. temperature==0 is greedy.
  - FINISH: eos or max_new_tokens frees the slot; its pages are reused by
    the next admission (tables are per-slot, so no copying). Tokens decoded
    past an eos inside a block are discarded on the host (bounded waste,
    the standard continuous-batching speculation tradeoff).

Numerics: with default greedy sampling the engine is EXACTLY equal to
``generate(cache_impl='paged')`` (verified token-for-token on the real chip);
versus the dense-cache generate it matches exactly in fp32 (CPU tests) while
bf16-on-TPU tokens may diverge at softmax near-ties between the two attention
kernels — the standard cross-kernel serving caveat.

**Fused mega-step mode** (``fused=True``; auto at ``max_batch >= 32`` —
docs/SERVING.md): the big-batch (128-256 slot) step loop. Block tables,
per-slot positions, the active-row mask and the sampling state are
DEVICE-resident and mutated only by traced scatter programs
(``_queue_update`` -> ``_flush_updates``) — the per-step host rebuild +
``.copy()`` upload of ``_tables_host`` is gone, which also retires the
async-borrow hazard class (PT-TRACE-005) at the source. Decode runs as
ONE jitted mega-step over all ``max_batch`` rows with ``jnp.where``-masked
inactive rows (admission or completion never changes the program shape),
sampling and the position advance stay in-graph, and prefill packs
multiple (slot, chunk) rows into one ``paged_prefill_chunk`` call
(``_run_pack``). Host bookkeeping is O(active): occupied slots live in a
dict, free slots in a deque, and the per-step scans over ``max_batch``
are gone. Token streams are byte-identical to the legacy per-slot path
(greedy and seeded) — the fused programs run the same per-row math, and
per-row values are independent of batch width in fp32 (the warm==cold
argument; tests/test_serving_fused.py pins fused-vs-legacy equality).

**Speculative multi-token decoding** (``speculative=SpecConfig(...)``,
fused mode — docs/SERVING.md "Speculative decode"): each decode dispatch
emits 1..K+1 tokens per row — a device-resident n-gram drafter proposes K
tokens from a per-slot history ring, one K+1-wide ``paged_verify_step``
scores every position (``ops.paged_verify_attention`` append-then-gather),
and in-graph greedy exact-match acceptance keeps the longest correct
prefix plus one bonus token. Greedy output is byte-identical to the
non-speculative mega-step; sampling blocks keep the legacy path.

**int8 KV block format** (``kv_cache=KVCacheConfig(dtype="int8")`` —
docs/SERVING.md "int8 KV cache"): pools become
``ops.paged_attention.QuantizedKVPool`` — int8 pages with per-(page, head)
absmax scales, quantize-on-append / dequantize-in-gather — halving (bf16)
to quartering (f32) pool bytes, and composing with COW, the radix prefix
cache and ``KVChainCodec`` migration (PTKV1 carries dtype + scales).

``prefix_cache=PrefixCacheConfig(...)`` switches admission to a radix
prefix cache over a refcounted block pool with chunked prefill
(docs/SERVING.md): prompts sharing a system-prompt/few-shot prefix map the
already-filled KV blocks into their table and only prefill the uncached
suffix, one ``prefill_chunk`` per step interleaved with the decode batch;
a full-prompt hit copy-on-writes its last block before the first-token
re-step. For any given prompt, warm and cold admissions emit bit-identical
token streams (greedy and seeded sampling — see
``paged_prefill_attention``). One scoping note: a cached chain's final
block holds position L-1 k/v written by the first-token re-step's decode
program, so a LONGER prompt extending that chain reads re-step k/v where
its own cold prefill would have run the chunk-prefill program — the values
are mathematically equal but may differ in the last ulp under bf16 on TPU.
"""

from __future__ import annotations

import collections
import dataclasses
import time as _time
import weakref
from typing import Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


# THE sampler lives in generation_utils so generate() and the engine share one
# implementation; re-exported here for the serving-facing API surface.
from ..models.generation_utils import (fold_keys as _fold_keys,
                                       sample_rows, validate_sampling)
# host-side page bookkeeping lives next to the paged kernels; re-exported
# here as the serving-facing API surface
from ..ops.paged_attention import BlockAllocator, RadixPrefixCache

__all__ = ["AutoscaleConfig", "BlockAllocator", "BrownoutConfig",
           "ContinuousBatchingEngine", "EngineSaturated", "FleetConfig",
           "FleetRouter", "KVCacheConfig", "KVChainCodec", "KVChainCorrupt",
           "MeshConfig", "MeshDegraded", "PrefixCacheConfig",
           "RadixPrefixCache",
           "ReplicaState",
           "Request", "RequestJournal", "RequestShed", "SLOAutoscaler",
           "ServingSupervisor", "SpecConfig", "StepWatchdog", "TieredRouter"]


def __getattr__(name):
    # crash-recovery layer (recovery.py) re-exported lazily: it imports the
    # resilience stack, which must not load just because serving was
    # imported (same discipline as the faults/retry lazy imports below)
    if name in ("ServingSupervisor", "RequestJournal"):
        from . import recovery

        return getattr(recovery, name)
    if name in ("FleetRouter", "FleetConfig", "ReplicaState"):
        from . import fleet

        return getattr(fleet, name)
    if name in ("KVChainCodec", "KVChainCorrupt", "TieredRouter"):
        # disaggregated prefill/decode tiers (disagg.py) — lazy for the
        # same reason as the fleet: it pulls recovery + fleet in
        from . import disagg

        return getattr(disagg, name)
    if name in ("SLOAutoscaler", "AutoscaleConfig"):
        # the SLO-pressure autoscaler (autoscale.py) — lazy like the fleet:
        # importing serving must not pull the control loop in
        from . import autoscale

        return getattr(autoscale, name)
    if name == "StepWatchdog":
        from ..distributed.resilience.watchdog import StepWatchdog

        return StepWatchdog
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


class MeshDegraded(RuntimeError):
    """PT-SRV-008: the engine's tp device group lost devices mid-serve
    (the seeded ``device.loss`` fault site, or a real runtime device
    failure surfaced by the caller). Carries ``lost`` (devices gone) and
    ``survivors`` (devices still usable); the elastic
    :class:`ServingSupervisor` catches it, reshards the engine to the
    widest surviving tp width that still divides both head counts
    (falling to unsharded when none does), and re-admits every
    unfinished request from the journal byte-identically
    (docs/RESILIENCE.md "Elastic serving mesh")."""

    def __init__(self, msg: str, lost: int = 0, survivors: int = 1):
        super().__init__(msg)
        self.lost = int(lost)
        self.survivors = max(0, int(survivors))


class EngineSaturated(RuntimeError):
    """add_request refused: the engine's wait queue is at its high-water
    mark (``max_queue``). Admission control — callers shed load, retry with
    backoff, or scale out; the engine never hides an unbounded backlog."""


class RequestShed(RuntimeError):
    """add_request refused at SUBMIT time (PT-SRV-003): the request's
    ``deadline_s`` cannot be met at the engine's current decode throughput,
    so admitting it would only let it time out after queuing — wasting queue
    capacity and deadline-eviction work while helping nobody. Shedding
    happens before the request touches any engine state, so concurrently
    running requests' token streams are byte-identical to a run without the
    shed request. Callers route to another replica or degrade gracefully."""


@dataclasses.dataclass
class PrefixCacheConfig:
    """Knobs for the paged-KV prefix cache + chunked prefill
    (``ContinuousBatchingEngine(prefix_cache=...)`` — docs/SERVING.md).

    - ``prefill_chunk``: tokens prefilled per engine step per admitted slot
      (rounded up to a page multiple; default ``min(max_len, 8 * page)``).
      Long prompts advance one chunk per step INTERLEAVED with the decode
      batch, so a 2k-token admit no longer stalls every decoding slot.
    - ``extra_blocks``: pool headroom beyond the ``max_batch *
      pages_per_seq`` working set, retained for cached prefixes (0 still
      caches — prefix SHARING itself frees blocks).
    - ``pack_rows``: fused-mode prompt-packing budget — max (slot, chunk)
      rows per packed prefill call (default ``max(8, min(max_batch, 32))``;
      the pack always covers at least one chunk per mid-prefill slot, so
      this only bounds the EXTRA rows that let short prompts finish in one
      call)."""

    prefill_chunk: Optional[int] = None
    extra_blocks: int = 0
    pack_rows: Optional[int] = None


@dataclasses.dataclass
class SpecConfig:
    """Knobs for speculative multi-token decoding inside the fused
    mega-step (``ContinuousBatchingEngine(speculative=...)`` —
    docs/SERVING.md "Speculative decode").

    - ``k``: draft tokens proposed (and verified) per dispatch — each spec
      dispatch can emit 1..k+1 tokens per row (accepted prefix + one bonus
      from the verify logits).
    - ``ngram``: match length of the device-resident prompt-lookup
      drafter — the row's last ``ngram`` tokens are searched in its
      history ring; the continuation after the most recent match becomes
      the draft.
    - ``history``: per-slot device ring-buffer length (tokens) the drafter
      searches — generated + prompt ids, seeded from the prompt at
      activation.
    - ``_unsafe_accept_all``: DRILL-ONLY (tools/fault_drill.py
      ``spec_decode_divergence`` control arm): skip the argmax
      verification and trust every draft — demonstrates the silent greedy
      divergence the in-graph verify exists to prevent. Never enable.

    Greedy (temperature==0) output is byte-identical to the
    non-speculative mega-step — drafts only change how many tokens a
    dispatch emits, never which tokens. Blocks containing sampling rows
    (temperature>0) keep the legacy sampled mega-step.

    Composition with ``KVCacheConfig(dtype="int8")``: rejected drafts'
    appends feed the int8 blocks' monotone absmax scales, so a spec+int8
    engine's streams may differ from a NON-spec int8 engine's in the last
    quantization bit (int8 is lossy either way). What still holds — and
    is pinned by tests — is full determinism: identical spec+int8
    engines, warm/cold re-admissions and crash replay reproduce the same
    bytes (drafts are a deterministic function of the stream, so so is
    the rejected-append garbage)."""

    k: int = 4
    ngram: int = 2
    history: int = 64
    _unsafe_accept_all: bool = False


@dataclasses.dataclass
class KVCacheConfig:
    """Paged-KV pool storage format
    (``ContinuousBatchingEngine(kv_cache=...)`` — docs/SERVING.md "int8 KV
    cache"). ``dtype="int8"`` switches every pool to the int8 block
    format (``ops.paged_attention.QuantizedKVPool``): int8 pages with
    per-(page, head) absmax scales, quantize-on-append /
    dequantize-in-gather — pool bytes drop ~itemsize-fold (bf16 halves),
    doubling effective slots and radix prefix-cache reach at equal memory.
    Composes with COW (scales copy with the page), the radix prefix cache,
    and ``KVChainCodec`` migration (the PTKV1 artifact carries dtype +
    scales, crc over the int8 bytes)."""

    dtype: Optional[str] = None

    def __post_init__(self):
        if self.dtype not in (None, "param", "int8"):
            raise ValueError(f"unsupported KV cache dtype {self.dtype!r} "
                             "(supported: None/'param', 'int8')")


@dataclasses.dataclass
class MeshConfig:
    """Mesh-sharded serving (``ContinuousBatchingEngine(mesh=...)`` —
    docs/SERVING.md "Sharded serving").

    ``tp`` devices run every hot-path program (fused mega-step, packed
    prefill chunk, speculative verify, first-token re-step) under
    ``shard_map``: weights are column-sharded along their OUTPUT dim
    (q/k/v along heads, gate/up along mlp, an untied lm_head along
    vocab), the paged KV pools shard along kv_heads to match the k/v
    projections, and the only collectives are ``all_gather``s of
    DISJOINT shards — pure data movement. Every output element is
    computed whole on exactly one device with its contraction in the
    original order, so greedy streams are byte-identical to the
    1-device engine at any ``tp`` (the serving identity contract; a
    psum-style partial-sum reduction would reassociate and is
    impossible by construction in this layout). In-replica ``tp``
    composes with procfleet scale-out: each worker binds its own device
    group (``ProcFleetConfig.mesh``).

    - ``tp``: tensor-parallel width (devices per engine replica).
    - ``devices``: explicit device list (length >= tp; default
      ``jax.devices()[:tp]``) — procfleet workers pass their group.
    - ``abstract``: build a symbolic ``jax.sharding.AbstractMesh``
      instead of binding real devices — tracing/audit only (PT-COMM /
      PT-COST record the sharded programs' contracts on a 1-device
      host this way); actually dispatching on an abstract engine fails
      by construction.

    Requires the fused engine with a prefix cache, and a model that
    opts in via the ``tp_serving = True`` marker (llama; GPT's fused
    interleaved qkv projection cannot be column-sharded)."""

    tp: int = 1
    devices: Optional[Sequence] = None
    abstract: bool = False

    def __post_init__(self):
        if int(self.tp) < 1:
            raise ValueError(f"MeshConfig.tp must be >= 1, got {self.tp}")


def ngram_draft(hist, hlen, last_tok, k: int, n: int):
    """Device-resident prompt-lookup drafter (no draft model, no host
    sync): propose ``k`` draft tokens per row from its history ring.

    ``hist`` [B, H] int32 ring buffer of emitted tokens (token with global
    index g lives at slot g % H), ``hlen`` [B] tokens written so far,
    ``last_tok`` [B] the newest token (not yet in the ring — it enters on
    the next spec step, so the effective sequence is
    ``hist-window ++ last_tok``). The row's last ``n`` tokens are matched
    against every earlier window; the ``k`` tokens following the MOST
    RECENT match become the draft. No match (or under ``n`` tokens of
    history) falls back to repeating ``last_tok`` — drafts never affect
    WHICH tokens are emitted (greedy verify is exact), only how many per
    dispatch, so the fallback costs acceptance, never correctness."""
    H = hist.shape[1]
    g = hlen[:, None] - H + jnp.arange(H)[None, :]      # global idx per slot
    lin = jnp.take_along_axis(hist, jnp.mod(g, H), axis=1)
    lin = jnp.concatenate([lin, last_tok[:, None]], axis=1)     # [B, H+1]
    L = H + 1
    tail = lin[:, L - n:]                               # the current n-gram
    J = L - n                                           # candidate starts
    win_idx = jnp.arange(J)[:, None] + jnp.arange(n)[None, :]
    wins = lin[:, win_idx]                              # [B, J, n]
    match = jnp.all(wins == tail[:, None, :], axis=-1)  # [B, J]
    valid = (g >= 0)[:, :J]          # J = L - n <= H: start-slot validity
    jv = jnp.where(match & valid, jnp.arange(J)[None, :], -1)
    jbest = jnp.max(jv, axis=1)
    has = (jbest >= 0) & (hlen >= n)
    cont = jnp.clip(jbest[:, None] + n + jnp.arange(k)[None, :], 0, L - 1)
    drafts = jnp.take_along_axis(lin, cont, axis=1)
    return jnp.where(has[:, None], drafts,
                     last_tok[:, None]).astype(jnp.int32)


def spec_accept(drafts, targets, caps):
    """Pure accept/reject math of greedy speculative decoding (in-graph;
    host-testable without a model — tests/test_serving_spec.py).

    ``drafts`` [B, K] proposed tokens, ``targets`` [B, K+1] the greedy
    (argmax) token per verify-window position — ``targets[:, i]`` is what
    the model emits AFTER window position i, so draft i is correct iff
    ``drafts[:, i] == targets[:, i]`` and every earlier draft was.
    ``caps`` [B] >= 0 bounds per-row emission (max_new / max_len budget;
    0 masks a row out entirely).

    Returns ``(out [B, K+1], emit [B], n_acc [B])``: the emitted tokens
    are ``out[:, :emit]`` — the accepted draft prefix plus ONE bonus token
    (the model's own next token after the last accepted position), which
    is exactly the non-speculative greedy stream."""
    B, K = drafts.shape
    match = drafts == targets[:, :K]
    acc = jnp.cumprod(match.astype(jnp.int32), axis=1)
    n_acc = jnp.sum(acc, axis=1)                        # [B] 0..K
    emit = jnp.minimum(n_acc + 1, jnp.maximum(caps, 0))
    bonus = jnp.take_along_axis(targets, n_acc[:, None], axis=1)
    padded = jnp.concatenate([drafts, bonus], axis=1)   # [B, K+1]
    out = jnp.where(jnp.arange(K + 1)[None, :] < n_acc[:, None],
                    padded, bonus)
    return (out.astype(jnp.int32), emit.astype(jnp.int32),
            n_acc.astype(jnp.int32))


@dataclasses.dataclass
class BrownoutConfig:
    """Hysteretic degraded mode under sustained KV-pool pressure
    (``ContinuousBatchingEngine(brownout=...)`` — docs/SERVING.md).

    After ``enter_after`` consecutive steps with a deferred admission (the
    pool could not serve the queue head even after LRU eviction) the engine
    enters **brownout**: idle cached blocks are flushed back to the pool,
    prefix-cache admission stops matching/registering chains, and chunked
    prefill collapses to whole-prompt prefill — the byte-identical legacy
    serving behavior (warm==cold bit-identity means token streams cannot
    change, only memory/throughput shape). Brownout exits only after
    ``exit_after`` consecutive pressure-free steps with at least
    ``exit_free_frac`` of the pool free — hysteresis, so a workload
    oscillating at the edge does not flap the cache on and off."""

    enter_after: int = 2
    exit_free_frac: float = 0.5
    exit_after: int = 4


class Request:
    """One generation request tracked by the engine.

    Sampling params mirror ``generate()``: ``temperature=0`` (default) is
    greedy; otherwise temperature + optional top-p (nucleus) + top-k filter.
    ``seed`` (default: the request id) makes the request's sample stream
    reproducible regardless of batching or arrival order.

    ``deadline_s`` (measured from enqueue) bounds the request's total life
    — queue wait plus decode. A request past its deadline is evicted at the
    next engine step: ``done=True, failed=True``, ``error`` names the
    deadline, its slot/pages are freed, and other slots are untouched.
    Eviction latency is bounded by one decode block. A deadline the engine
    can already see is infeasible at submit time is refused with
    :class:`RequestShed` instead of queuing (PT-SRV-003).

    ``priority`` orders admission: lower values admit first (0 = highest);
    within a class, arrival order (FIFO) is preserved. Priorities reorder
    the WAIT QUEUE only — already-admitted slots are never preempted, so a
    late high-priority burst shortens queue wait without corrupting anyone's
    stream.
    """

    PRIORITY_HIGH = 0
    PRIORITY_NORMAL = 1
    PRIORITY_LOW = 2

    _counter = [0]

    def __init__(self, prompt_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0, top_p: float = 1.0,
                 top_k: int = 0, seed: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 priority: int = PRIORITY_NORMAL,
                 tenant: Optional[str] = None):
        validate_sampling(temperature, top_p, top_k)
        Request._counter[0] += 1
        self.rid = Request._counter[0]
        self.prompt = np.asarray(
            prompt_ids._data if isinstance(prompt_ids, Tensor) else prompt_ids
        ).reshape(-1).astype(np.int32)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.top_k = int(top_k)
        self.seed = int(seed if seed is not None else self.rid)
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.priority = int(priority)
        # workload tenant tag (observability/workload.py multi-tenant mix):
        # rides the trace stamps so SLO attainment splits per tenant
        # (observability/slo.py); journaled, so it survives failover
        self.tenant = None if tenant is None else str(tenant)
        self.output: List[int] = []
        self.done = False
        self.failed = False
        self.error: Optional[str] = None
        self._enqueued_at: Optional[float] = None  # set by add_request
        # tokens SCHEDULED so far (device-side results may still be pending
        # materialization — without eos the schedule is deterministic, so the
        # engine books progress before reading any token value)
        self._n_out = 0
        self._engine = None  # weakref, set by add_request

    @property
    def tokens(self) -> List[int]:
        """Materialized output tokens. Under async (deterministic-schedule)
        batching, ``done`` can flip True while token blocks are still
        device-side; this accessor drains the engine's pending readbacks
        first, so it is always complete once ``done`` is True. Reading
        ``.output`` directly is only guaranteed complete after the engine's
        ``finished()`` has returned the request."""
        eng = self._engine() if self._engine is not None else None
        if eng is not None:
            eng._drain_pending()
        elif len(self.output) < self._n_out:
            raise RuntimeError(
                f"request {self.rid}: {self._n_out - len(self.output)} "
                "scheduled tokens were never materialized and the engine has "
                "been garbage-collected — keep the engine alive (or call its "
                "finished()) before dropping it")
        return self.output


class ContinuousBatchingEngine:
    # Carry/donation declaration for the jitted hot-path programs —
    # consumed by the jit builders below and pinned by tests
    # (test_program_cost.py: every declared carry must be donated, and
    # non-carries never); tools/audit_program_cost.py then audits the
    # resulting ``donated_invars`` off the TRACED programs (PT-COST-003).
    # The kv pools / device position vector are step-to-step carries;
    # donating them lets XLA alias the output buffers in place of keeping
    # two copies of the KV pool live across every decode block.
    # ``tables`` / ``act`` / the sampling vectors are NOT carries of these
    # programs (the mega-step returns neither) and must stay undonated.
    # Argnums index the builders' positional args.
    _MEGA_ARG_NAMES = ("params", "toks", "kv", "tables", "pos", "act",
                       "seeds", "temps", "tops", "topks")
    _MEGA_CARRIES = ("kv", "pos")
    _MEGA_DONATE_ARGNUMS = (2, 4)
    _CHUNK_ARG_NAMES = ("params", "ids", "kv", "rows", "starts")
    _CHUNK_CARRIES = ("kv",)
    _CHUNK_DONATE_ARGNUMS = (2,)
    # first-token program: kv is the carry worth donating (the full KV
    # pool); ``last_tok`` is also a carry but is max_batch int32s —
    # deliberately left undonated (not worth the aliasing constraint)
    _FIRST_ARG_NAMES = ("params", "last", "kv", "rows", "last_tok",
                        "ints", "floats")
    _FIRST_CARRIES = ("kv",)
    _FIRST_DONATE_ARGNUMS = (2,)
    # speculative verify mega-step (docs/SERVING.md "Speculative decode"):
    # kv pools, positions and the drafter's history ring/length are all
    # step-to-step carries; tables/act/caps are read-only inputs the host
    # keeps live across the call and must stay undonated.
    _SPEC_ARG_NAMES = ("params", "toks", "kv", "tables", "pos", "act",
                       "hist", "hlen", "caps")
    _SPEC_CARRIES = ("kv", "pos", "hist", "hlen")
    _SPEC_DONATE_ARGNUMS = (2, 4, 6, 7)

    def __init__(self, model, max_batch: int = 8, max_len: int = 512,
                 page_size: int = 64, block_size: int = 8,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 max_queue: Optional[int] = None,
                 prefix_cache: Union[bool, PrefixCacheConfig, None] = False,
                 compile_cache_cap: int = 64,
                 shed_infeasible: bool = True,
                 brownout: Union[bool, BrownoutConfig, None] = None,
                 fused: Optional[bool] = None,
                 speculative: Union[bool, SpecConfig, None] = None,
                 kv_cache: Union[str, KVCacheConfig, None] = None,
                 mesh: Union[int, "MeshConfig", None] = None,
                 tracer=None, trace_tags: Optional[Dict] = None,
                 donate_carry: bool = True,
                 _unsafe_overcommit: bool = False):
        self.model = model
        # buffer donation on the carry arguments of the jitted hot-path
        # programs (mega-step kv/pos, prefill-chunk / first-token kv).
        # Off switch exists for the PT-COST byte-identity A/B and for
        # debugging with retained pre-step buffers.
        self._donate_carry = bool(donate_carry)
        # per-request trace spans (observability.TraceRecorder — docs/
        # OBSERVABILITY.md): every stamp site is host-side, behind a single
        # `is not None` check, and records into a bounded buffer — nothing
        # on the jitted step path. Assignable post-construction (the
        # ServingSupervisor attaches one to factory-built engines).
        self.tracer = tracer
        self.trace_tags = dict(trace_tags or {})
        self.max_batch = max_batch
        self.max_len = max_len
        self.page_size = page_size
        self.block_size = max(1, int(block_size))
        # bounded-queue backpressure: add_request raises EngineSaturated
        # past this many waiting requests (None = unbounded, legacy)
        self.max_queue = None if max_queue is None else max(0, int(max_queue))
        self.prompt_buckets = (sorted(int(b) for b in prompt_buckets)
                               if prompt_buckets else None)
        if self.prompt_buckets and self.prompt_buckets[-1] > max_len:
            raise ValueError(f"prompt bucket {self.prompt_buckets[-1]} "
                             f"exceeds max_len {max_len}")
        self.compile_cache_cap = max(1, int(compile_cache_cap))
        if prefix_cache is True:
            prefix_cache = PrefixCacheConfig()
        elif not prefix_cache:
            prefix_cache = None
        self.prefix_cache = prefix_cache
        # deadline-feasibility shedding (PT-SRV-003): armed once the engine
        # has measured a decode rate; until then every deadline is admitted
        # (a cold engine has no basis to refuse work)
        self.shed_infeasible = bool(shed_infeasible)
        if brownout is True:
            brownout = BrownoutConfig()
        elif not brownout:
            brownout = None
        self._brownout_cfg = brownout if prefix_cache is not None else None
        self._brownout_active = False
        self._pressure_steps = 0
        self._clear_steps = 0
        self._deferred_step = False
        self._step_idx = 0
        # EMA of scheduled-tokens/s across engine steps — the denominator of
        # the feasibility estimate (updated only on steps that scheduled
        # tokens, so idle ticks don't decay it toward zero)
        self._ema_tok_s: Optional[float] = None
        self._sched_tokens = 0
        self._maxp = -(-max_len // page_size)
        # fused mega-step mode (module docstring / docs/SERVING.md):
        # device-resident tables/positions/sampling state + one jitted
        # decode program over all rows. Auto-enabled at big batch, where
        # per-step table uploads and O(max_batch) host scans dominate.
        self._fused = (max_batch >= 32) if fused is None else bool(fused)
        # speculative multi-token decoding (docs/SERVING.md "Speculative
        # decode"): a device-resident n-gram drafter + one K-wide verify
        # program per dispatch, greedy-exact. Fused-mode only — the spec
        # program IS a mega-step variant over the device-resident state.
        if speculative is True:
            speculative = SpecConfig()
        elif not speculative:
            speculative = None
        self._spec = speculative
        if self._spec is not None:
            if not self._fused:
                raise ValueError(
                    "speculative decoding needs the fused mega-step "
                    "(fused=True) — the drafter/verify state is "
                    "device-resident")
            if self._spec.k < 1 or self._spec.ngram < 1:
                raise ValueError("SpecConfig.k and .ngram must be >= 1")
            if self._spec.history < self._spec.ngram + self._spec.k:
                raise ValueError(
                    f"SpecConfig.history {self._spec.history} too short for "
                    f"ngram {self._spec.ngram} + k {self._spec.k}")
        # opt-in int8 paged-KV block format (docs/SERVING.md "int8 KV
        # cache"): pools become QuantizedKVPool (int8 pages + per-block
        # absmax scales) — every engine program and the migration codec
        # handle the format transparently.
        if isinstance(kv_cache, str):
            kv_cache = KVCacheConfig(dtype=kv_cache)
        elif kv_cache is None:
            kv_cache = KVCacheConfig()
        self.kv_cache = kv_cache
        self._kv_dtype = kv_cache.dtype if kv_cache.dtype == "int8" else None
        # mesh-sharded serving (docs/SERVING.md "Sharded serving"): every
        # hot-path program becomes jit(shard_map(...)) over a tp axis with
        # column-parallel weights and kv_heads-sharded pools. The gathers
        # concatenate disjoint shards — no reduction ever crosses a shard
        # boundary — so greedy streams stay byte-identical to the 1-device
        # engine (param specs + placement happen at the end of the ctor,
        # once the param list exists).
        if isinstance(mesh, int):
            mesh = MeshConfig(tp=mesh)
        self.mesh = mesh
        self._mesh = None
        self._mesh_axis = None
        if mesh is not None:
            if not self._fused or prefix_cache is None:
                raise ValueError(
                    "mesh-sharded serving needs the fused engine with a "
                    "prefix cache (fused=True, prefix_cache=...) — the "
                    "legacy step/prefill programs stay single-device")
            if not getattr(model, "tp_serving", False):
                raise ValueError(
                    f"{type(model).__name__} does not support tensor-"
                    "parallel serving (no tp_serving marker): its weights "
                    "must be column-shardable along heads/mlp/vocab")
            self._mesh_axis = "tp"
            tp = int(mesh.tp)
            if mesh.abstract:
                from ..static.comm.mesh import abstract_mesh

                self._mesh = abstract_mesh({self._mesh_axis: tp})
            else:
                devs = (list(mesh.devices) if mesh.devices is not None
                        else jax.devices()[:tp])
                if len(devs) < tp:
                    raise ValueError(
                        f"MeshConfig.tp={tp} needs {tp} devices, got "
                        f"{len(devs)} — on CPU hosts raise "
                        "--xla_force_host_platform_device_count")
                self._mesh = jax.sharding.Mesh(np.asarray(devs[:tp]),
                                               (self._mesh_axis,))
        # DRILL-ONLY knob (tools/fault_drill.py prefix_cache_exhaustion):
        # allocate past pool capacity by ripping blocks out of the radix
        # cache while live tables still map them — demonstrates the
        # corruption the refcounted path exists to prevent. Never enable.
        self._overcommit = bool(_unsafe_overcommit)
        if prefix_cache is not None:
            c = prefix_cache.prefill_chunk or min(max_len, 8 * page_size)
            self._chunk_tokens = -(-int(c) // page_size) * page_size
            n_blocks = (max_batch * self._maxp
                        + max(0, int(prefix_cache.extra_blocks)))
            # +1 page: parked decode rows (free / still-prefilling slots)
            # write their dummy token into a dedicated parking page, never
            # into a block another request may share
            self.caches = model._init_paged_caches(
                max_batch, max_len, page_size, num_blocks=n_blocks + 1,
                kv_dtype=self._kv_dtype)
            self._park = n_blocks
            self._alloc = BlockAllocator(n_blocks)
            self._radix = RadixPrefixCache(page_size, self._alloc)
            self._tables_host = np.full((max_batch, self._maxp), self._park,
                                        np.int32)
            self._tables_dirty = True
            self._slot_rows: List[Optional[np.ndarray]] = [None] * max_batch
            self._slot_blocks: List[Optional[List[int]]] = [None] * max_batch
            self._prefill_next: Dict[int, int] = {}
            self._jit_chunk: Dict[int, object] = {}
            self._jit_first: Dict[tuple, object] = {}
            self._cow_fn = None
            self._jit_cow_batch: Dict[int, object] = {}
            self._pack_rows = (max(8, min(max_batch, 32))
                               if prefix_cache.pack_rows is None
                               else max(1, int(prefix_cache.pack_rows)))
        else:
            self.caches = model._init_paged_caches(max_batch, max_len,
                                                   page_size,
                                                   kv_dtype=self._kv_dtype)
        self._slots: List[Optional[Request]] = [None] * max_batch
        # O(active) bookkeeping (big-batch refactor): occupied slots in a
        # dict, free slots in a deque — per-step work is bounded by what is
        # actually live, never by max_batch (a 256-slot engine pays those
        # scans per token otherwise). ``_slots`` stays the authoritative
        # slot array; these are maintained at the same chokepoints.
        self._occupied: Dict[int, Request] = {}
        self._free_slots: collections.deque = collections.deque(
            range(max_batch))
        # per-slot NEXT write position (== tokens currently in the slot's cache)
        self._pos = np.zeros(max_batch, np.int32)
        # last emitted token per slot, DEVICE-resident: the decode chain never
        # round-trips token values through the host (they're materialized
        # lazily from self._pending — see _drain_pending)
        self._last_tok = jnp.zeros(max_batch, jnp.int32)
        self._pending: List[tuple] = []
        self._temps = np.zeros(max_batch, np.float32)
        self._tops = np.ones(max_batch, np.float32)
        self._topks = np.zeros(max_batch, np.int32)
        self._seeds = np.zeros(max_batch, np.int32)
        # device copies of the sampling params, re-uploaded only when an
        # admission changes them (every host->device put costs a dispatch
        # through a remote runtime)
        self._samp_dev = None
        if self._fused:
            # device-resident per-slot step state: positions, active mask,
            # sampling params. Admission/release mutate them ONLY through
            # _queue_update -> _flush_updates (traced scatters applied at
            # the next decode dispatch) — no mutable host buffer is ever
            # handed to jnp.asarray, which retires the async-borrow hazard
            # class (PT-TRACE-005) at the source.
            self._dev_pos = jnp.zeros(max_batch, jnp.int32)
            self._dev_act = jnp.zeros(max_batch, jnp.bool_)
            self._dev_samp = (jnp.zeros(max_batch, jnp.int32),
                              jnp.zeros(max_batch, jnp.float32),
                              jnp.ones(max_batch, jnp.float32),
                              jnp.zeros(max_batch, jnp.int32))
            self._upd: Dict[int, tuple] = {}
            self._upd_width = min(max_batch, 32)
            self._jit_mega = None
            self._jit_apply = None
            if self._spec is not None:
                # drafter state: per-slot history ring + written count —
                # device-resident like pos/act, mutated only by the spec
                # program and the activation scatters (_flush_updates)
                self._dev_hist = jnp.zeros(
                    (max_batch, self._spec.history), jnp.int32)
                self._dev_hlen = jnp.zeros(max_batch, jnp.int32)
                self._jit_spec = None
            if self.prefix_cache is not None:
                # the device table starts all-parked (the legacy path
                # builds this lazily via the dirty-flag upload; the fused
                # path never uploads a host table at all)
                self.caches = {"kv": self.caches["kv"],
                               "tables": jnp.full(
                                   (max_batch, self._maxp), self._park,
                                   jnp.int32)}
                self._tables_dirty = False
        self._queue: collections.deque = collections.deque()
        self._finished: Dict[int, Request] = {}
        # deadline-carrying requests currently in the system: the per-step
        # expiry scan short-circuits to a single int check when zero (the
        # common serving case) — the r05 throughput dip was exactly this
        # class of always-on host work on the decode hot path
        self._n_deadlined = 0
        # resilience hooks cached at first step (module lookups + imports
        # off the per-step path; the lazy-import discipline is preserved —
        # nothing resilience-side loads until the engine actually steps)
        self._fault_hook = None
        self._device_loss_hook = None
        self._retry_stats_fn = None
        # host-side accounting: admission vs decode dispatch time (the
        # admission-stall share is stats["admit_host_s"] / wall) plus the
        # prefix-cache counters (docs/SERVING.md: hit_tokens / miss_tokens
        # feed serving_prefix_hit_rate; cow_copies / evictions expose block
        # lifecycle; compile_cache_entries is the bounded-compile-cache
        # telemetry, warned past ``compile_cache_cap``)
        self.stats = {"admit_host_s": 0.0, "decode_host_s": 0.0,
                      "compile_cache_entries": 0, "shed": 0,
                      "retry_attempts": 0, "retry_giveups": 0,
                      "fused_updates": 0,
                      # speculative decode counters (zero when spec off) —
                      # exported as pt_spec_proposed/accepted_total + the
                      # acceptance-rate gauge by the engine collector
                      "spec_proposed": 0, "spec_accepted": 0,
                      "spec_steps": 0,
                      # mesh-sharded serving telemetry (zero on unsharded
                      # engines — the collector renders the families
                      # unconditionally so dashboards never lose them):
                      # accumulated per-device collective wire bytes of
                      # every sharded dispatch + sharded decode dispatches
                      "mesh_collective_bytes": 0.0, "mesh_decode_steps": 0}
        # per-program collective census (label -> per-dispatch wire bytes),
        # filled lazily as each sharded program first dispatches — feeds
        # the serving collector and mirrors the PT-COMM contract entries
        self._mesh_programs: Dict[str, float] = {}
        # int8 block-format occupancy gauge (pt_kv_quant_blocks): pool
        # pages held in quantized form — 0 on fp engines
        self._kv_quant_blocks = (int(self.caches["kv"][0][0].shape[0])
                                 if self._kv_dtype == "int8" else 0)
        # int8 allocation hygiene (_reset_quant_blocks): one compiled
        # reset-scatter per power-of-two width
        self._jit_qreset: Dict[int, object] = {}
        if self.prefix_cache is not None:
            self.stats.update(hit_tokens=0, miss_tokens=0, cow_copies=0,
                              evictions=0, prefill_host_s=0.0,
                              brownouts=0, brownout_steps=0, packed_rows=0)

        from ..jit.api import _collect_state

        _, tensors = _collect_state(model)
        self._params = [t._data for t in tensors]
        self._tensors = tensors
        self._jit_prefill: Dict[int, object] = {}
        self._jit_step = None
        # mesh placement (real meshes: one device_put pass; abstract
        # meshes: specs only — the audit path never touches devices).
        # Head-granularity check first: a column shard must hold WHOLE
        # heads (the kv pools shard along kv_heads; a mid-head split
        # would break the per-shard [.., heads, head_dim] reshape).
        self._param_specs = None
        if self._mesh is not None:
            cfg = getattr(model, "config", None)
            tp = int(self.mesh.tp)
            for f in ("num_attention_heads", "num_key_value_heads"):
                n = getattr(cfg, f, None)
                if n is not None and int(n) % tp:
                    raise ValueError(
                        f"{f}={n} not divisible by mesh tp={tp} — shards "
                        "must hold whole heads (KV pools shard kv_heads)")
            self._param_specs = [self._tp_param_spec(t) for t in tensors]
            if not self.mesh.abstract:
                self._place_on_mesh()

    def _req_tags(self, req: "Request") -> Dict:
        """Stamp tags for per-request trace sites (submit / shed / admit —
        the queue-wait stamp): the engine-level tags plus the request's
        workload tenant, so SLO attainment and the queue-wait histogram
        events split per tenant (observability/slo.py)."""
        if req.tenant is None:
            return self.trace_tags
        tags = dict(self.trace_tags)
        tags["tenant"] = req.tenant
        return tags

    # ---- public API ----
    def add_request(self, req: Request) -> int:
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            raise EngineSaturated(
                f"engine queue at high-water mark ({self.max_queue} waiting, "
                f"{len(self._occupied)}/{self.max_batch} "
                "slots busy) — shed load or scale out")
        if len(req.prompt) + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {len(req.prompt)} + max_new {req.max_new_tokens} "
                f"exceeds engine max_len {self.max_len}")
        if self.prompt_buckets and len(req.prompt) > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt {len(req.prompt)} exceeds largest prompt bucket "
                f"{self.prompt_buckets[-1]}")
        if self.prefix_cache is not None:
            need = self._pages_needed(len(req.prompt), req.max_new_tokens)
            if need > self._alloc.num_blocks:
                raise ValueError(
                    f"request needs {need} KV blocks but the pool holds "
                    f"{self._alloc.num_blocks} — raise "
                    "PrefixCacheConfig.extra_blocks or shrink the request")
        # family-specific length limits (e.g. GPT's learned position table) —
        # the same validation generate() applies
        validate = getattr(self.model, "_validate_generate", None)
        if validate is not None:
            validate(len(req.prompt), len(req.prompt) + req.max_new_tokens)
        if self.tracer is not None:
            # stamp AFTER the caller-error validations (a ValueError'd
            # request never entered the system) but BEFORE the shed check
            # (a shed is a real terminal outcome of a real submission)
            self.tracer.submit(req.rid, len(req.prompt), req.max_new_tokens,
                               self._req_tags(req))
            try:
                self._shed_check(req)
            except RequestShed:
                self.tracer.shed(req.rid, self._req_tags(req))
                raise
        else:
            self._shed_check(req)
        req._engine = weakref.ref(self)
        req._enqueued_at = _time.monotonic()
        if req.deadline_s is not None:
            self._n_deadlined += 1
        # weighted admission order: lower priority value admits first; FIFO
        # within a class (insert behind every equal-or-higher-priority
        # waiter). The queue HEAD keeps its head-of-line semantics in
        # prefix mode — priorities only choose who the head is.
        q = self._queue
        i = len(q)
        while i > 0 and q[i - 1].priority > req.priority:
            i -= 1
        if i == len(q):
            q.append(req)
        else:
            q.insert(i, req)
        return req.rid

    def _shed_check(self, req: "Request"):
        """Deadline-feasibility admission control (PT-SRV-003): refuse at
        SUBMIT a request whose deadline cannot be met at the measured decode
        throughput — a typed :class:`RequestShed` now beats a deadline
        eviction after seconds of queue wait. Conservative by construction:
        no measured rate (cold engine) or no deadline means no shedding, and
        the backlog estimate counts only decode tokens ahead of the request
        (prefill compute is charged to the rate EMA, not the backlog)."""
        if (not self.shed_infeasible or req.deadline_s is None
                or self._ema_tok_s is None or self._ema_tok_s <= 0.0):
            return
        backlog = req.max_new_tokens
        for r in self._queue:
            if r.priority <= req.priority:
                backlog += r.max_new_tokens - r._n_out
        for r in self._occupied.values():   # O(active), never O(max_batch)
            backlog += max(0, r.max_new_tokens - r._n_out)
        est = backlog / self._ema_tok_s
        if est > req.deadline_s:
            self.stats["shed"] += 1
            raise RequestShed(
                f"PT-SRV-003: request rid={req.rid} shed at submit — "
                f"{backlog} backlog tokens at {self._ema_tok_s:.1f} tok/s "
                f"needs ~{est:.3f}s, past its {req.deadline_s:.3f}s deadline")

    def has_work(self) -> bool:
        return bool(self._queue) or bool(self._occupied)

    def active_slots(self) -> int:
        """Occupied slots (decoding + mid-prefill) — the O(1) counter the
        supervisor's ``load()`` and the metrics collectors read instead of
        scanning ``_slots`` (a 256-slot fleet pays that scan per request
        at routing time otherwise)."""
        return len(self._occupied)

    def step(self):
        """Advance active slots in ONE device program, then admit new
        requests while that program is in flight.

        Decode-first ordering (round 5, VERDICT "admission serializes with
        decode"): the decode scan for already-active slots is DISPATCHED
        before admission touches the host, so admission's prompt packing,
        prefill compile-cache lookups, and (on the eos path) its synchronous
        first-token materialization all overlap the in-flight decode block
        instead of stalling it. Newly admitted slots join the next block —
        on a single chip both programs execute serially anyway, so the
        schedule shift costs nothing while removing every host-side
        admission stall from the decode critical path. When all slots are
        idle, admission runs first so the wave starts without a wasted step.

        Without eos the whole schedule is DETERMINISTIC (a slot frees exactly
        when its request's max_new_tokens are scheduled), so no host decision
        ever needs a token VALUE: the engine runs to the next completion
        event per program, chains the last-token carry device-to-device, and
        defers all token materialization to ``_drain_pending`` — zero
        synchronous host round-trips in the decode path, exactly like
        ``generate()``'s async dispatch. eos-carrying batches pace at
        ``block_size`` and materialize each block (early exit needs the
        values). Host-side time is accounted in ``self.stats``
        (admit_host_s / decode_host_s) so the admission share is measurable
        at any workload."""
        if self._fault_hook is None:
            from ..distributed.resilience.faults import (device_loss,
                                                         maybe_inject)

            self._fault_hook = maybe_inject
            self._device_loss_hook = device_loss
        self._step_idx += 1
        # injection sites (docs/RESILIENCE.md): `serving.stall` sleeps the
        # step past its wall-clock budget (StepWatchdog / PT-SRV-002);
        # `serving.step` kills the engine mid-wave (ServingSupervisor
        # rebuild-from-journal / PT-SRV-001); `device.loss` removes devices
        # from the tp mesh (MeshDegraded / PT-SRV-008 — the elastic
        # reshard-and-resume drill). One global read each when no plan is
        # installed.
        self._fault_hook("serving.stall", f"step:{self._step_idx}")
        self._fault_hook("serving.step", f"step:{self._step_idx}")
        lost = self._device_loss_hook(f"step:{self._step_idx}")
        if lost > 0 and self._mesh is not None and not self.mesh.abstract:
            tp = int(self.mesh.tp)
            survivors = max(0, tp - lost)
            raise MeshDegraded(
                f"PT-SRV-008: tp={tp} device group lost {lost} device(s) "
                f"at step {self._step_idx} ({survivors} surviving) — "
                f"engine must reshard to a narrower mesh",
                lost=lost, survivors=survivors)
        t0 = _time.perf_counter()
        sched0 = self._sched_tokens
        self._deferred_step = False
        try:
            self._step_inner()
        finally:
            dt = _time.perf_counter() - t0
            d = self._sched_tokens - sched0
            if d > 0 and dt > 0:
                rate = d / dt
                self._ema_tok_s = (rate if self._ema_tok_s is None
                                   else 0.7 * self._ema_tok_s + 0.3 * rate)
            if self._brownout_cfg is not None:
                self._brownout_tick()

    def _brownout_tick(self):
        """Hysteretic brownout state machine (docs/SERVING.md), evaluated
        once per step: sustained admission deferrals enter the degraded
        mode (idle cached blocks flushed, matching/registration and chunked
        prefill off); a sustained pressure-free streak with real pool
        headroom exits it."""
        cfg = self._brownout_cfg
        if self._brownout_active:
            self.stats["brownout_steps"] += 1
            free_frac = self._alloc.free_blocks / max(1, self._alloc.num_blocks)
            if not self._deferred_step and free_frac >= cfg.exit_free_frac:
                self._clear_steps += 1
                if self._clear_steps >= cfg.exit_after:
                    self._brownout_active = False
                    self._pressure_steps = self._clear_steps = 0
            else:
                self._clear_steps = 0
            return
        if self._deferred_step:
            self._pressure_steps += 1
            if self._pressure_steps >= cfg.enter_after:
                self._brownout_active = True
                self._clear_steps = 0
                self.stats["brownouts"] += 1
                # flush cached-idle blocks: under pressure the working set
                # outranks reuse — reclaimed pages go straight back to the
                # pool the deferred head is waiting on
                self._radix.evict_lru(self._alloc.num_blocks)
                self.stats["evictions"] = self._radix.evictions
        else:
            self._pressure_steps = 0

    def _step_inner(self):
        self._evict_expired()
        if self.prefix_cache is not None:
            # chunked-prefill budget: the decode batch is dispatched first,
            # then every mid-prefill slot advances by ONE chunk and newly
            # complete prompts take their first token — a long admit costs
            # each decode step one chunk of prefill, never a full prompt
            decoding = len(self._occupied) > len(self._prefill_next)
            if decoding:
                self._decode_block()
            t0 = _time.perf_counter()
            self._admit()
            self._prefill_tick()
            self.stats["admit_host_s"] += _time.perf_counter() - t0
            if not decoding:
                self._decode_block()
            return
        if not self._occupied:
            t0 = _time.perf_counter()
            self._admit()
            self.stats["admit_host_s"] += _time.perf_counter() - t0
            self._decode_block()
            return
        self._decode_block()
        t0 = _time.perf_counter()
        self._admit()
        self.stats["admit_host_s"] += _time.perf_counter() - t0

    def _evict_expired(self):
        """Deadline enforcement: fail-and-free requests past ``deadline_s``
        (active slots AND still-queued requests) so a straggler can neither
        hog a slot forever nor hang its caller. Tokens already scheduled for
        an evicted slot stay in the pending readbacks — ``tokens`` remains
        complete up to the eviction point. A single int check when no
        deadline-carrying request is in the system."""
        if not self._n_deadlined:
            return
        now = _time.monotonic()

        def expired(r):
            return (r.deadline_s is not None and r._enqueued_at is not None
                    and now - r._enqueued_at > r.deadline_s)

        def fail(r):
            r.done = True
            r.failed = True
            r.error = (f"deadline exceeded: {now - r._enqueued_at:.3f}s > "
                       f"{r.deadline_s:.3f}s ({r._n_out} tokens scheduled)")
            self._mark_done(r)

        # O(active): walks the occupied dict, never all max_batch slots
        for i, req in sorted(self._occupied.items()):
            if expired(req):
                fail(req)
                # prefix mode: DECREFs (never frees) blocks other live
                # tables or the radix cache still reference
                self._release_slot(i)
        if any(expired(r) for r in self._queue):
            keep = collections.deque()
            for r in self._queue:
                if expired(r):
                    fail(r)
                else:
                    keep.append(r)
            self._queue = keep

    def _decode_block(self):
        t0 = _time.perf_counter()
        try:
            self._decode_block_inner()
        finally:
            self.stats["decode_host_s"] += _time.perf_counter() - t0

    def _decode_block_inner(self):
        if self._fused:
            # device-resident state: every admission/release queued since
            # the last block lands as ONE traced scatter program — the host
            # never rebuilds or re-uploads a [max_batch, pages] table
            self._flush_updates()
        elif self.prefix_cache is not None and self._tables_dirty:
            # dynamic block tables: rows for decode-ready slots map their
            # allocated (possibly shared) pages; free and still-prefilling
            # rows point at the parking page so the scan's dummy append can
            # never touch a block another request shares. The .copy() is
            # LOAD-BEARING: jax borrows the host buffer for an async
            # transfer, and _release_slot mutates _tables_host — without a
            # private snapshot the scan can observe post-mutation rows
            # (measured ~1/30 runs decoding against parking-page tables)
            self.caches = {"kv": self.caches["kv"],
                           "tables": jnp.asarray(self._tables_host.copy())}
            self._tables_dirty = False
        # O(active): the decode set comes from the occupied dict (sorted for
        # the legacy path's deterministic slot order), never a max_batch scan
        live = [(i, r) for i, r in sorted(self._occupied.items())
                if not (self.prefix_cache is not None
                        and i in self._prefill_next)]
        if not live:
            return
        if (self._spec is not None
                and not any(r.temperature > 0.0 for _, r in live)
                and all(self.max_len - int(self._pos[i]) >= self._spec.k
                        for i, _ in live)):
            # all-greedy block with verify-window headroom on every row
            # (the K+1 window writes k/v at positions pos-1 .. pos-1+K):
            # one speculative dispatch replaces the scan block. Sampling
            # rows keep the legacy sampled mega-step; rows at the max_len
            # boundary finish on ordinary blocks.
            return self._decode_spec_block(live)
        # block length: never decode past a request's max_new_tokens or the
        # engine max_len (pages beyond the table would clamp-corrupt)
        cap = min(min(r.max_new_tokens - r._n_out for _, r in live),
                  min(self.max_len - int(self._pos[i]) for i, _ in live))
        n = min(self.block_size, cap)
        async_ok = all(r.eos_token_id is None for _, r in live)
        if async_ok:
            # run toward the next completion event; allowed scan lengths are
            # block_size * 2^k so the compiled-program set stays O(log) in
            # max_len (each distinct n compiles a full-model scan)
            stretch = self.block_size
            while stretch * 2 <= cap:
                stretch *= 2
            n = max(n, cap if cap <= self.block_size else stretch)
        n = max(1, n)
        do_sample = bool(any(r.temperature > 0.0 for _, r in live))
        toks = self._last_tok
        t0_tr = None if self.tracer is None else self.tracer.now()
        if self._fused:
            # ONE jitted mega-step over all rows: decode + sampling +
            # position advance in-graph, inactive rows masked by the
            # device-side act vector — admission never retraces
            if self._jit_mega is None:
                self._jit_mega = self._build_mega_jit()
                self._note_compiled()
            seeds_d, temps_d, tops_d, topks_d = self._dev_samp
            out, self._last_tok, new_kv, self._dev_pos = self._jit_mega(
                self._params, toks, self.caches["kv"],
                self.caches["tables"], self._dev_pos, self._dev_act,
                seeds_d, temps_d, tops_d, topks_d, n_steps=n,
                do_sample=do_sample)
            self.caches = {"kv": new_kv, "tables": self.caches["tables"]}
        else:
            active = np.zeros(self.max_batch, bool)
            for i, _ in live:
                active[i] = True
            # parked rows decode at position 0 over slot-local pages —
            # harmless
            pos_vec = jnp.asarray(np.where(active, self._pos, 1) - 1)
            if self._jit_step is None:
                from ..core import autograd_engine
                from ..jit.api import _Swap

                def run(params, toks, caches, pos_vec, seeds, temps, tops,
                        topks, n_steps, do_sample):
                    def body(carry, _):
                        tok, cs, pos = carry
                        with autograd_engine.no_grad(), _Swap(self._tensors,
                                                              params):
                            logits, cs = self.model.paged_token_step(
                                tok, cs, pos)
                        if do_sample:
                            keys = _fold_keys(seeds, pos + 1)
                            nxt = sample_rows(logits, keys, temps, tops,
                                              topks)
                        else:
                            # all-greedy batches skip the sampler: its
                            # vocab-wide argsort costs ~10 ms/token at 32k
                            # vocab (measured 150x engine slowdown before
                            # this gate)
                            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                        return (nxt, cs, pos + 1), nxt

                    (tok, cs, _), out = jax.lax.scan(
                        body, (toks, caches, pos_vec), None, length=n_steps)
                    return jnp.swapaxes(out, 0, 1), tok, cs

                self._jit_step = jax.jit(
                    run, static_argnames=("n_steps", "do_sample"))
                self._note_compiled()
            if self._samp_dev is None:
                # private snapshots: jax borrows host buffers for async
                # transfers and these arrays mutate on admission/slot-release
                self._samp_dev = (jnp.asarray(self._seeds.copy()),
                                  jnp.asarray(self._temps.copy()),
                                  jnp.asarray(self._tops.copy()),
                                  jnp.asarray(self._topks.copy()))
            seeds_d, temps_d, tops_d, topks_d = self._samp_dev
            out, self._last_tok, self.caches = self._jit_step(
                self._params, toks, self.caches, pos_vec,
                seeds_d, temps_d, tops_d, topks_d, n_steps=n,
                do_sample=do_sample)
        t1_tr = None if self.tracer is None else self.tracer.now()
        if async_ok:
            entries = []
            tok_marks = [] if self.tracer is not None else None
            for i, req in live:
                took = min(n, req.max_new_tokens - req._n_out)
                entries.append((i, req, took))
                req._n_out += took
                self._sched_tokens += took
                if tok_marks is not None:
                    tok_marks.append((req.rid, req._n_out))
                self._pos[i] += took
                if req._n_out >= req.max_new_tokens:
                    req.done = True
                    self._mark_done(req)
                    self._release_slot(i)   # slot + its pages are free again
            if self.tracer is not None:
                # ONE lock acquisition for the whole block's stamps — the
                # PR 9 recorder RLock must not serialize a 256-row step
                self.tracer.decode_block_batch(
                    t0_tr, n, len(live), tok_marks, t1=t1_tr,
                    tags=self.trace_tags,
                    tokens=sum(e[2] for e in entries))
            self._pending.append((out, entries))
            return
        # eos path: materialize (in generation order — drain older pendings
        # first so req.output stays ordered across an async->sync transition)
        self._drain_pending()
        out = np.asarray(out)
        tok_marks = [] if self.tracer is not None else None
        block_tokens = 0
        for i, req in live:
            took = 0
            for j in range(n):
                tok = int(out[i, j])
                req.output.append(tok)
                req._n_out += 1
                took = j + 1
                if ((req.eos_token_id is not None and tok == req.eos_token_id)
                        or req._n_out >= req.max_new_tokens):
                    req.done = True
                    break
            self._pos[i] += took
            self._sched_tokens += took
            block_tokens += took
            if tok_marks is not None:
                tok_marks.append((req.rid, req._n_out))
            if req.done:
                self._mark_done(req)
                self._release_slot(i)       # slot + its pages are free again
        if self.tracer is not None:
            self.tracer.decode_block_batch(t0_tr, n, len(live), tok_marks,
                                           t1=t1_tr, tags=self.trace_tags,
                                           tokens=block_tokens)

    def run_until_done(self, max_steps: int = 100000):
        steps = 0
        while self.has_work() and steps < max_steps:
            self.step()
            steps += 1
        return self.finished()

    def finished(self) -> Dict[int, Request]:
        self._drain_pending()
        if self._fused:
            # control plane: land any queued release scatters so a drained
            # engine's device state (act mask / parked tables) is actually
            # drained, not pending the next decode dispatch
            self._flush_updates()
        # retry-registry snapshot rides here (control plane), NOT in step():
        # a per-step dict copy was measurable on the decode hot path
        if self._retry_stats_fn is None:
            from ..distributed.resilience.retry import retry_stats

            self._retry_stats_fn = retry_stats
        rs = self._retry_stats_fn()
        self.stats["retry_attempts"] = rs["attempts"]
        self.stats["retry_giveups"] = rs["giveups"]
        out, self._finished = self._finished, {}
        return out

    def _mark_done(self, req: "Request"):
        """Single chokepoint for request completion: surfaces the request
        in ``_finished``, retires its deadline from the expiry-scan
        counter, and stamps the terminal trace span (finish / evict /
        fail — the tracer infers the kind from failed+error)."""
        if req.deadline_s is not None:
            self._n_deadlined = max(0, self._n_deadlined - 1)
        self._finished[req.rid] = req
        if self.tracer is not None:
            self.tracer.finish(req.rid, req._n_out, failed=req.failed,
                               error=req.error, tags=self.trace_tags)

    def withdraw_queued(self, rid: int) -> bool:
        """Remove a still-WAITING request from the queue (never an admitted
        slot) — the fleet's drain-migration primitive. Returns False when
        the request is not in the queue."""
        for i, r in enumerate(self._queue):
            if r.rid == rid:
                del self._queue[i]
                if r.deadline_s is not None:
                    self._n_deadlined = max(0, self._n_deadlined - 1)
                return True
        return False

    # -- disaggregated-tier hooks (inference/disagg.py — docs/SERVING.md
    # "Disaggregated tiers") ------------------------------------------------
    def slot_of(self, rid: int) -> Optional[int]:
        """Slot currently serving ``rid`` (None when queued/finished) —
        O(active), never O(max_batch)."""
        for i, r in self._occupied.items():
            if r.rid == rid:
                return i
        return None

    def migration_ready(self) -> List[int]:
        """rids whose prefill is COMPLETE (first token scheduled, slot in
        the decode set) with decode work left — the prefill tier's
        migration candidates. Mid-chunk slots are not exportable: their
        cache holds a partial prompt and no sampling has happened."""
        out = []
        for i, r in sorted(self._occupied.items()):
            if self.prefix_cache is not None and i in self._prefill_next:
                continue
            if r._n_out >= 1 and not r.done:
                out.append(r.rid)
        return out

    def withdraw_active(self, rid: int) -> bool:
        """Release ``rid``'s ACTIVE slot without terminal bookkeeping —
        the KV-migration handoff (ownership moves to another engine; the
        request is neither done nor failed here). The caller must have
        exported the chain bytes FIRST: the decref'd pages may be
        re-mapped by the very next admission."""
        slot = self.slot_of(rid)
        if slot is None:
            return False
        req = self._slots[slot]
        if req.deadline_s is not None:
            self._n_deadlined = max(0, self._n_deadlined - 1)
        self._release_slot(slot)
        return True

    def admit_migrated(self, req: "Request", blocks: Sequence[int],
                       pos: int, last_tok: int) -> int:
        """Resume-at-position admission: occupy a free slot with a
        migrated finished-prefill chain whose pages the caller
        (:class:`~paddle_tpu.inference.disagg.KVChainCodec`) has already
        allocated (refcount 1) and filled with the exported bytes.

        Maps the table row, restores the device position and last-token
        carry, and registers the prompt's full pages in the radix cache so
        the migrated prefix is cache-visible to later admissions (first
        writer wins — a duplicate chain stays private). Decode then
        continues through the ordinary step programs: sample keys are
        stateless (``fold_in(seed, position)``), so given the same page
        bytes the continued stream is bit-identical to never migrating.
        Raises :class:`EngineSaturated` when no slot is free — the caller
        still owns ``blocks`` and must decref them."""
        if self.prefix_cache is None:
            raise ValueError("KV-chain splice needs a prefix-cache engine "
                             "(dynamic block tables over the refcounted "
                             "pool)")
        if not self._free_slots:
            raise EngineSaturated(
                f"no free slot for migrated rid={req.rid} "
                f"({len(self._occupied)}/{self.max_batch} busy)")
        slot = self._free_slots.popleft()
        # int8 block hygiene: the chain's WRITTEN prefix was scattered
        # wholesale (bytes + scales) by the codec; the tail blocks the
        # chain will decode into are recycled allocations and need their
        # stale scales cleared
        if self._kv_dtype == "int8":
            n_written = max(0, -(-(int(pos) - 1) // self.page_size))
            self._reset_quant_blocks(list(blocks)[n_written:])
        row = np.full(self._maxp, self._park, np.int32)
        row[: len(blocks)] = blocks
        self._slot_rows[slot] = row
        self._slot_blocks[slot] = list(blocks)
        self._slots[slot] = req
        self._occupied[slot] = req
        req._engine = weakref.ref(self)
        # deadline clock RESTARTS at re-admission (recovery.py semantics:
        # a tier handoff is the operator's cost, not the request's)
        req._enqueued_at = _time.monotonic()
        if req.deadline_s is not None:
            self._n_deadlined += 1
        self._pos[slot] = int(pos)
        self._temps[slot] = req.temperature
        self._tops[slot] = req.top_p
        self._topks[slot] = req.top_k
        self._seeds[slot] = req.seed
        self._samp_dev = None
        # control-plane eager scatter: the decode chain reads the carry
        # from device state, and migration happens once per request
        self._last_tok = self._last_tok.at[slot].set(
            jnp.int32(int(last_tok)))
        if self._fused:
            # spec engines re-seed the drafter ring with prompt + delivered
            # tokens (minus the last-token carry restored above) so the
            # migrated stream drafts from its full history
            self._queue_update(slot, row, int(pos), True, req.seed,
                               req.temperature, req.top_p, req.top_k,
                               hist=(self._spec_seed(req.prompt,
                                                     extra=req.output[:-1])
                                     if self._spec is not None else None))
        else:
            self._tables_host[slot] = row
            self._tables_dirty = True
        n_full = len(req.prompt) // self.page_size
        if n_full and not self._brownout_active:
            self._radix.insert(req.prompt[: n_full * self.page_size],
                               list(blocks)[:n_full])
        return slot

    def _drain_pending(self):
        """Materialize deferred token blocks into request outputs.

        All host copies are STARTED asynchronously first — a remote runtime
        charges a full round trip per synchronous readback (measured ~130 ms
        through the axon tunnel), so serial np.asarray calls would dominate
        the whole decode wave."""
        for arr_dev, _ in self._pending:
            try:
                arr_dev.copy_to_host_async()
            except AttributeError:
                pass
        for arr_dev, entries in self._pending:
            arr = np.asarray(arr_dev)
            for row, req, took in entries:
                if arr.ndim == 1:           # prefill firsts [g]
                    req.output.append(int(arr[row]))
                else:                       # decode block [slots, n]
                    req.output.extend(int(t) for t in arr[row, :took])
        self._pending.clear()

    # ---- internals ----
    def _release_slot(self, i: int):
        """Free slot ``i``. Prefix mode DECREFS the slot's blocks (a shared
        prefix block stays alive while any other table or the radix cache
        references it — freeing it would corrupt the survivors) and parks
        the slot's decode-table row (fused mode: via the next traced
        scatter — freed pages may be re-mapped by the very next admission,
        and the inactive row's dummy append must never touch them)."""
        if self._slots[i] is not None:
            self._occupied.pop(i, None)
            self._free_slots.append(i)
        self._slots[i] = None
        self._pos[i] = 0
        self._temps[i] = 0.0
        if self.prefix_cache is not None:
            blocks = self._slot_blocks[i]
            if blocks:
                self._alloc.decref(blocks)
            self._slot_blocks[i] = None
            self._slot_rows[i] = None
            self._prefill_next.pop(i, None)
            if self._fused:
                # the device table (caches["tables"], scatter-updated) is
                # authoritative in fused mode — don't maintain a host
                # mirror that could silently drift from it
                self._queue_update(i, None, 0, False)
            else:
                self._tables_host[i] = self._park
                self._tables_dirty = True
        elif self._fused:
            self._queue_update(i, None, 0, False)

    # -- fused mega-step machinery (module docstring / docs/SERVING.md) ----
    def _queue_update(self, slot: int, row, pos: int, act: bool,
                      seed: int = 0, temp: float = 0.0, top_p: float = 1.0,
                      top_k: int = 0, hist=None):
        """Queue one slot's device-state change (activation or release).
        The LATEST update per slot wins — a release followed by a re-admit
        of the same slot in one step collapses to the admit — and
        everything queued lands as ONE traced scatter program at the next
        decode dispatch. ``row=None`` means the parking row (release) or
        an unchanged static table (legacy-layout engines). ``hist`` (spec
        engines) is the slot's drafter seed ``(ring_row, hlen)`` — None
        resets the ring (release / non-spec engines ignore it)."""
        self._upd[slot] = (None if row is None else np.asarray(row, np.int32),
                           int(pos), bool(act), int(seed), float(temp),
                           float(top_p), int(top_k), hist)

    def _flush_updates(self):
        """Apply queued slot updates to the device-resident step state in
        bounded-width batches of ONE scatter program each. Padding entries
        carry index ``max_batch`` — jax drops out-of-bounds scatter
        updates, so a single compiled program serves every update count."""
        if not self._upd:
            return
        items = list(self._upd.items())
        self._upd.clear()
        with_spec = self._spec is not None
        if self._jit_apply is None:
            with_tables = self.prefix_cache is not None

            def apply(tables, pos, act, seeds, temps, tops, topks, hist,
                      hlen, idx, urows, upos, uact, useeds, utemps, utops,
                      utopks, uhist, uhlen):
                if with_tables:
                    tables = tables.at[idx].set(urows)
                if with_spec:
                    hist = hist.at[idx].set(uhist)
                    hlen = hlen.at[idx].set(uhlen)
                return (tables, pos.at[idx].set(upos),
                        act.at[idx].set(uact), seeds.at[idx].set(useeds),
                        temps.at[idx].set(utemps), tops.at[idx].set(utops),
                        topks.at[idx].set(utopks), hist, hlen)

            self._jit_apply = jax.jit(apply)
            self._note_compiled()
        W = self._upd_width
        with_tables = self.prefix_cache is not None
        H = self._spec.history if with_spec else 1
        for lo in range(0, len(items), W):
            batch = items[lo:lo + W]
            idx = np.full(W, self.max_batch, np.int32)
            # legacy-layout engines have static slot-owned tables: the
            # apply program ignores urows, so don't build/upload the
            # [W, maxp] buffer at all (a 1-element dummy keeps the
            # signature); same for the drafter ring on non-spec engines
            urows = (np.full((W, self._maxp), self._park, np.int32)
                     if with_tables else np.zeros((1, 1), np.int32))
            uhist = (np.zeros((W, H), np.int32) if with_spec
                     else np.zeros((1, 1), np.int32))
            uhlen = np.zeros(W if with_spec else 1, np.int32)
            upos = np.zeros(W, np.int32)
            uact = np.zeros(W, bool)
            useeds = np.zeros(W, np.int32)
            utemps = np.zeros(W, np.float32)
            utops = np.ones(W, np.float32)
            utopks = np.zeros(W, np.int32)
            for j, (slot, (row, pos, act, seed, temp, top_p, top_k,
                           hist_seed)) in enumerate(batch):
                idx[j] = slot
                if with_tables and row is not None:
                    urows[j] = row
                if with_spec and hist_seed is not None:
                    uhist[j], uhlen[j] = hist_seed
                upos[j] = pos
                uact[j] = act
                useeds[j] = seed
                utemps[j] = temp
                utops[j] = top_p
                utopks[j] = top_k
            seeds_d, temps_d, tops_d, topks_d = self._dev_samp
            hist_d = self._dev_hist if with_spec else jnp.zeros((1, 1),
                                                                jnp.int32)
            hlen_d = self._dev_hlen if with_spec else jnp.zeros(1, jnp.int32)
            tables, self._dev_pos, self._dev_act, s, t, p, k, hist_d, \
                hlen_d = self._jit_apply(
                    self.caches["tables"], self._dev_pos, self._dev_act,
                    seeds_d, temps_d, tops_d, topks_d, hist_d, hlen_d, idx,
                    urows, upos, uact, useeds, utemps, utops, utopks,
                    uhist, uhlen)
            self._dev_samp = (s, t, p, k)
            if with_spec:
                self._dev_hist, self._dev_hlen = hist_d, hlen_d
            self.caches = {"kv": self.caches["kv"], "tables": tables}
            self.stats["fused_updates"] += len(batch)

    # -- mesh-sharded serving (docs/SERVING.md "Sharded serving") ----------
    def _tp_param_spec(self, t):
        """Column-parallel placement rule for ONE parameter: a 2-dim
        weight whose LAST logical axis is an output-feature axis (heads /
        mlp / vocab) shards that axis across tp; everything else —
        o_proj/down_proj (output axis "embed"), the embedding, norms —
        replicates. Splitting only output dims is what keeps every output
        element's contraction whole on one device (the identity
        contract); the matching all_gathers live in the model layers
        (distributed.auto_parallel.serving_sharding)."""
        from jax.sharding import PartitionSpec as P

        axes = getattr(t, "logical_axes", None) or ()
        data = t._data
        if data.ndim == 2 and axes and axes[-1] in ("heads", "mlp",
                                                    "vocab"):
            tp = int(self.mesh.tp)
            if data.shape[-1] % tp:
                raise ValueError(
                    f"param {axes} shape {tuple(data.shape)}: output dim "
                    f"{data.shape[-1]} not divisible by mesh tp={tp}")
            return P(None, self._mesh_axis)
        return P()

    def _kv_spec(self):
        """ONE PartitionSpec prefix covering EVERY kv-pool leaf: pools
        are [pages, kv_heads, page, head_dim] (the int8 format adds
        [pages, kv_heads] absmax scales) — all shard axis 1, the
        kv_heads axis, matching the column-sharded k/v projections.
        Appends, decode gathers, COW page copies, quant resets and the
        int8 scatter-max scales are then shard-local forever: no decode
        step ever reshards the pool, and per-(page, head) quantization
        partitions EXACTLY across head shards."""
        from jax.sharding import PartitionSpec as P

        return P(None, self._mesh_axis)

    def _arg_specs(self, kinds):
        from jax.sharding import PartitionSpec as P

        out = []
        for k in kinds:
            if k == "params":
                out.append(self._param_specs)
            elif k == "kv":
                out.append(self._kv_spec())
            else:
                out.append(P())
        return tuple(out)

    def _place_on_mesh(self):
        """One-time initial reshard: params column-sharded, kv pools
        sharded along kv_heads, block tables + device-resident step
        state replicated. After this no hot-path dispatch moves resident
        bytes between placements — the per-step collectives are exactly
        the activation all_gathers the census records. Stamped as one
        "reshard" tracer span (the only reshard boundary the engine
        has)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        t0 = None if self.tracer is None else self.tracer.now()
        mesh = self._mesh
        rep = NamedSharding(mesh, P())
        kv_sh = NamedSharding(mesh, self._kv_spec())
        put = jax.device_put
        self._params = [put(p, NamedSharding(mesh, s))
                        for p, s in zip(self._params, self._param_specs)]
        kv = jax.tree_util.tree_map(lambda x: put(x, kv_sh),
                                    self.caches["kv"])
        self.caches = {"kv": kv, "tables": put(self.caches["tables"], rep)}
        self._last_tok = put(self._last_tok, rep)
        self._dev_pos = put(self._dev_pos, rep)
        self._dev_act = put(self._dev_act, rep)
        self._dev_samp = tuple(put(x, rep) for x in self._dev_samp)
        if self._spec is not None:
            self._dev_hist = put(self._dev_hist, rep)
            self._dev_hlen = put(self._dev_hlen, rep)
        if self.tracer is not None:
            self.tracer.span("reshard", None, t0, tags=self.trace_tags,
                             tp=int(self.mesh.tp))

    def _mesh_census(self, name, key, fn, args):
        """Per-dispatch collective wire bytes of a freshly built sharded
        program: ONE extra trace (``make_jaxpr`` — no XLA compile, and
        BEFORE the first real call, so donation has not consumed any
        input buffer), censused by the PT-COMM walker. Recorded per
        program for the serving collector; failures degrade to 0.0 —
        the census is telemetry, never load-bearing."""
        label = name if not key else name + "@" + ",".join(map(str, key))
        total = 0.0
        try:
            from ..static.comm.collectives import iter_collectives

            jaxpr = jax.make_jaxpr(fn)(*args)
            for c in iter_collectives(jaxpr):
                total += c.total_wire_bytes
        except Exception:
            total = 0.0
        self._mesh_programs[label] = total
        if self.tracer is not None:
            self.tracer.instant("mesh_census", None, self.trace_tags,
                                program=label, wire_bytes=total)
        return total

    def _mesh_jit(self, run, in_kinds, out_kinds, donate, static_names=(),
                  name="program", count_stat=None):
        """jit(shard_map(run)) under the engine's placement contract:
        ``in_kinds``/``out_kinds`` name each argument/output "params"
        (per-param column specs), "kv" (kv_heads-sharded pool tree) or
        anything else (replicated); ``out_kinds`` may be the bare string
        "kv" for programs returning the pool tree alone. The body is
        traced inside :func:`serving_shard_axis`, the trace-time channel
        telling model layers to all_gather their column-sharded outputs.

        Returns a dispatcher callable. Statics (the mega-step's
        ``n_steps``/``do_sample``) select a cached
        ``jit(shard_map(partial(run, **statics)))`` — shard_map has no
        static-argument support, and baking them per variant keeps the
        ``donated_invars`` visible on the traced pjit equation exactly
        where PT-COST-003 audits them. First dispatch per variant runs
        the collective census once; every dispatch then accumulates the
        per-dispatch wire bytes into ``stats['mesh_collective_bytes']``."""
        from functools import partial

        from ..distributed.auto_parallel.serving_sharding import \
            serving_shard_axis
        from ..framework.jax_compat import shard_map

        axis = self._mesh_axis
        in_specs = self._arg_specs(in_kinds)
        out_specs = (self._kv_spec() if out_kinds == "kv"
                     else self._arg_specs(out_kinds))

        def build(**statics):
            fn = partial(run, **statics) if statics else run

            def body(*args):
                with serving_shard_axis(axis):
                    return fn(*args)

            sm = shard_map(body, mesh=self._mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
            return jax.jit(sm, donate_argnums=donate)

        cache = {}

        def dispatch(*args, **statics):
            key = tuple(statics[n] for n in static_names)
            ent = cache.get(key)
            if ent is None:
                fn = build(**statics)
                ent = cache[key] = (fn,
                                    self._mesh_census(name, key, fn, args))
            fn, per_dispatch = ent
            self.stats["mesh_collective_bytes"] += per_dispatch
            if count_stat is not None:
                self.stats[count_stat] += 1
            return fn(*args)

        return dispatch

    def _build_mega_jit(self):
        """The jitted mega-step EXACTLY as ``step`` dispatches it —
        donation included. tools/audit_program_cost.py traces this (pure
        tracing, no compile) so the audited ``donated_invars`` are the
        production program's, not a parallel declaration. Mesh engines
        get the same program as jit(shard_map(...)) behind a
        static-variant dispatcher (``_mesh_jit``) — byte-identical
        output, per-shard compute."""
        donate = self._MEGA_DONATE_ARGNUMS if self._donate_carry else ()
        if self._mesh is not None:
            return self._mesh_jit(
                self._mega_step_fn(), self._MEGA_ARG_NAMES,
                ("rep", "rep", "kv", "rep"), donate,
                static_names=("n_steps", "do_sample"), name="mega_step",
                count_stat="mesh_decode_steps")
        return jax.jit(self._mega_step_fn(),
                       static_argnames=("n_steps", "do_sample"),
                       donate_argnums=donate)

    def _mega_step_fn(self):
        """The fused mega-step program (tools/lint_graph.py records and
        lints this — the one program a 128-256-slot engine dispatches per
        decode block): decode ``n_steps`` tokens for every row at per-row
        positions, sample in-graph, and advance the device-side positions,
        with inactive rows masked by the ``act`` vector (they step a
        parked dummy row whose output the host ignores) — so admissions
        and completions never change the program shape and never retrace.
        The per-row math is IDENTICAL to the legacy ``_jit_step`` body,
        which is what makes fused-vs-legacy token streams byte-identical."""
        from ..core import autograd_engine
        from ..jit.api import _Swap

        def run(params, toks, kv, tables, pos, act, seeds, temps, tops,
                topks, n_steps, do_sample):
            caches = {"kv": kv, "tables": tables}
            pos_vec = jnp.where(act, pos, 1) - 1

            def body(carry, _):
                tok, cs, p = carry
                with autograd_engine.no_grad(), _Swap(self._tensors, params):
                    logits, cs = self.model.paged_token_step(tok, cs, p)
                if do_sample:
                    keys = _fold_keys(seeds, p + 1)
                    nxt = sample_rows(logits, keys, temps, tops, topks)
                else:
                    nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                return (nxt, cs, p + 1), nxt

            (tok, cs, _), out = jax.lax.scan(
                body, (toks, caches, pos_vec), None, length=n_steps)
            new_pos = jnp.where(act, pos + n_steps, pos)
            return jnp.swapaxes(out, 0, 1), tok, cs["kv"], new_pos

        return run

    # -- speculative multi-token decoding (docs/SERVING.md) ----------------
    def _build_spec_jit(self):
        """The jitted speculative verify mega-step EXACTLY as dispatched —
        donation included (kv / pos / drafter ring+length are the carries;
        tools/audit_program_cost.py traces this, PT-COST-003 audits the
        ``donated_invars``)."""
        donate = self._SPEC_DONATE_ARGNUMS if self._donate_carry else ()
        if self._mesh is not None:
            return self._mesh_jit(
                self._spec_step_fn(), self._SPEC_ARG_NAMES,
                ("rep", "rep", "rep", "kv", "rep", "rep", "rep"), donate,
                name="spec_verify", count_stat="mesh_decode_steps")
        return jax.jit(self._spec_step_fn(), donate_argnums=donate)

    def _spec_step_fn(self):
        """ONE speculative dispatch over all rows (draft -> verify ->
        accept/rollback, all in-graph):

        1. DRAFT: the device-resident prompt-lookup drafter
           (:func:`ngram_draft`) proposes K tokens per row from its
           history ring — no draft model, no host sync.
        2. VERIFY: the K+1 window [last_token, drafts] runs through the
           model's ``paged_verify_step`` (append-then-gather +
           absolute-position masking — ``ops.paged_verify_attention``),
           scoring every position in one pass.
        3. ACCEPT: greedy exact-match accept/reject
           (:func:`spec_accept`) keeps the longest draft prefix whose
           tokens equal the verify argmaxes, plus ONE bonus token — the
           emitted stream is byte-identical to the non-speculative
           mega-step. Rejected appends need no scatter rollback: the
           per-row position only advances over accepted tokens, so
           rejected k/v sits beyond the attended window and is
           overwritten as decode proceeds (the engine's standard
           pad-append invariant). Inactive rows are masked (emit 0) by
           the same act-vector idiom as the mega-step — churn never
           retraces."""
        from ..core import autograd_engine
        from ..jit.api import _Swap

        spec = self._spec
        K, N, H = spec.k, spec.ngram, spec.history
        accept_all = spec._unsafe_accept_all

        def run(params, toks, kv, tables, pos, act, hist, hlen, caps):
            pos_vec = jnp.where(act, pos, 1) - 1
            drafts = ngram_draft(hist, hlen, toks, K, N)
            window = jnp.concatenate([toks[:, None], drafts], axis=1)
            caches = {"kv": kv, "tables": tables}
            with autograd_engine.no_grad(), _Swap(self._tensors, params):
                logits, caches = self.model.paged_verify_step(
                    window, caches, pos_vec)
            targets = jnp.argmax(logits, -1).astype(jnp.int32)
            if accept_all:
                # DRILL-ONLY control arm (spec_decode_divergence): trust
                # every draft — the verification this path skips is what
                # keeps greedy streams byte-identical
                targets = jnp.concatenate([drafts, targets[:, K:]], axis=1)
            out, emit, _ = spec_accept(drafts, targets,
                                       jnp.where(act, caps, 0))
            emit = jnp.where(act, emit, 0)
            last = jnp.take_along_axis(
                out, jnp.clip(emit - 1, 0, K)[:, None], axis=1)[:, 0]
            last = jnp.where(emit > 0, last, toks)
            # ring append: the OLD last token plus all emitted-but-newest
            # tokens enter the ring; the newest rides the last-token carry
            vals = jnp.concatenate([toks[:, None], out[:, :K]], axis=1)
            j = jnp.arange(K + 1)[None, :]
            widx = jnp.where(j < emit[:, None],
                             (hlen[:, None] + j) % H, H)   # H: dropped
            hist = hist.at[jnp.arange(hist.shape[0])[:, None],
                           widx].set(vals)
            hlen = hlen + emit
            new_pos = jnp.where(act, pos + emit, pos)
            return out, emit, last, caches["kv"], new_pos, hist, hlen

        return run

    def _decode_spec_block(self, live):
        """Dispatch one speculative verify step and book its variable
        per-row emission. Unlike the deterministic-schedule scan path,
        acceptance is data-dependent — the per-row emit counts (a [B]
        int32 vector) are read back synchronously per dispatch; the token
        matrix itself stays a deferred readback (``_drain_pending``)
        unless an eos-carrying row needs the values."""
        spec = self._spec
        K = spec.k
        caps = np.zeros(self.max_batch, np.int32)
        for i, r in live:
            caps[i] = min(r.max_new_tokens - r._n_out,
                          self.max_len - int(self._pos[i]))
        t0_tr = None if self.tracer is None else self.tracer.now()
        if self._jit_spec is None:
            self._jit_spec = self._build_spec_jit()
            self._note_compiled()
        (out_dev, emit_dev, self._last_tok, new_kv, self._dev_pos,
         self._dev_hist, self._dev_hlen) = self._jit_spec(
            self._params, self._last_tok, self.caches["kv"],
            self.caches["tables"], self._dev_pos, self._dev_act,
            self._dev_hist, self._dev_hlen, jnp.asarray(caps))
        self.caches = {"kv": new_kv, "tables": self.caches["tables"]}
        emit = np.asarray(emit_dev)         # the one sync read ([B] int32)
        # proposal counter derives from the already-synced emit vector —
        # never a second device readback per dispatch (a remote runtime
        # charges a full round trip each); the ACCEPTED counter is
        # credited per row below from the post-eos/cap delivered count, so
        # acceptance telemetry tracks delivered-token truth
        self.stats["spec_proposed"] += K * len(live)
        self.stats["spec_steps"] += 1
        t1_tr = None if self.tracer is None else self.tracer.now()
        any_eos = any(r.eos_token_id is not None for _, r in live)
        out = None
        if any_eos:
            # materialize in generation order (drain older pendings first)
            self._drain_pending()
            out = np.asarray(out_dev)
        entries = []
        tok_marks = [] if self.tracer is not None else None
        total = 0
        for i, req in live:
            took = int(emit[i])
            if out is not None:
                used = 0
                for jj in range(took):
                    tok = int(out[i, jj])
                    req.output.append(tok)
                    req._n_out += 1
                    used = jj + 1
                    if (req.eos_token_id is not None
                            and tok == req.eos_token_id):
                        req.done = True
                        break
                took = used
            else:
                entries.append((i, req, took))
                req._n_out += took
            # accepted drafts among DELIVERED tokens (eos/cap truncation
            # included): every delivered token past the first of a
            # dispatch is an accepted draft
            self.stats["spec_accepted"] += max(0, took - 1)
            self._pos[i] += took
            self._sched_tokens += took
            total += took
            if tok_marks is not None:
                tok_marks.append((req.rid, req._n_out))
            if req._n_out >= req.max_new_tokens:
                req.done = True
            if req.done:
                self._mark_done(req)
                self._release_slot(i)
        if self.tracer is not None:
            # tokens-per-step rides the block span: at K>1 a dispatch
            # emits a variable token count, and the SLO inter-token math
            # must see real progress, not dispatch counts
            self.tracer.decode_block_batch(t0_tr, K + 1, len(live),
                                           tok_marks, t1=t1_tr,
                                           tags=self.trace_tags,
                                           tokens=total)
        if entries:
            self._pending.append((out_dev, entries))

    def _reset_quant_blocks(self, blocks):
        """int8 allocation hygiene: zero the page bytes AND the per-block
        absmax scales of freshly-allocated blocks. A recycled page keeps
        its previous occupant's scale, and quantize-on-append grows scales
        monotonically (scatter-max) — without this reset a new request's
        first tokens would quantize under the STALE (possibly much larger)
        scale, so a warm re-admission through recycled pages would emit
        different bytes than its cold run: the warm==cold guarantee would
        silently narrow to never-recycled pools. Eager control-plane
        dispatch (once per admission, never on the decode hot path),
        padded to power-of-two widths with an out-of-range index jax
        drops — compiled programs stay O(log pool)."""
        if self._kv_dtype != "int8" or not len(blocks):
            return
        from ..ops.paged_attention import QuantizedKVPool

        W = 1
        while W < len(blocks):
            W *= 2
        fn = self._jit_qreset.get(W)
        if fn is None:
            def run(kv, idx):
                out = []
                for k, v in kv:
                    out.append((
                        QuantizedKVPool(k.data.at[idx].set(0),
                                        k.scale.at[idx].set(0.0)),
                        QuantizedKVPool(v.data.at[idx].set(0),
                                        v.scale.at[idx].set(0.0))))
                return out

            fn = self._jit_qreset[W] = jax.jit(run)
            self._note_compiled()
        npages = int(self.caches["kv"][0][0].shape[0])
        idx = np.full(W, npages, np.int32)     # pad: out of range, dropped
        idx[:len(blocks)] = blocks
        self.caches = {"kv": fn(self.caches["kv"], jnp.asarray(idx)),
                       "tables": self.caches["tables"]}

    def _spec_seed(self, prompt, extra=()):
        """Drafter seed for a slot activation: the last ``history`` tokens
        of prompt (+ already-delivered tokens on migration), laid out in
        ring order — token with global index g at slot g % H — so the spec
        program's ring arithmetic continues seamlessly. The CURRENT last
        token stays out (it rides the device last-token carry and enters
        the ring on the next spec step)."""
        H = self._spec.history
        toks = np.asarray(prompt, np.int32).reshape(-1)
        if len(extra):
            toks = np.concatenate(
                [toks, np.asarray(extra, np.int32).reshape(-1)])
        hlen = len(toks)
        row = np.zeros(H, np.int32)
        tail = toks[max(0, hlen - H):]
        if len(tail):
            row[np.arange(hlen - len(tail), hlen) % H] = tail
        return row, hlen

    def _cow_copy_batch(self, pairs):
        """All of an admission wave's COW copies in ONE device dispatch
        (the legacy path copies per admission). Padded to a power-of-two
        width with park->park self-copies so the compiled-program set
        stays O(log max_batch); the sources stay pinned (incref'd by
        ``_try_admit_prefix``) until the copy is dispatched — ``evict_lru``
        under a later admission in the same wave must not reclaim them
        first."""
        from ..ops.paged_attention import copy_pages

        W = 1
        while W < len(pairs):
            W *= 2
        fn = self._jit_cow_batch.get(W)
        if fn is None:
            def run(kv, src, dst):
                return [copy_pages(k, v, src, dst) for (k, v) in kv]

            fn = self._jit_cow_batch[W] = jax.jit(run)
            self._note_compiled()
        src = np.full(W, self._park, np.int32)
        dst = np.full(W, self._park, np.int32)
        for j, (s, d) in enumerate(pairs):
            src[j] = s
            dst[j] = d
        self.caches = {"kv": fn(self.caches["kv"], jnp.asarray(src),
                                jnp.asarray(dst)),
                       "tables": self.caches["tables"]}
        self._alloc.decref([s for s, _ in pairs])

    def _pages_needed(self, prompt_len: int, max_new: int) -> int:
        return -(-(prompt_len + max_new) // self.page_size)

    def _note_compiled(self):
        """Bounded-compile-cache telemetry (PT 1's PT-TRACE-001 churn lint,
        in-process): serving programs key on shapes — admission group size,
        prompt bucket, chunk width, sampling mode — so a shape-churning
        workload compiles without bound. Track the entry count and warn
        past ``compile_cache_cap``. (``_jit_step`` counts as one entry; its
        n_steps variants live in jax's own jit cache.)"""
        n = (len(self._jit_prefill) + len(self._jit_qreset)
             + (self._jit_step is not None))
        if self._fused:
            n += (self._jit_mega is not None) + (self._jit_apply is not None)
            if self._spec is not None:
                n += self._jit_spec is not None
        if self.prefix_cache is not None:
            n += (len(self._jit_chunk) + len(self._jit_first)
                  + (self._cow_fn is not None) + len(self._jit_cow_batch))
        self.stats["compile_cache_entries"] = n
        if n > self.compile_cache_cap:
            import warnings

            warnings.warn(
                f"PT-TRACE-001: serving engine holds {n} compiled programs "
                f"(cap {self.compile_cache_cap}) — admission-shape churn is "
                "recompiling per wave; pin prompt_buckets / prefill_chunk "
                "or raise compile_cache_cap", RuntimeWarning, stacklevel=3)

    def _admit(self):
        if self.prefix_cache is not None:
            return self._admit_prefix()
        return self._admit_legacy()

    # -- prefix-cache admission + chunked prefill ---------------------------
    def _admit_prefix(self):
        """Admission with radix prefix matching over the refcounted pool.

        FIFO with head-of-line blocking on pool exhaustion: when the queue
        head cannot get its blocks (even after LRU eviction of idle cached
        blocks) it stays queued and later arrivals wait behind it — the
        queue then fills and ``add_request`` backpressures via
        ``EngineSaturated``; the allocator never overcommits shared blocks
        (tools/fault_drill.py drills exactly this)."""
        from ..distributed.resilience.faults import resource_hold

        if not self._queue:
            return
        cow_wave = [] if self._fused else None
        while self._free_slots and self._queue:
            req = self._queue[0]
            held = resource_hold("serving.block_pool", f"rid:{req.rid}")
            if held:
                self._alloc.hold(held)
            if not self._try_admit_prefix(self._free_slots[0], req, cow_wave):
                # deferral = the pool could not serve the head even after
                # LRU eviction — the brownout pressure signal
                self._deferred_step = True
                break
            self._queue.popleft()
            self._free_slots.popleft()
        if cow_wave:
            self._cow_copy_batch(cow_wave)
        self.stats["evictions"] = self._radix.evictions

    def _try_admit_prefix(self, slot: int, req: "Request",
                          cow_wave=None) -> bool:
        page = self.page_size
        prompt = req.prompt
        n_full = len(prompt) // page
        # brownout: admission stops consulting the radix cache entirely —
        # every block is freshly allocated (still through the refcounted
        # pool), which is exactly the cache-off working-set shape
        matched = (self._radix.match(prompt[: n_full * page])
                   if n_full and not self._brownout_active else [])
        cow_src = None
        if matched and len(matched) * page == len(prompt):
            # FULL-prompt hit: nothing to prefill, but the first-token
            # re-step rewrites position L-1 inside the last shared block —
            # copy-on-write it into a private page first
            cow_src = matched[-1]
            matched = matched[:-1]
        need = self._pages_needed(len(prompt), req.max_new_tokens)
        fresh_n = need - len(matched)          # includes the COW copy
        # Pin the matched chain (and the COW source) BEFORE the
        # eviction-capable alloc: they are refcount-0 CACHED-IDLE until
        # incref'd, so evict_lru under shortfall could reclaim them and
        # alloc would hand the same pages back as `fresh` — double-mapping
        # a block in this slot's table (decode appends into the suffix
        # copy would clobber the shared prefix k/v).
        pinned = matched + ([cow_src] if cow_src is not None else [])
        self._alloc.incref(pinned)
        fresh = self._alloc.alloc(fresh_n, evict=self._radix.evict_lru)
        if fresh is None and self._overcommit:
            fresh = self._steal_blocks(fresh_n, avoid=set(pinned))
        if fresh is None:
            self._alloc.decref(pinned)
            return False                       # pool exhausted — defer
        # int8 block hygiene BEFORE any write (incl. the COW copy below,
        # which overwrites its dst wholesale anyway): recycled pages must
        # not leak their previous occupant's absmax scale into this
        # request's quantization
        self._reset_quant_blocks(fresh)
        cached = len(matched) * page
        if cow_src is not None:
            dst = fresh[0]
            if cow_wave is None:
                self._cow_copy(cow_src, dst)
                self._alloc.decref([cow_src])  # copy done — unpin the source
            else:
                # fused: the whole admission wave's COW copies batch into
                # one program (_cow_copy_batch); the source stays pinned
                # until that dispatch so eviction cannot reclaim it first
                cow_wave.append((cow_src, dst))
            self.stats["cow_copies"] += 1
            blocks = matched + [dst] + fresh[1:]
            cached = len(prompt)
        else:
            blocks = matched + fresh
        row = np.full(self._maxp, self._park, np.int32)
        row[: len(blocks)] = blocks
        self._slot_rows[slot] = row
        self._slot_blocks[slot] = blocks
        self._slots[slot] = req
        self._occupied[slot] = req
        # next uncached write position; == len(prompt) means straight to
        # the first-token re-step. The slot joins the decode batch (and the
        # device-side table) only once prefill completes.
        self._prefill_next[slot] = cached
        self.stats["hit_tokens"] += cached
        self.stats["miss_tokens"] += len(prompt) - cached
        if self.tracer is not None:
            now = _time.monotonic()
            self.tracer.admit(
                req.rid, now - (req._enqueued_at or now),
                hit_tokens=cached, miss_tokens=len(prompt) - cached,
                tags=self._req_tags(req))
        return True

    def _steal_blocks(self, n: int, avoid=()):
        """DRILL-ONLY (``_unsafe_overcommit``): what a refcount-less
        allocator does under exhaustion — rip LRU radix leaves out of the
        cache and hand them to the new request even though live tables
        still map them. The fault drill asserts the resulting shared-block
        corruption; production admission defers instead."""
        legit = list(self._alloc.alloc(min(n, self._alloc.free_blocks)) or [])
        stolen = []
        victims = sorted(self._radix._by_block.values(),
                         key=lambda nd: nd.last_used)
        for nd in victims:
            if len(legit) + len(stolen) >= n:
                break
            if nd.block in avoid or nd.children:
                continue
            nd.parent.children.pop(nd.key, None)
            del self._radix._by_block[nd.block]
            self._alloc._ref[nd.block] = self._alloc._ref.get(nd.block, 0) + 1
            stolen.append(nd.block)
        if len(legit) + len(stolen) < n:
            self._alloc.decref(stolen + legit)
            return None
        # stolen pages first: they become the thief's PROMPT blocks, so its
        # very next prefill overwrites a page the victim still reads
        return stolen + legit

    def _cow_copy(self, src: int, dst: int):
        if self._cow_fn is None:
            from ..ops.paged_attention import copy_pages

            def run(kv, src, dst):
                return [copy_pages(k, v, src, dst) for (k, v) in kv]

            self._cow_fn = jax.jit(run)
            self._note_compiled()
        self.caches = {"kv": self._cow_fn(self.caches["kv"], np.int32(src),
                                          np.int32(dst)),
                       "tables": self.caches["tables"]}

    def _prefill_tick(self):
        """One chunk of prefill per mid-prefill slot, then the first-token
        re-step (+ radix registration) for slots whose prompts are fully
        written. Chunks are batched across slots at per-row offsets; the
        re-step runs through ``paged_token_step`` so warm (cache-hit) and
        cold admissions share one program per shape — the warm==cold
        bit-identity guarantee (see ops.paged_prefill_attention)."""
        if not self._prefill_next:
            return
        t0 = _time.perf_counter()
        try:
            chunkers = [(s, self._slots[s]) for s in sorted(self._prefill_next)
                        if self._prefill_next[s] < len(self._slots[s].prompt)]
            if chunkers and self._fused:
                # prompt-packing prefill (_run_pack): several short prompts
                # — and several chunks of one long prompt — advance in ONE
                # call per step instead of one chunk per slot per step
                self._run_pack(chunkers)
                while self._brownout_active and any(
                        self._prefill_next[s] < len(r.prompt)
                        for s, r in chunkers):
                    self._run_pack([(s, r) for s, r in chunkers
                                    if self._prefill_next[s] < len(r.prompt)])
            elif chunkers:
                self._run_chunk(chunkers)
                while self._brownout_active and any(
                        self._prefill_next[s] < len(r.prompt)
                        for s, r in chunkers):
                    # brownout disables chunked INTERLEAVING: the whole
                    # prompt prefills this tick (legacy admit-stalls-a-step
                    # behavior), trading decode overlap for zero extra
                    # mid-prefill state under pressure. Same compiled chunk
                    # program, run to completion.
                    self._run_chunk([(s, r) for s, r in chunkers
                                     if self._prefill_next[s] < len(r.prompt)])
            ready = [(s, self._slots[s]) for s in sorted(self._prefill_next)
                     if self._prefill_next[s] >= len(self._slots[s].prompt)]
            if ready:
                self._first_token(ready)
        finally:
            self.stats["prefill_host_s"] += _time.perf_counter() - t0

    def _prefill_row(self, s: int, req: "Request"):
        """Table row handed to the prefill-chunk program: the slot's REAL
        prompt pages, with everything beyond them (the decode-headroom
        blocks) parked. A chunk's pad tail (ids right-padded to the chunk
        width) scatters k/v at positions past the prompt — with the full
        row those bytes land in the slot's future decode blocks. Harmless
        under fp (masked, then overwritten) but corrosive under int8: the
        pad garbage feeds the blocks' scatter-max absmax scales, which are
        MONOTONE — a cold admission's decode blocks would quantize under
        pad-inflated scales while a warm full-prompt hit (no prefill, no
        pads) would not, silently breaking warm==cold byte-identity.
        Parking the pad extent keeps decode blocks byte-virgin on every
        admission path. Pads inside the final partially-filled prompt page
        still land there (same bytes on every path: pad k/v depends only
        on the pad token id and its absolute position)."""
        row = np.full(self._maxp, self._park, np.int32)
        n_real = -(-len(req.prompt) // self.page_size)
        row[:n_real] = self._slot_rows[s][:n_real]
        return row

    def _chunk_fn(self, g: int):
        """The compiled prefill-chunk program for ``g`` rows — shared by
        the legacy one-chunk-per-slot path (``_run_chunk``) and the fused
        packed path (``_run_pack``): both dispatch the same
        (params, ids, kv, rows, starts) program, they only lay the rows
        out differently."""
        fn = self._jit_chunk.get(g)
        if fn is None:
            from ..core import autograd_engine
            from ..jit.api import _Swap

            def run(params, ids, kv, rows, starts):
                sub = {"kv": kv, "tables": rows}
                with autograd_engine.no_grad(), _Swap(self._tensors, params):
                    sub = self.model.paged_prefill_chunk(ids, sub, starts)
                return sub["kv"]

            donate = self._CHUNK_DONATE_ARGNUMS if self._donate_carry else ()
            if self._mesh is not None:
                fn = self._mesh_jit(run, self._CHUNK_ARG_NAMES, "kv",
                                    donate, name=f"prefill_chunk@{g}")
            else:
                fn = jax.jit(run, donate_argnums=donate)
            self._jit_chunk[g] = fn
            self._note_compiled()
        return fn

    def _run_chunk(self, group):
        C = self._chunk_tokens
        g = len(group)
        t0_tr = None if self.tracer is None else self.tracer.now()
        ids = np.zeros((g, C), np.int32)
        starts = np.zeros(g, np.int32)
        rows = np.stack([self._prefill_row(s, req) for s, req in group])
        for r, (s, req) in enumerate(group):
            nxt = self._prefill_next[s]
            chunk = req.prompt[nxt: nxt + C]
            ids[r, : len(chunk)] = chunk
            starts[r] = nxt
        fn = self._chunk_fn(g)
        new_kv = fn(self._params, jnp.asarray(ids), self.caches["kv"],
                    jnp.asarray(rows), jnp.asarray(starts))
        self.caches = {"kv": new_kv, "tables": self.caches["tables"]}
        for s, req in group:
            nxt = self._prefill_next[s]
            self._prefill_next[s] = min(nxt + C, len(req.prompt))
            if self.tracer is not None:
                # one span per slot per chunk, host-dispatch window, with
                # the real (unpadded) token count this chunk advanced
                self.tracer.prefill_chunk(
                    req.rid, t0_tr, self._prefill_next[s] - nxt,
                    tags=self.trace_tags)

    def _run_pack(self, group):
        """Prompt-packing prefill (fused mode): flatten (slot, chunk)
        pairs into the rows of ONE ``paged_prefill_chunk`` call — several
        short prompts complete their whole prefill, and a long prompt
        advances several chunks, in a single device program instead of
        one chunk per slot per step.

        Safe by the same absolute-position-masking argument as chunked
        prefill (``ops.paged_prefill_attention``): every row's k/v is
        appended before any row's attention gathers, and a query attends
        exactly the keys at positions <= its own — so a later chunk of
        the same prompt reads the earlier chunk's pages written IN THE
        SAME program, bit-identical to running the chunks sequentially.
        Rows are assigned breadth-first (one chunk per slot per pass), so
        every mid-prefill slot advances at least one chunk per step — the
        legacy interleaving guarantee — and ``PrefixCacheConfig.pack_rows``
        bounds the extra rows. Row counts are bucketed to powers of two
        with parked dummy rows, so admission-width churn at 128+ slots
        compiles O(log max_batch) variants, not one per width."""
        C = self._chunk_tokens
        budget = max(len(group), self._pack_rows)
        offs = {s: self._prefill_next[s] for s, _ in group}
        rows = []
        progress = True
        while len(rows) < budget and progress:
            progress = False
            for s, req in group:
                if len(rows) >= budget:
                    break
                if offs[s] < len(req.prompt):
                    rows.append((s, req, offs[s]))
                    offs[s] = min(offs[s] + C, len(req.prompt))
                    progress = True
        g = 1
        while g < len(rows):
            g *= 2
        t0_tr = None if self.tracer is None else self.tracer.now()
        ids = np.zeros((g, C), np.int32)
        starts = np.zeros(g, np.int32)
        trows = np.full((g, self._maxp), self._park, np.int32)
        for r, (s, req, off) in enumerate(rows):
            chunk = req.prompt[off: off + C]
            ids[r, : len(chunk)] = chunk
            starts[r] = off
            trows[r] = self._prefill_row(s, req)
        fn = self._chunk_fn(g)
        new_kv = fn(self._params, jnp.asarray(ids), self.caches["kv"],
                    jnp.asarray(trows), jnp.asarray(starts))
        self.caches = {"kv": new_kv, "tables": self.caches["tables"]}
        self.stats["packed_rows"] += len(rows)
        for s, req in group:
            nxt = self._prefill_next[s]
            if offs[s] > nxt:
                self._prefill_next[s] = offs[s]
                if self.tracer is not None:
                    self.tracer.prefill_chunk(req.rid, t0_tr, offs[s] - nxt,
                                              tags=self.trace_tags)

    def _first_token(self, ready):
        """Re-step the last REAL prompt token at its true position (k/v
        rewrite into a private/COW block, logits over exactly the real
        prompt) and sample the first token — the chunked-path analogue of
        the legacy bucketed re-step; then register the prompt's full blocks
        in the radix cache and promote the slot into the decode batch
        (fused mode: activation rides the next traced scatter, and group
        widths are bucketed to powers of two — dummy rows re-step the
        parking page at position 0 and scatter to slot index ``max_batch``,
        which jax drops — so admission-wave width churn never retraces)."""
        g = len(ready)
        if self._fused:
            g = 1
            while g < len(ready):
                g *= 2
        do_sample = any(r.temperature > 0.0 for _, r in ready)
        last = np.zeros(g, np.int32)
        rows = np.full((g, self._maxp), self._park, np.int32)
        ints = np.zeros((g, 4), np.int32)
        ints[:, 0] = 1                       # dummy rows re-step position 0
        ints[:, 3] = self.max_batch          # dummy scatter index: dropped
        floats = np.zeros((g, 2), np.float32)
        floats[:, 1] = 1.0
        for r, (s, req) in enumerate(ready):
            last[r] = req.prompt[-1]
            rows[r] = self._slot_rows[s]
            ints[r] = (len(req.prompt), req.seed, req.top_k, s)
            floats[r] = (req.temperature, req.top_p)
        fn = self._jit_first.get((g, do_sample))
        if fn is None:
            from ..core import autograd_engine
            from ..jit.api import _Swap

            def run(params, last, kv, rows, last_tok, ints, floats,
                    _sample=do_sample):
                true_len, seed, top_k, slots_ = (ints[:, 0], ints[:, 1],
                                                 ints[:, 2], ints[:, 3])
                temp, top_p = floats[:, 0], floats[:, 1]
                sub = {"kv": kv, "tables": rows}
                with autograd_engine.no_grad(), _Swap(self._tensors, params):
                    logits, sub = self.model.paged_token_step(
                        last, sub, true_len - 1)
                if _sample:
                    keys = _fold_keys(seed, true_len)
                    nxt = sample_rows(logits, keys, temp, top_p, top_k)
                else:
                    nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                return nxt, sub["kv"], last_tok.at[slots_].set(nxt)

            donate = self._FIRST_DONATE_ARGNUMS if self._donate_carry \
                else ()
            if self._mesh is not None:
                fn = self._mesh_jit(run, self._FIRST_ARG_NAMES,
                                    ("rep", "kv", "rep"), donate,
                                    name=f"first_token@{g}")
            else:
                fn = jax.jit(run, donate_argnums=donate)
            self._jit_first[(g, do_sample)] = fn
            self._note_compiled()
        firsts_dev, new_kv, self._last_tok = fn(
            self._params, jnp.asarray(last), self.caches["kv"],
            jnp.asarray(rows), self._last_tok, jnp.asarray(ints),
            jnp.asarray(floats))
        self.caches = {"kv": new_kv, "tables": self.caches["tables"]}
        self._samp_dev = None   # sampling params change -> re-upload lazily
        any_eos = any(r.eos_token_id is not None for _, r in ready)
        firsts = np.asarray(firsts_dev) if any_eos else None
        entries = []
        ft_marks = [] if self.tracer is not None else None
        for row, (slot, req) in enumerate(ready):
            n_full = len(req.prompt) // self.page_size
            if n_full and not self._brownout_active:
                # register AFTER the full prompt (incl. the re-step rewrite)
                # is scheduled — later admissions are device-ordered behind
                # these writes; first writer wins on duplicate chains.
                # Brownout skips registration: blocks must return to the
                # pool the moment the request finishes, not linger cached.
                self._radix.insert(req.prompt[: n_full * self.page_size],
                                   self._slot_blocks[slot][:n_full])
            del self._prefill_next[slot]
            self._temps[slot] = req.temperature
            self._tops[slot] = req.top_p
            self._topks[slot] = req.top_k
            self._seeds[slot] = req.seed
            req._n_out += 1
            self._sched_tokens += 1
            if ft_marks is not None:
                ft_marks.append((req.rid, req._n_out))
            self._pos[slot] = len(req.prompt) + 1
            if self._fused:
                # activation rides the next traced scatter: table row,
                # position, active flag, sampling params — and on spec
                # engines the drafter ring seeded with the prompt — in one
                # update (no host-table mirror — the device table is
                # authoritative)
                self._queue_update(slot, self._slot_rows[slot],
                                   len(req.prompt) + 1, True, req.seed,
                                   req.temperature, req.top_p, req.top_k,
                                   hist=(self._spec_seed(req.prompt)
                                         if self._spec is not None
                                         else None))
            else:
                self._tables_host[slot] = self._slot_rows[slot]
                self._tables_dirty = True
            if firsts is not None:
                req.output.append(int(firsts[row]))
            else:
                entries.append((row, req, 1))
        if ft_marks:
            # one lock acquisition for the whole admission wave's
            # first-token + token stamps (not one per slot)
            self.tracer.first_tokens(ft_marks, tags=self.trace_tags)
        for row, (slot, req) in enumerate(ready):
            if ((firsts is not None and req.eos_token_id is not None
                 and int(firsts[row]) == req.eos_token_id)
                    or req._n_out >= req.max_new_tokens):
                req.done = True
                self._mark_done(req)
                self._release_slot(slot)
        if entries:
            self._pending.append((firsts_dev, entries))

    def _admit_legacy(self):
        """Admit queued requests into free slots — ONE batched prefill call
        per prompt bucket (per-request prefills pay a full host round trip
        each through a remote runtime; batching amortizes it and runs the
        prompt chunks as one device program)."""
        if not self._queue:
            return
        take = []
        while self._free_slots and self._queue:
            take.append((self._free_slots.popleft(), self._queue.popleft()))
        if not take:
            return
        if self._kv_dtype == "int8":
            # legacy layout: slot i statically owns pages [i*maxp,
            # (i+1)*maxp) — reset the admitted slots' pages so recycled
            # scales never shape the new prompts' quantization
            self._reset_quant_blocks([s * self._maxp + j
                                      for s, _ in take
                                      for j in range(self._maxp)])
        # group by (bucket, padded?): exact-length rows must take the
        # no-restep program — their first token then comes from the SAME
        # prefill-chunk logits generate(cache_impl='paged') computes, keeping
        # the token-exact equality guarantee even at bf16 softmax near-ties
        groups: Dict[tuple, list] = {}
        for slot, req in take:
            b = self._bucket(len(req.prompt))
            groups.setdefault((b, len(req.prompt) != b), []).append(
                (slot, req))
        self._samp_dev = None   # sampling params change -> re-upload lazily
        for (padded, _), grp in groups.items():
            # the prefill program also scatters the group's first tokens into
            # the device-resident last-token carry (no eager device ops here:
            # each eager dispatch costs ~8 ms python-side through the tunnel)
            t0_tr = None if self.tracer is None else self.tracer.now()
            firsts_dev = self._prefill_group(padded, grp)
            if self.tracer is not None:
                self.tracer.span("prefill_group", None, t0_tr,
                                 tags=self.trace_tags,
                                 tokens=padded * len(grp), slots=len(grp))
            any_eos = any(r.eos_token_id is not None for _, r in grp)
            firsts = np.asarray(firsts_dev) if any_eos else None
            entries = []
            ft_marks = [] if self.tracer is not None else None
            for row, (slot, req) in enumerate(grp):
                self._temps[slot] = req.temperature
                self._tops[slot] = req.top_p
                self._topks[slot] = req.top_k
                self._seeds[slot] = req.seed
                self._slots[slot] = req
                self._occupied[slot] = req
                req._n_out += 1
                self._sched_tokens += 1
                if self.tracer is not None:
                    now = _time.monotonic()
                    self.tracer.admit(req.rid,
                                      now - (req._enqueued_at or now),
                                      miss_tokens=len(req.prompt),
                                      tags=self._req_tags(req))
                    ft_marks.append((req.rid, req._n_out))
                self._pos[slot] = len(req.prompt) + 1
                if self._fused:
                    # static slot-owned tables in legacy layout: activation
                    # only flips act/pos/sampling (+ the spec drafter seed)
                    # via the traced scatter
                    self._queue_update(slot, None, len(req.prompt) + 1, True,
                                       req.seed, req.temperature, req.top_p,
                                       req.top_k,
                                       hist=(self._spec_seed(req.prompt)
                                             if self._spec is not None
                                             else None))
                if firsts is not None:
                    req.output.append(int(firsts[row]))
                else:
                    entries.append((row, req, 1))
            if ft_marks:
                # one lock acquisition for the group's first-token stamps
                self.tracer.first_tokens(ft_marks, tags=self.trace_tags)
            for row, (slot, req) in enumerate(grp):
                if ((firsts is not None and req.eos_token_id is not None
                     and int(firsts[row]) == req.eos_token_id)
                        or req._n_out >= req.max_new_tokens):
                    req.done = True
                    self._mark_done(req)
                    self._release_slot(slot)
            if entries:
                self._pending.append((firsts_dev, entries))

    def _bucket(self, n: int) -> int:
        if not self.prompt_buckets:
            return n
        for b in self.prompt_buckets:
            if b >= n:
                return b
        return n  # unreachable: add_request validates against the last bucket

    def _prefill_group(self, padded: int, grp):
        """Prefill a GROUP of slots sharing one padded prompt length; returns
        the first sampled token per slot.

        Compiles once per (PADDED length, restep, sampling, group size) — with
        ``prompt_buckets`` that is once per bucket per admission width; the
        re-step of the last real token keeps bucketed numerics exact (see
        module docstring). ``_admit`` groups exact-length rows separately so
        they take the no-restep program (same prefill-chunk logits as
        ``generate(cache_impl='paged')``, token-exact even at bf16 ties)."""
        slots = [s for s, _ in grp]
        reqs = [r for _, r in grp]
        restep = any(len(r.prompt) != padded for r in reqs)
        ids = np.stack([
            np.concatenate([r.prompt,
                            np.zeros(padded - len(r.prompt), np.int32)])
            for r in reqs])
        do_sample = any(r.temperature > 0.0 for r in reqs)
        fn = self._jit_prefill.get((padded, restep, do_sample))
        if fn is None:
            from ..core import autograd_engine
            from ..jit.api import _Swap

            def run(params, ids, kv, all_tables, last_tok, ints, floats,
                    _restep=restep, _sample=do_sample):
                # ints [g, 4]: true_len, seed, top_k, slot; floats [g, 2]:
                # temperature, top_p — packed so an admission moves THREE
                # host->device buffers total (ids/ints/floats); the table
                # gather and last-token scatter run inside this program
                true_len, seed, top_k, slots_ = (ints[:, 0], ints[:, 1],
                                                 ints[:, 2], ints[:, 3])
                temp, top_p = floats[:, 0], floats[:, 1]
                sub = {"kv": kv, "tables": all_tables[slots_]}
                with autograd_engine.no_grad(), _Swap(self._tensors, params):
                    logits, sub = self.model._decode_chunk(
                        ids, sub, 0, None, None)
                    if _restep:
                        # re-step the last REAL token at its true position:
                        # identical k/v rewrite, logits over the real prompt
                        # only (pad columns beyond true_len not yet attended)
                        last = jnp.take_along_axis(
                            ids, true_len[:, None] - 1, axis=1)[:, 0]
                        logits, sub = self.model.paged_token_step(
                            last, sub, true_len - 1)
                if _sample:
                    # sample_rows takes temp<=0 rows to argmax — mixed
                    # greedy/sampling groups stay exact for the greedy rows
                    keys = _fold_keys(seed, true_len)
                    nxt = sample_rows(logits, keys, temp, top_p, top_k)
                else:
                    nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                return nxt, sub["kv"], last_tok.at[slots_].set(nxt)

            fn = self._jit_prefill[(padded, restep, do_sample)] = jax.jit(run)
            self._note_compiled()
        ints = np.asarray([[len(r.prompt), r.seed, r.top_k, s]
                           for s, r in grp], np.int32)
        floats = np.asarray([[r.temperature, r.top_p] for _, r in grp],
                            np.float32)
        firsts, new_kv, self._last_tok = fn(
            self._params, jnp.asarray(ids), self.caches["kv"],
            self.caches["tables"], self._last_tok,
            jnp.asarray(ints), jnp.asarray(floats))
        self.caches = {"kv": new_kv, "tables": self.caches["tables"]}
        return firsts                      # device array — materialized lazily
