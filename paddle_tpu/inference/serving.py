"""Continuous-batching serving engine over paged KV caches.

The TPU-native counterpart of the reference's serving stack around
block_multihead_attention (python/paddle/incubate/nn/functional/
block_multihead_attention.py over block_multi_head_attention_kernel.cu):
a fixed pool of KV pages + per-slot block tables, requests admitted into
free slots as others finish — decode compute and cache memory are bounded
by the pool, not by the longest request.

Design (one jitted program per phase, static shapes):
  - ``max_batch`` slots share per-layer page pools sized
    ``max_batch * ceil(max_len / page)`` pages (``_init_paged_caches``).
  - ADMIT: a new request prefills ITS slot only (an s>1 paged_decode_step
    chunk at exact prompt length; lengths compile once each — pad prompts
    client-side to a few buckets to bound compilations).
  - STEP: ONE fused ``paged_token_step`` advances EVERY active slot — each
    slot at its own position (per-row positions/context lengths flow into
    the paged decode kernel). Inactive slots run on a parked dummy row whose
    output is ignored.
  - FINISH: eos or max_new_tokens frees the slot; its pages are reused by
    the next admission (tables are per-slot, so no copying).

Greedy decoding (the serving default). Models plug in via the GenerationMixin
paged hooks: ``_init_paged_caches`` + ``paged_token_step`` + ``_decode_chunk``
(llama and GPT implement all three).

Numerics: the engine is EXACTLY equal to ``generate(cache_impl='paged')``
(verified token-for-token on the real chip, 32/32); versus the dense-cache
generate it matches exactly in fp32 (CPU tests) while bf16-on-TPU tokens may
diverge at softmax near-ties between the two attention kernels — the standard
cross-kernel serving caveat.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


class Request:
    """One generation request tracked by the engine."""

    _counter = [0]

    def __init__(self, prompt_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None):
        Request._counter[0] += 1
        self.rid = Request._counter[0]
        self.prompt = np.asarray(
            prompt_ids._data if isinstance(prompt_ids, Tensor) else prompt_ids
        ).reshape(-1).astype(np.int32)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.output: List[int] = []
        self.done = False


class ContinuousBatchingEngine:
    def __init__(self, model, max_batch: int = 8, max_len: int = 512,
                 page_size: int = 64):
        self.model = model
        self.max_batch = max_batch
        self.max_len = max_len
        self.page_size = page_size
        self.caches = model._init_paged_caches(max_batch, max_len, page_size)
        self._slots: List[Optional[Request]] = [None] * max_batch
        # per-slot NEXT write position (== tokens currently in the slot's cache)
        self._pos = np.zeros(max_batch, np.int32)
        self._last_tok = np.zeros(max_batch, np.int32)
        self._queue: collections.deque = collections.deque()
        self._finished: Dict[int, Request] = {}

        from ..jit.api import _collect_state

        _, tensors = _collect_state(model)
        self._params = [t._data for t in tensors]
        self._tensors = tensors
        self._jit_prefill: Dict[int, object] = {}
        self._jit_step = None

    # ---- public API ----
    def add_request(self, req: Request) -> int:
        if len(req.prompt) + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {len(req.prompt)} + max_new {req.max_new_tokens} "
                f"exceeds engine max_len {self.max_len}")
        # family-specific length limits (e.g. GPT's learned position table) —
        # the same validation generate() applies
        validate = getattr(self.model, "_validate_generate", None)
        if validate is not None:
            validate(len(req.prompt), len(req.prompt) + req.max_new_tokens)
        self._queue.append(req)
        return req.rid

    def has_work(self) -> bool:
        return bool(self._queue) or any(s is not None for s in self._slots)

    def step(self):
        """Admit whatever fits, then advance every active slot one token."""
        self._admit()
        if not any(s is not None for s in self._slots):
            return
        active = np.array([s is not None for s in self._slots])
        # parked rows decode at position 0 over slot-local pages — harmless
        pos_vec = jnp.asarray(np.where(active, self._pos, 1) - 1)
        toks = jnp.asarray(self._last_tok)
        if self._jit_step is None:
            from ..core import autograd_engine
            from ..jit.api import _Swap

            def run(params, toks, caches, pos_vec):
                with autograd_engine.no_grad(), _Swap(self._tensors, params):
                    logits, caches = self.model.paged_token_step(
                        toks, caches, pos_vec)
                return jnp.argmax(logits, -1).astype(jnp.int32), caches

            self._jit_step = jax.jit(run)
        nxt, self.caches = self._jit_step(self._params, toks, self.caches,
                                          pos_vec)
        nxt = np.asarray(nxt)
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            tok = int(nxt[i])
            req.output.append(tok)
            self._last_tok[i] = tok
            self._pos[i] += 1
            if ((req.eos_token_id is not None and tok == req.eos_token_id)
                    or len(req.output) >= req.max_new_tokens):
                req.done = True
                self._finished[req.rid] = req
                self._slots[i] = None       # slot + its pages are free again
                self._pos[i] = 0

    def run_until_done(self, max_steps: int = 100000):
        steps = 0
        while self.has_work() and steps < max_steps:
            self.step()
            steps += 1
        return self.finished()

    def finished(self) -> Dict[int, Request]:
        out, self._finished = self._finished, {}
        return out

    # ---- internals ----
    def _admit(self):
        for i in range(self.max_batch):
            if self._slots[i] is not None or not self._queue:
                continue
            req = self._queue.popleft()
            first = self._prefill(i, req)
            self._slots[i] = req
            req.output.append(first)
            self._last_tok[i] = first
            self._pos[i] = len(req.prompt) + 1
            if ((req.eos_token_id is not None and first == req.eos_token_id)
                    or len(req.output) >= req.max_new_tokens):
                req.done = True
                self._finished[req.rid] = req
                self._slots[i] = None
                self._pos[i] = 0

    def _prefill(self, slot: int, req: Request) -> int:
        """Prefill ONE slot's pages with the prompt; returns the first token.

        Compiles once per (slot-independent) prompt length — pad prompts to a
        few fixed buckets client-side to bound compilations."""
        n = len(req.prompt)
        fn = self._jit_prefill.get(n)
        if fn is None:
            from ..core import autograd_engine
            from ..jit.api import _Swap

            def run(params, ids, kv, tables):
                sub = {"kv": kv, "tables": tables}
                with autograd_engine.no_grad(), _Swap(self._tensors, params):
                    logits, sub = self.model._decode_chunk(
                        ids, sub, 0, None, None)
                return jnp.argmax(logits, -1).astype(jnp.int32), sub["kv"]

            fn = self._jit_prefill[n] = jax.jit(run)
        tables = self.caches["tables"][slot:slot + 1]
        kv = self.caches["kv"]
        first, new_kv = fn(self._params, jnp.asarray(req.prompt)[None], kv,
                           tables)
        self.caches = {"kv": new_kv, "tables": self.caches["tables"]}
        return int(first[0])
