"""Continuous-batching serving engine over paged KV caches.

The TPU-native counterpart of the reference's serving stack around
block_multihead_attention (python/paddle/incubate/nn/functional/
block_multihead_attention.py over block_multi_head_attention_kernel.cu)
plus its sampling op (python/paddle/tensor/search.py:1362 top_p_sampling):
a fixed pool of KV pages + per-slot block tables, requests admitted into
free slots as others finish — decode compute and cache memory are bounded
by the pool, not by the longest request.

Design (one jitted program per phase, static shapes):
  - ``max_batch`` slots share per-layer page pools sized
    ``max_batch * ceil(max_len / page)`` pages (``_init_paged_caches``).
  - ADMIT: a new request prefills ITS slot only. With ``prompt_buckets`` the
    prompt is right-padded to the nearest bucket (one compilation per bucket):
    the padded chunk fills the cache, then the last REAL token is re-stepped
    at its true position so the first sampled token sees exactly the real
    prompt — pad cache entries sit beyond the attended window and are
    overwritten as decode advances.
  - STEP: ONE fused ``lax.scan`` of ``paged_token_step`` advances EVERY
    active slot — per-row positions flow into the paged decode kernel;
    inactive slots run on a parked dummy row whose output is ignored.
    Without eos the schedule is deterministic, so the engine runs toward the
    next completion event per program (scan lengths block_size·2^k), chains
    the last-token carry device-to-device, and materializes token values
    LAZILY (``_drain_pending``) — zero synchronous host round-trips, like
    ``generate()``'s async dispatch. eos-carrying batches pace at
    ``block_size`` tokens per host sync (early exit needs the values).
  - SAMPLE: per-request temperature / top-p / top-k / seed, applied
    row-vectorized inside the fused step. Keys are stateless:
    ``fold_in(key(seed), token_position)`` — reproducible per request and
    independent of batching/arrival order. temperature==0 is greedy.
  - FINISH: eos or max_new_tokens frees the slot; its pages are reused by
    the next admission (tables are per-slot, so no copying). Tokens decoded
    past an eos inside a block are discarded on the host (bounded waste,
    the standard continuous-batching speculation tradeoff).

Numerics: with default greedy sampling the engine is EXACTLY equal to
``generate(cache_impl='paged')`` (verified token-for-token on the real chip);
versus the dense-cache generate it matches exactly in fp32 (CPU tests) while
bf16-on-TPU tokens may diverge at softmax near-ties between the two attention
kernels — the standard cross-kernel serving caveat.
"""

from __future__ import annotations

import collections
import weakref
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


# THE sampler lives in generation_utils so generate() and the engine share one
# implementation; re-exported here for the serving-facing API surface.
from ..models.generation_utils import (fold_keys as _fold_keys,
                                       sample_rows, validate_sampling)


class EngineSaturated(RuntimeError):
    """add_request refused: the engine's wait queue is at its high-water
    mark (``max_queue``). Admission control — callers shed load, retry with
    backoff, or scale out; the engine never hides an unbounded backlog."""


class Request:
    """One generation request tracked by the engine.

    Sampling params mirror ``generate()``: ``temperature=0`` (default) is
    greedy; otherwise temperature + optional top-p (nucleus) + top-k filter.
    ``seed`` (default: the request id) makes the request's sample stream
    reproducible regardless of batching or arrival order.

    ``deadline_s`` (measured from enqueue) bounds the request's total life
    — queue wait plus decode. A request past its deadline is evicted at the
    next engine step: ``done=True, failed=True``, ``error`` names the
    deadline, its slot/pages are freed, and other slots are untouched.
    Eviction latency is bounded by one decode block.
    """

    _counter = [0]

    def __init__(self, prompt_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0, top_p: float = 1.0,
                 top_k: int = 0, seed: Optional[int] = None,
                 deadline_s: Optional[float] = None):
        validate_sampling(temperature, top_p, top_k)
        Request._counter[0] += 1
        self.rid = Request._counter[0]
        self.prompt = np.asarray(
            prompt_ids._data if isinstance(prompt_ids, Tensor) else prompt_ids
        ).reshape(-1).astype(np.int32)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.top_k = int(top_k)
        self.seed = int(seed if seed is not None else self.rid)
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.output: List[int] = []
        self.done = False
        self.failed = False
        self.error: Optional[str] = None
        self._enqueued_at: Optional[float] = None  # set by add_request
        # tokens SCHEDULED so far (device-side results may still be pending
        # materialization — without eos the schedule is deterministic, so the
        # engine books progress before reading any token value)
        self._n_out = 0
        self._engine = None  # weakref, set by add_request

    @property
    def tokens(self) -> List[int]:
        """Materialized output tokens. Under async (deterministic-schedule)
        batching, ``done`` can flip True while token blocks are still
        device-side; this accessor drains the engine's pending readbacks
        first, so it is always complete once ``done`` is True. Reading
        ``.output`` directly is only guaranteed complete after the engine's
        ``finished()`` has returned the request."""
        eng = self._engine() if self._engine is not None else None
        if eng is not None:
            eng._drain_pending()
        elif len(self.output) < self._n_out:
            raise RuntimeError(
                f"request {self.rid}: {self._n_out - len(self.output)} "
                "scheduled tokens were never materialized and the engine has "
                "been garbage-collected — keep the engine alive (or call its "
                "finished()) before dropping it")
        return self.output


class ContinuousBatchingEngine:
    def __init__(self, model, max_batch: int = 8, max_len: int = 512,
                 page_size: int = 64, block_size: int = 8,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 max_queue: Optional[int] = None):
        self.model = model
        self.max_batch = max_batch
        self.max_len = max_len
        self.page_size = page_size
        self.block_size = max(1, int(block_size))
        # bounded-queue backpressure: add_request raises EngineSaturated
        # past this many waiting requests (None = unbounded, legacy)
        self.max_queue = None if max_queue is None else max(0, int(max_queue))
        self.prompt_buckets = (sorted(int(b) for b in prompt_buckets)
                               if prompt_buckets else None)
        if self.prompt_buckets and self.prompt_buckets[-1] > max_len:
            raise ValueError(f"prompt bucket {self.prompt_buckets[-1]} "
                             f"exceeds max_len {max_len}")
        self.caches = model._init_paged_caches(max_batch, max_len, page_size)
        self._slots: List[Optional[Request]] = [None] * max_batch
        # per-slot NEXT write position (== tokens currently in the slot's cache)
        self._pos = np.zeros(max_batch, np.int32)
        # last emitted token per slot, DEVICE-resident: the decode chain never
        # round-trips token values through the host (they're materialized
        # lazily from self._pending — see _drain_pending)
        self._last_tok = jnp.zeros(max_batch, jnp.int32)
        self._pending: List[tuple] = []
        self._temps = np.zeros(max_batch, np.float32)
        self._tops = np.ones(max_batch, np.float32)
        self._topks = np.zeros(max_batch, np.int32)
        self._seeds = np.zeros(max_batch, np.int32)
        # device copies of the sampling params, re-uploaded only when an
        # admission changes them (every host->device put costs a dispatch
        # through a remote runtime)
        self._samp_dev = None
        self._queue: collections.deque = collections.deque()
        self._finished: Dict[int, Request] = {}
        # host-side accounting: admission vs decode dispatch time (the
        # admission-stall share is stats["admit_host_s"] / wall)
        self.stats = {"admit_host_s": 0.0, "decode_host_s": 0.0}

        from ..jit.api import _collect_state

        _, tensors = _collect_state(model)
        self._params = [t._data for t in tensors]
        self._tensors = tensors
        self._jit_prefill: Dict[int, object] = {}
        self._jit_step = None

    # ---- public API ----
    def add_request(self, req: Request) -> int:
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            raise EngineSaturated(
                f"engine queue at high-water mark ({self.max_queue} waiting, "
                f"{sum(s is not None for s in self._slots)}/{self.max_batch} "
                "slots busy) — shed load or scale out")
        if len(req.prompt) + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {len(req.prompt)} + max_new {req.max_new_tokens} "
                f"exceeds engine max_len {self.max_len}")
        if self.prompt_buckets and len(req.prompt) > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt {len(req.prompt)} exceeds largest prompt bucket "
                f"{self.prompt_buckets[-1]}")
        # family-specific length limits (e.g. GPT's learned position table) —
        # the same validation generate() applies
        validate = getattr(self.model, "_validate_generate", None)
        if validate is not None:
            validate(len(req.prompt), len(req.prompt) + req.max_new_tokens)
        req._engine = weakref.ref(self)
        import time as _time

        req._enqueued_at = _time.monotonic()
        self._queue.append(req)
        return req.rid

    def has_work(self) -> bool:
        return bool(self._queue) or any(s is not None for s in self._slots)

    def step(self):
        """Advance active slots in ONE device program, then admit new
        requests while that program is in flight.

        Decode-first ordering (round 5, VERDICT "admission serializes with
        decode"): the decode scan for already-active slots is DISPATCHED
        before admission touches the host, so admission's prompt packing,
        prefill compile-cache lookups, and (on the eos path) its synchronous
        first-token materialization all overlap the in-flight decode block
        instead of stalling it. Newly admitted slots join the next block —
        on a single chip both programs execute serially anyway, so the
        schedule shift costs nothing while removing every host-side
        admission stall from the decode critical path. When all slots are
        idle, admission runs first so the wave starts without a wasted step.

        Without eos the whole schedule is DETERMINISTIC (a slot frees exactly
        when its request's max_new_tokens are scheduled), so no host decision
        ever needs a token VALUE: the engine runs to the next completion
        event per program, chains the last-token carry device-to-device, and
        defers all token materialization to ``_drain_pending`` — zero
        synchronous host round-trips in the decode path, exactly like
        ``generate()``'s async dispatch. eos-carrying batches pace at
        ``block_size`` and materialize each block (early exit needs the
        values). Host-side time is accounted in ``self.stats``
        (admit_host_s / decode_host_s) so the admission share is measurable
        at any workload."""
        import time as _time

        self._evict_expired()
        if not any(s is not None for s in self._slots):
            t0 = _time.perf_counter()
            self._admit()
            self.stats["admit_host_s"] += _time.perf_counter() - t0
            self._decode_block()
            return
        self._decode_block()
        t0 = _time.perf_counter()
        self._admit()
        self.stats["admit_host_s"] += _time.perf_counter() - t0

    def _evict_expired(self):
        """Deadline enforcement: fail-and-free requests past ``deadline_s``
        (active slots AND still-queued requests) so a straggler can neither
        hog a slot forever nor hang its caller. Tokens already scheduled for
        an evicted slot stay in the pending readbacks — ``tokens`` remains
        complete up to the eviction point."""
        import time as _time

        now = _time.monotonic()

        def expired(r):
            return (r.deadline_s is not None and r._enqueued_at is not None
                    and now - r._enqueued_at > r.deadline_s)

        def fail(r):
            r.done = True
            r.failed = True
            r.error = (f"deadline exceeded: {now - r._enqueued_at:.3f}s > "
                       f"{r.deadline_s:.3f}s ({r._n_out} tokens scheduled)")
            self._finished[r.rid] = r

        for i, req in enumerate(self._slots):
            if req is not None and expired(req):
                fail(req)
                self._slots[i] = None   # slot + its pages are free again
                self._pos[i] = 0
                self._temps[i] = 0.0
        if any(expired(r) for r in self._queue):
            keep = collections.deque()
            for r in self._queue:
                if expired(r):
                    fail(r)
                else:
                    keep.append(r)
            self._queue = keep

    def _decode_block(self):
        import time as _time

        t0 = _time.perf_counter()
        try:
            self._decode_block_inner()
        finally:
            self.stats["decode_host_s"] += _time.perf_counter() - t0

    def _decode_block_inner(self):
        live = [(i, r) for i, r in enumerate(self._slots) if r is not None]
        if not live:
            return
        active = np.array([s is not None for s in self._slots])
        # block length: never decode past a request's max_new_tokens or the
        # engine max_len (pages beyond the table would clamp-corrupt)
        cap = min(min(r.max_new_tokens - r._n_out for _, r in live),
                  min(self.max_len - int(self._pos[i]) for i, _ in live))
        n = min(self.block_size, cap)
        async_ok = all(r.eos_token_id is None for _, r in live)
        if async_ok:
            # run toward the next completion event; allowed scan lengths are
            # block_size * 2^k so the compiled-program set stays O(log) in
            # max_len (each distinct n compiles a full-model scan)
            stretch = self.block_size
            while stretch * 2 <= cap:
                stretch *= 2
            n = max(n, cap if cap <= self.block_size else stretch)
        n = max(1, n)
        # parked rows decode at position 0 over slot-local pages — harmless
        pos_vec = jnp.asarray(np.where(active, self._pos, 1) - 1)
        toks = self._last_tok
        if self._jit_step is None:
            from ..core import autograd_engine
            from ..jit.api import _Swap

            def run(params, toks, caches, pos_vec, seeds, temps, tops, topks,
                    n_steps, do_sample):
                def body(carry, _):
                    tok, cs, pos = carry
                    with autograd_engine.no_grad(), _Swap(self._tensors,
                                                          params):
                        logits, cs = self.model.paged_token_step(tok, cs, pos)
                    if do_sample:
                        keys = _fold_keys(seeds, pos + 1)
                        nxt = sample_rows(logits, keys, temps, tops, topks)
                    else:
                        # all-greedy batches skip the sampler: its vocab-wide
                        # argsort costs ~10 ms/token at 32k vocab (measured
                        # 150x engine slowdown before this gate)
                        nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                    return (nxt, cs, pos + 1), nxt

                (tok, cs, _), out = jax.lax.scan(
                    body, (toks, caches, pos_vec), None, length=n_steps)
                return jnp.swapaxes(out, 0, 1), tok, cs

            self._jit_step = jax.jit(run,
                                     static_argnames=("n_steps", "do_sample"))
        do_sample = bool(any(self._temps[i] > 0.0 for i, _ in live))
        if self._samp_dev is None:
            self._samp_dev = (jnp.asarray(self._seeds),
                              jnp.asarray(self._temps),
                              jnp.asarray(self._tops),
                              jnp.asarray(self._topks))
        seeds_d, temps_d, tops_d, topks_d = self._samp_dev
        out, self._last_tok, self.caches = self._jit_step(
            self._params, toks, self.caches, pos_vec,
            seeds_d, temps_d, tops_d, topks_d, n_steps=n,
            do_sample=do_sample)
        if async_ok:
            entries = []
            for i, req in live:
                took = min(n, req.max_new_tokens - req._n_out)
                entries.append((i, req, took))
                req._n_out += took
                self._pos[i] += took
                if req._n_out >= req.max_new_tokens:
                    req.done = True
                    self._finished[req.rid] = req
                    self._slots[i] = None   # slot + its pages are free again
                    self._pos[i] = 0
                    self._temps[i] = 0.0
            self._pending.append((out, entries))
            return
        # eos path: materialize (in generation order — drain older pendings
        # first so req.output stays ordered across an async->sync transition)
        self._drain_pending()
        out = np.asarray(out)
        for i, req in live:
            took = 0
            for j in range(n):
                tok = int(out[i, j])
                req.output.append(tok)
                req._n_out += 1
                took = j + 1
                if ((req.eos_token_id is not None and tok == req.eos_token_id)
                        or req._n_out >= req.max_new_tokens):
                    req.done = True
                    break
            self._pos[i] += took
            if req.done:
                self._finished[req.rid] = req
                self._slots[i] = None       # slot + its pages are free again
                self._pos[i] = 0
                self._temps[i] = 0.0

    def run_until_done(self, max_steps: int = 100000):
        steps = 0
        while self.has_work() and steps < max_steps:
            self.step()
            steps += 1
        return self.finished()

    def finished(self) -> Dict[int, Request]:
        self._drain_pending()
        out, self._finished = self._finished, {}
        return out

    def _drain_pending(self):
        """Materialize deferred token blocks into request outputs.

        All host copies are STARTED asynchronously first — a remote runtime
        charges a full round trip per synchronous readback (measured ~130 ms
        through the axon tunnel), so serial np.asarray calls would dominate
        the whole decode wave."""
        for arr_dev, _ in self._pending:
            try:
                arr_dev.copy_to_host_async()
            except AttributeError:
                pass
        for arr_dev, entries in self._pending:
            arr = np.asarray(arr_dev)
            for row, req, took in entries:
                if arr.ndim == 1:           # prefill firsts [g]
                    req.output.append(int(arr[row]))
                else:                       # decode block [slots, n]
                    req.output.extend(int(t) for t in arr[row, :took])
        self._pending.clear()

    # ---- internals ----
    def _admit(self):
        """Admit queued requests into free slots — ONE batched prefill call
        per prompt bucket (per-request prefills pay a full host round trip
        each through a remote runtime; batching amortizes it and runs the
        prompt chunks as one device program)."""
        free = [i for i in range(self.max_batch) if self._slots[i] is None]
        take = []
        while free and self._queue:
            take.append((free.pop(0), self._queue.popleft()))
        if not take:
            return
        # group by (bucket, padded?): exact-length rows must take the
        # no-restep program — their first token then comes from the SAME
        # prefill-chunk logits generate(cache_impl='paged') computes, keeping
        # the token-exact equality guarantee even at bf16 softmax near-ties
        groups: Dict[tuple, list] = {}
        for slot, req in take:
            b = self._bucket(len(req.prompt))
            groups.setdefault((b, len(req.prompt) != b), []).append(
                (slot, req))
        self._samp_dev = None   # sampling params change -> re-upload lazily
        for (padded, _), grp in groups.items():
            # the prefill program also scatters the group's first tokens into
            # the device-resident last-token carry (no eager device ops here:
            # each eager dispatch costs ~8 ms python-side through the tunnel)
            firsts_dev = self._prefill_group(padded, grp)
            any_eos = any(r.eos_token_id is not None for _, r in grp)
            firsts = np.asarray(firsts_dev) if any_eos else None
            entries = []
            for row, (slot, req) in enumerate(grp):
                self._temps[slot] = req.temperature
                self._tops[slot] = req.top_p
                self._topks[slot] = req.top_k
                self._seeds[slot] = req.seed
                self._slots[slot] = req
                req._n_out += 1
                self._pos[slot] = len(req.prompt) + 1
                if firsts is not None:
                    req.output.append(int(firsts[row]))
                else:
                    entries.append((row, req, 1))
                if ((firsts is not None and req.eos_token_id is not None
                     and int(firsts[row]) == req.eos_token_id)
                        or req._n_out >= req.max_new_tokens):
                    req.done = True
                    self._finished[req.rid] = req
                    self._slots[slot] = None
                    self._pos[slot] = 0
                    self._temps[slot] = 0.0
            if entries:
                self._pending.append((firsts_dev, entries))

    def _bucket(self, n: int) -> int:
        if not self.prompt_buckets:
            return n
        for b in self.prompt_buckets:
            if b >= n:
                return b
        return n  # unreachable: add_request validates against the last bucket

    def _prefill_group(self, padded: int, grp):
        """Prefill a GROUP of slots sharing one padded prompt length; returns
        the first sampled token per slot.

        Compiles once per (PADDED length, restep, sampling, group size) — with
        ``prompt_buckets`` that is once per bucket per admission width; the
        re-step of the last real token keeps bucketed numerics exact (see
        module docstring). ``_admit`` groups exact-length rows separately so
        they take the no-restep program (same prefill-chunk logits as
        ``generate(cache_impl='paged')``, token-exact even at bf16 ties)."""
        slots = [s for s, _ in grp]
        reqs = [r for _, r in grp]
        restep = any(len(r.prompt) != padded for r in reqs)
        ids = np.stack([
            np.concatenate([r.prompt,
                            np.zeros(padded - len(r.prompt), np.int32)])
            for r in reqs])
        do_sample = any(r.temperature > 0.0 for r in reqs)
        fn = self._jit_prefill.get((padded, restep, do_sample))
        if fn is None:
            from ..core import autograd_engine
            from ..jit.api import _Swap

            def run(params, ids, kv, all_tables, last_tok, ints, floats,
                    _restep=restep, _sample=do_sample):
                # ints [g, 4]: true_len, seed, top_k, slot; floats [g, 2]:
                # temperature, top_p — packed so an admission moves THREE
                # host->device buffers total (ids/ints/floats); the table
                # gather and last-token scatter run inside this program
                true_len, seed, top_k, slots_ = (ints[:, 0], ints[:, 1],
                                                 ints[:, 2], ints[:, 3])
                temp, top_p = floats[:, 0], floats[:, 1]
                sub = {"kv": kv, "tables": all_tables[slots_]}
                with autograd_engine.no_grad(), _Swap(self._tensors, params):
                    logits, sub = self.model._decode_chunk(
                        ids, sub, 0, None, None)
                    if _restep:
                        # re-step the last REAL token at its true position:
                        # identical k/v rewrite, logits over the real prompt
                        # only (pad columns beyond true_len not yet attended)
                        last = jnp.take_along_axis(
                            ids, true_len[:, None] - 1, axis=1)[:, 0]
                        logits, sub = self.model.paged_token_step(
                            last, sub, true_len - 1)
                if _sample:
                    # sample_rows takes temp<=0 rows to argmax — mixed
                    # greedy/sampling groups stay exact for the greedy rows
                    keys = _fold_keys(seed, true_len)
                    nxt = sample_rows(logits, keys, temp, top_p, top_k)
                else:
                    nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                return nxt, sub["kv"], last_tok.at[slots_].set(nxt)

            fn = self._jit_prefill[(padded, restep, do_sample)] = jax.jit(run)
        ints = np.asarray([[len(r.prompt), r.seed, r.top_k, s]
                           for s, r in grp], np.int32)
        floats = np.asarray([[r.temperature, r.top_p] for _, r in grp],
                            np.float32)
        firsts, new_kv, self._last_tok = fn(
            self._params, jnp.asarray(ids), self.caches["kv"],
            self.caches["tables"], self._last_tok,
            jnp.asarray(ints), jnp.asarray(floats))
        self.caches = {"kv": new_kv, "tables": self.caches["tables"]}
        return firsts                      # device array — materialized lazily
