"""paddle_tpu.inference.procfleet — process-per-replica serving transport.

The fleet/tiered routers and the SLO autoscaler (docs/SERVING.md,
ROADMAP item 1) gain REAL replica isolation: each replica is a spawned
worker process owning its own engine, device memory and journal, driven
over a crc-framed localhost wire protocol. Replica death is process death
(a SIGKILL'd worker's unfinished work re-admits on survivors
byte-identically from its on-disk journal — the ``fleet_proc_kill``
drill), and scale-out is measurable (``bench_fleet --processes`` →
``fleet_proc_tokens_per_sec``).

Modules:

- :mod:`~paddle_tpu.inference.procfleet.wire` — the PT-PROC framed
  message protocol (:class:`WireCorrupt` = PT-PROC-001).
- :mod:`~paddle_tpu.inference.procfleet.worker` — the spawned replica
  process (:class:`WorkerSpec`, ``worker_main``).
- :mod:`~paddle_tpu.inference.procfleet.proxy` — the driver-side replica
  proxy (:class:`ProcReplica`, :class:`WorkerDead` = PT-PROC-002/003).
- :mod:`~paddle_tpu.inference.procfleet.router` —
  :class:`ProcFleetRouter` / :class:`ProcTieredRouter` over
  :class:`ProcFleetConfig`.
- :mod:`~paddle_tpu.inference.procfleet.presets` — picklable worker
  engine factories for drills/tests/benches.

The wire/worker/proxy layer is pure host control plane (stdlib only);
the router layer rides the fleet substrate. Workers pull in the heavy
stack in their OWN process — a driver spawning N replicas pays one jax
runtime, not N.
"""

from .proxy import ProcReplica, WorkerDead  # noqa: F401
from .router import (ProcFleetConfig, ProcFleetRouter,  # noqa: F401
                     ProcTieredRouter)
from .wire import Message, WireClosed, WireCorrupt  # noqa: F401
from .worker import WorkerSpec, worker_main  # noqa: F401

__all__ = ["Message", "ProcFleetConfig", "ProcFleetRouter", "ProcReplica",
           "ProcTieredRouter", "WireClosed", "WireCorrupt", "WorkerDead",
           "WorkerSpec", "worker_main"]
