"""paddle_tpu.inference.procfleet — process-per-replica serving transport.

The fleet/tiered routers and the SLO autoscaler (docs/SERVING.md,
ROADMAP item 1) gain REAL replica isolation: each replica is a spawned
worker process owning its own engine, device memory and journal, driven
over a crc-framed localhost wire protocol. Replica death is process death
(a SIGKILL'd worker's unfinished work re-admits on survivors
byte-identically from its on-disk journal — the ``fleet_proc_kill``
drill), and scale-out is measurable (``bench_fleet --processes`` →
``fleet_proc_tokens_per_sec``).

Modules:

- :mod:`~paddle_tpu.inference.procfleet.wire` — the PT-PROC framed
  message protocol (:class:`WireCorrupt` = PT-PROC-001).
- :mod:`~paddle_tpu.inference.procfleet.transport` — the pluggable
  frame transport seam (:class:`TcpTransport`,
  :class:`LoopbackTransport` for in-process thread workers, and the
  fault-injecting :class:`ChaosTransport` driven by the ``net.*``
  FaultPlan sites — docs/RESILIENCE.md).
- :mod:`~paddle_tpu.inference.procfleet.worker` — the spawned replica
  process (:class:`WorkerSpec`, ``worker_main``) and its loopback
  thread twin (``worker_thread_main``).
- :mod:`~paddle_tpu.inference.procfleet.proxy` — the driver-side replica
  proxy (:class:`ProcReplica`, :class:`WorkerDead` = PT-PROC-002/003,
  the per-peer :class:`CircuitBreaker` raising :class:`BreakerOpen` =
  PT-PROC-004).
- :mod:`~paddle_tpu.inference.procfleet.router` —
  :class:`ProcFleetRouter` / :class:`ProcTieredRouter` over
  :class:`ProcFleetConfig`.
- :mod:`~paddle_tpu.inference.procfleet.presets` — picklable worker
  engine factories for drills/tests/benches.

The wire/worker/proxy layer is pure host control plane (stdlib only);
the router layer rides the fleet substrate. Workers pull in the heavy
stack in their OWN process — a driver spawning N replicas pays one jax
runtime, not N.
"""

from .proxy import (BreakerOpen, CircuitBreaker, MeshMismatch,  # noqa: F401
                    ProcReplica, WorkerDead)
from .router import (ProcFleetConfig, ProcFleetRouter,  # noqa: F401
                     ProcTieredRouter)
from .transport import (ChaosTransport, LoopbackTransport,  # noqa: F401
                        TcpTransport, Transport, loopback_pair)
from .wire import Message, WireClosed, WireCorrupt  # noqa: F401
from .worker import WorkerSpec, worker_main, worker_thread_main  # noqa: F401

__all__ = ["BreakerOpen", "ChaosTransport", "CircuitBreaker",
           "LoopbackTransport", "Message", "MeshMismatch", "ProcFleetConfig",
           "ProcFleetRouter", "ProcReplica", "ProcTieredRouter",
           "TcpTransport", "Transport", "WireClosed", "WireCorrupt",
           "WorkerDead", "WorkerSpec", "loopback_pair", "worker_main",
           "worker_thread_main"]
