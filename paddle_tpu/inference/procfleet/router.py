"""Process-per-replica fleet wiring: FleetRouter over worker processes.

:class:`ProcFleetRouter` is a :class:`~paddle_tpu.inference.fleet.FleetRouter`
whose ONE overridden construction point (``_make_sup``) spawns a replica
worker process and returns its :class:`~.proxy.ProcReplica` — every router
behavior (radix-affinity routing, journal-backed failover, drain/rolling
restart, brownout, the autoscaler's ``add_replica``/``retire_replica``)
runs unchanged over real processes:

- ``add_replica()`` SPAWNS a process (the autoscaler's scale-up is now a
  real scale-out: each worker owns its own device memory and its own
  python interpreter — ``bench_fleet --processes`` measures it);
- ``retire_replica()`` drains then REAPS the process (scale-in);
- a replica death is process death: ``WorkerDead`` out of a step hits the
  router's existing exception boundary, the proxy's on-disk journal
  (shared ``fleet_dir``, unchanged format) feeds the existing failover,
  and a SIGKILL'd worker's streams continue byte-identically on
  survivors — the ``fleet_proc_kill`` drill's contract.

:class:`ProcTieredRouter` runs the disaggregated prefill/decode split
(inference/disagg.py) over process tiers: finished-prefill KV chains
travel the wire as ``KVChainCodec`` artifacts in MIGRATE_OUT/MIGRATE_IN
frames — per-page crc32 + chain digest verified at import on the decode
worker, so in-transit damage is a typed PT-SRV-007 refusal there exactly
as in-process (the artifact bytes ARE the transport format; a future
RDMA/ICI path slots in behind the same codec).
"""

from __future__ import annotations

import dataclasses
import socket
import time
from typing import Callable, Dict, List, Optional, Union

from ..fleet import FleetRouter, ReplicaState, _GONE
from .proxy import ProcReplica, WorkerDead
from .worker import WorkerSpec

__all__ = ["ProcFleetConfig", "ProcFleetRouter", "ProcTieredRouter"]


@dataclasses.dataclass
class ProcFleetConfig:
    """How worker processes are built and supervised.

    - ``factory`` / ``factory_kwargs``: the picklable engine factory every
      worker imports and calls (procfleet/presets.py ships test/bench
      factories). Factories seed their own rng — identical weights per
      replica is what makes cross-process failover byte-identical.
    - ``sup_kwargs``: per-worker ``ServingSupervisor`` knobs
      (step_budget_s, max_recoveries, fsync).
    - ``env``: environment applied in the child before heavy imports
      (e.g. ``{"JAX_PLATFORMS": "cpu"}`` pins workers to host devices).
    - ``op_timeout_s``: per-wire-op budget; a worker that stops answering
      is treated as dead (PT-PROC-003).
    - ``spawn_timeout_s``: spawn → HELLO budget (covers the child's jax
      import + model build + engine construction).
    - ``heartbeat_s``: optional driver-side heartbeat probe interval
      (``pt_procfleet_heartbeats_total``); None polls only at fleet steps.
    - ``metrics_port``: 0 = each worker binds an ephemeral ``/metrics``
      port (reported in HELLO, aggregated under ``replica=i`` labels by
      ``procfleet_collector``); None disables worker endpoints.
    - ``transport``: ``"tcp"`` (default, real worker processes) or
      ``"loopback"`` — worker threads over an in-process queue-pair
      transport: same supervisor/journal/serve loop, no process spawn, no
      cold jit (the fast arm for tests and chaos drills; ``env`` is NOT
      applied and workers bind no metrics port).
    - ``chaos``: wrap every replica transport in a
      :class:`~.transport.ChaosTransport` — the active ``FaultPlan``'s
      ``net.connect``/``net.send``/``net.recv`` specs inject drops,
      stalls, duplicate delivery, torn frames, payload bitflips and
      per-peer blackholes (docs/RESILIENCE.md).
    - ``breaker``: per-replica circuit-breaker kwargs (see
      :class:`~.proxy.CircuitBreaker`: fail_threshold, latency_s,
      cooldown_s, ema_alpha), or None for no breaker.
    - ``migrate_bw_bytes_per_s``: assumed wire bandwidth sizing the
      MIGRATE_IN/OUT per-op deadlines to the payload bytes.
    - ``hedge``: race a timed-out MIGRATE_IN against the next decode
      replica (False = retry the same target only).
    - ``verify_crc``: worker-side per-page crc verification on chain
      import — ``False`` is the fault drills' control arm (silent
      corruption instead of a typed PT-SRV-007).
    - ``mesh``: in-replica tensor-parallel width (None/1 = unsharded
      workers). Each replica serves from its OWN device group, so fleet
      scale-out composes with in-replica sharding (docs/SERVING.md
      "Sharded serving"): spawned (tcp) workers own a fresh runtime and
      bind its first ``mesh`` devices (cpu platforms force that many XLA
      host devices before backend init); loopback worker threads share
      THIS process's runtime, so the driver hands replica ``i`` the
      disjoint device slice ``[i*mesh, (i+1)*mesh)`` (wrapping modulo
      the available groups). Requires a factory whose engine accepts
      ``mesh=`` (the presets pass it through).
    """

    factory: Union[str, Callable]
    factory_kwargs: dict = dataclasses.field(default_factory=dict)
    sup_kwargs: dict = dataclasses.field(default_factory=dict)
    env: dict = dataclasses.field(default_factory=dict)
    op_timeout_s: float = 120.0
    spawn_timeout_s: float = 300.0
    heartbeat_s: Optional[float] = None
    metrics_port: Optional[int] = 0
    transport: str = "tcp"
    chaos: bool = False
    breaker: Optional[dict] = None
    migrate_bw_bytes_per_s: float = 32.0 * 1024 * 1024
    hedge: bool = True
    verify_crc: bool = True
    mesh: Optional[int] = None


class ProcFleetRouter(FleetRouter):
    """N replica worker PROCESSES behaving like one reliable engine.

    >>> proc = ProcFleetConfig(
    ...     factory="paddle_tpu.inference.procfleet.presets:"
    ...             "tiny_llama_engine")
    >>> fleet = ProcFleetRouter(proc, fleet_dir, num_replicas=2)
    >>> fleet.submit(Request(prompt, max_new_tokens=64))
    >>> done = fleet.run_until_done()
    >>> fleet.close()                       # reaps every worker

    ``step_budget_s``/``max_recoveries``/``fsync`` are per-WORKER
    supervisor knobs here — set them in ``proc_config.sup_kwargs`` (each
    worker arms its own StepWatchdog in its own process)."""

    def __init__(self, proc_config: ProcFleetConfig, fleet_dir: str,
                 num_replicas: int = 2, **kw):
        self.proc = proc_config
        # build_engine is never called driver-side (workers build their
        # own engines); the factory rides along for introspection only
        super().__init__(proc_config.factory, fleet_dir,
                         num_replicas=num_replicas, **kw)
        self.stats.setdefault("proc_spawned", 0)
        self.stats.setdefault("proc_reaped", 0)

    def _cfg_for(self, idx: int) -> ProcFleetConfig:
        """The replica's FULL worker config — factory AND transport knobs
        (op/spawn timeouts, heartbeat); the tiered subclass returns the
        tier's own config so a slow decode build gets decode's budgets."""
        return self.proc

    def _spec_kwargs(self, idx: int) -> dict:
        cfg = self._cfg_for(idx)
        mesh = (int(cfg.mesh) if cfg.mesh and int(cfg.mesh) > 1 else None)
        group = None
        if mesh is not None and cfg.transport == "loopback":
            # loopback worker threads share THIS process's jax runtime:
            # hand each replica a disjoint device-group slice by index
            # (wrapping modulo the available groups — overlapping groups
            # on small hosts share devices, they never miscompute)
            import jax

            n_groups = max(1, len(jax.devices()) // mesh)
            gi = idx % n_groups
            group = tuple(range(gi * mesh, (gi + 1) * mesh))
        return dict(factory=cfg.factory,
                    factory_kwargs=dict(cfg.factory_kwargs),
                    sup_kwargs=dict(cfg.sup_kwargs),
                    env=dict(cfg.env),
                    metrics_port=cfg.metrics_port,
                    verify_crc=cfg.verify_crc,
                    mesh=mesh,
                    device_group=group,
                    tier=self.tier_of(idx))

    def _make_sup(self, idx: int, path: str) -> ProcReplica:
        spec = WorkerSpec(journal_path=path, **self._spec_kwargs(idx))
        cfg = self._cfg_for(idx)
        tags = {"replica": idx}
        return ProcReplica(
            spec, idx=idx, tracer=self.tracer, trace_tags=tags,
            op_timeout_s=cfg.op_timeout_s,
            spawn_timeout_s=cfg.spawn_timeout_s,
            heartbeat_s=cfg.heartbeat_s, stats=self.stats,
            transport=cfg.transport, chaos=cfg.chaos,
            breaker=cfg.breaker,
            migrate_bw_bytes_per_s=cfg.migrate_bw_bytes_per_s)

    def drain(self, idx: int) -> None:
        """Router drain + a worker-side DRAIN mark (the worker refuses new
        non-resumed admissions for the window — defense in depth while
        the router migrates its queue)."""
        rep = self.replicas[idx]
        if (self.graceful_drain and rep.state == ReplicaState.ALIVE
                and isinstance(rep.sup, ProcReplica) and not rep.sup.dead):
            try:
                rep.sup.drain_mark()
            except WorkerDead:
                pass            # death wins: the step loop will adjudicate
        super().drain(idx)

    def worker_metrics_urls(self) -> Dict[int, str]:
        """``{replica idx: /metrics url}`` for every live worker — the
        remote-scrape topology input (docs/OBSERVABILITY.md)."""
        out = {}
        for rep in self.replicas:
            if rep.state in _GONE or not isinstance(rep.sup, ProcReplica):
                continue
            url = rep.sup.metrics_url
            if url and not rep.sup.dead:
                out[rep.idx] = url
        return out

    def heartbeat_total(self) -> int:
        return sum(rep.sup.heartbeat_count() for rep in self.replicas
                   if isinstance(rep.sup, ProcReplica))


class ProcTieredRouter(ProcFleetRouter):
    """Disaggregated prefill/decode tiers over process replicas.

    Replicas ``0..num_prefill-1`` are the prefill tier (new submissions
    route only here), the rest decode. After every fleet tick the driver
    pumps finished prefills: MIGRATE_OUT exports + retires the chain on
    the prefill worker (its journal's ``migr-kv`` keeps the rid out of its
    replay set), the artifact crosses the wire, MIGRATE_IN splices it into
    the least-loaded decode worker which verifies per-page crc32 + chain
    digest before a byte touches its pool. Refusal, typed corruption and
    import TIMEOUT all take ONE retry-elsewhere policy (the driver still
    holds the clean artifact — wire-transit damage is per-hop): try the
    next decode worker, with the timeout arm HEDGING onto the
    next-least-loaded replica and rolling the loser back via
    MIGRATE_CANCEL; exhausted, re-run prefill under resume semantics on a
    survivor."""

    def __init__(self, prefill_config: ProcFleetConfig,
                 decode_config: ProcFleetConfig, fleet_dir: str,
                 num_prefill: int = 1, num_decode: int = 1, **kw):
        if num_prefill < 1 or num_decode < 1:
            raise ValueError("each tier needs at least one replica")
        self._prefill_cfg = prefill_config
        self._decode_cfg = decode_config
        self._num_prefill = int(num_prefill)
        super().__init__(prefill_config, fleet_dir,
                         num_replicas=int(num_prefill) + int(num_decode),
                         **kw)
        try:
            for rep in self.replicas:
                if not rep.sup.engine.prefix_cache:
                    raise ValueError(
                        f"{rep.tier}-tier worker {rep.idx} was built "
                        "without a prefix cache — KV-block migration needs "
                        "prefix_cache engines on both tiers")
        except Exception:
            # every worker already spawned: a validation failure must not
            # leak N full-jax processes until interpreter exit
            self.close()
            raise
        self.stats.update(migrations=0, migration_s=0.0, migration_pages=0,
                          migration_bytes=0, migration_corrupt=0,
                          migration_deferred=0, migration_refused=0,
                          migration_reprefill=0, migration_hedges=0)
        #: per-migration wall-clock seconds, newest-last, capped — the
        #: ``serving_migration_under_loss`` bench reads p99 from here
        self.migration_samples: List[float] = []
        self._hedge = bool(decode_config.hedge)
        self._corrupt_hook = None

    def tier_of(self, idx: int) -> str:
        return "prefill" if idx < self._num_prefill else "decode"

    def _cfg_for(self, idx: int) -> ProcFleetConfig:
        # the tier's OWN config drives both the worker spec and the
        # proxy's transport knobs — a slow decode build gets decode's
        # spawn budget, and the drills' verify_crc/chaos/breaker arms
        # land on the tier they target
        return (self._prefill_cfg if idx < self._num_prefill
                else self._decode_cfg)

    def _routable(self, req):
        alive = super()._routable(req)
        pre = [r for r in alive if r.tier == "prefill"]
        return pre or alive

    def _pick_survivor(self, req, exclude=frozenset()):
        alive = [r for r in self.replicas
                 if r.state == ReplicaState.ALIVE and r.idx not in exclude]
        pool = [r for r in alive if r.tier == "prefill"] or alive
        if not pool:
            return None
        n = len(pool)
        return min(pool, key=lambda r: (r.sup.load(),
                                        (r.idx - req.rid) % n))

    # -- the migration pump (driver thread, post-tick) ---------------------
    # LOCKSTEP NOTE: this pump mirrors disagg.TieredRouter's
    # (_migrate_ready/_migrate_one/_compatible/_decode_targets) with the
    # engine-touching steps replaced by wire ops (export_migration /
    # import_migration) — a behavioral fix to either pump (new refusal
    # class, stats key, trace tag, fallback ordering) must land in BOTH.
    def step(self) -> None:
        super().step()
        self._migrate_ready()

    def _decode_targets(self, rid: int) -> List:
        # an OPEN breaker filters the replica out of the candidate list —
        # a slow peer must not eat a migration's whole deadline before
        # the hedge even starts (all breakers open -> deferred: the rid
        # keeps decoding on the prefill tier and retries next step)
        alive = [r for r in self.replicas
                 if r.state == ReplicaState.ALIVE and r.tier == "decode"
                 and not r.sup.dead
                 and r.sup.breaker_state() != "open"]
        n = max(1, len(alive))
        return sorted(alive, key=lambda r: (r.sup.load(),
                                            (r.idx - rid) % n))

    def _compatible(self, src, dst, user) -> bool:
        """Geometry gate from the workers' HELLO state PLUS the capacity
        gate from their latest reply-piggybacked ``[free slots, free
        pages]`` — a chain must never be retired from its source toward a
        worker that cannot hold it (a merely-full decode tier DEFERS: the
        candidate keeps decoding on the prefill tier and retries next
        step, instead of paying a whole re-prefill). The page estimate is
        optimistic, same as in-process — the import's ``EngineSaturated``
        fallback stays load-bearing."""
        s, d = src.sup.engine, dst.sup.engine
        if not (bool(getattr(d, "prefix_cache", False))
                and d.page_size == s.page_size
                and getattr(d, "layers", None) == getattr(s, "layers", None)
                and getattr(d, "kvh", None) == getattr(s, "kvh", None)
                and getattr(d, "hd", None) == getattr(s, "hd", None)
                and getattr(d, "dtype", None) == getattr(s, "dtype", None)
                and len(user.prompt) + user.max_new_tokens <= d.max_len):
            return False
        # engine._pages_needed, driver-side
        need = -(-(len(user.prompt) + user.max_new_tokens) // s.page_size)
        if getattr(d, "maxp", 0) < need:
            return False
        cap = dst.sup.capacity()
        return cap[0] >= 1 and cap[1] >= need

    def _reprefill_if_stranded(self, rid: int, user, src) -> None:
        """After a mid-handoff source death: if the journal adjudication
        left the rid owned by the (now dead) source and unfinished — the
        worker's ``migr-kv`` had committed, so its failover rightly
        skipped it — re-admit under resume semantics on a survivor. The
        source is dead, the target never spliced: no double-serve is
        possible, and re-running prefill beats the at-most-once drop."""
        if user.done or self._assigned.get(rid, src.idx) != src.idx:
            return
        target = self._pick_survivor(user, exclude={src.idx})
        if target is None:
            user.done = user.failed = True
            user.error = (f"PT-TIER-001: no surviving replica to re-run "
                          f"stranded migrated rid={rid} on")
            self._trace_lost(rid, user, src.idx)
            return
        self.stats["migration_reprefill"] += 1
        target.sup.submit(user, resume=True)
        self._assigned[rid] = target.idx
        self.events.append(
            ("PT-TIER-001",
             f"rid={rid} handoff interrupted by source death — prefill "
             f"re-run on replica {target.idx}"))

    def _migrate_ready(self) -> None:
        if self._corrupt_hook is None:
            from ...distributed.resilience.faults import corrupt

            self._corrupt_hook = corrupt
        for rep in self.replicas:
            if (rep.state != ReplicaState.ALIVE or rep.tier != "prefill"
                    or rep.sup.dead):
                continue
            for rid in rep.sup.migration_ready():
                user = self.requests.get(rid)
                if user is None or user.done or rep.sup.behind(rid):
                    continue
                self._migrate_one(rep, rid, user)

    def _migrate_one(self, src, rid: int, user) -> bool:
        targets = [r for r in self._decode_targets(rid)
                   if self._compatible(src, r, user)]
        if not targets:
            self.stats["migration_deferred"] += 1
            return False            # no decode capacity: decode in place
        t0 = time.monotonic()
        t0_tr = None if self.tracer is None else self.tracer.now()
        try:
            hdr, art = src.sup.export_migration(rid)
        except (KeyError, ValueError):
            return False            # finished/raced inside the worker:
        #                             nothing was retired, nothing moved
        except Exception as e:  # noqa: BLE001 — replica death boundary
            # WorkerDead, or a damaged CHAIN reply: whether the worker
            # committed its migr-kv before the failure is unknowable from
            # here — mark it dead and let the journal-backed failover
            # adjudicate from the ON-DISK truth (migr-kv committed → the
            # rid is re-admitted below, not replayed from that journal;
            # not committed → failover replays it). Same posture as the
            # in-process pump's catch-all (disagg.py _migrate_one): the
            # rid must never be stranded by an escaping exception.
            self._mark_dead(src, f"export of rid={rid} failed: "
                            f"{type(e).__name__}: {e}")
            self._handle_death(src)
            self._reprefill_if_stranded(rid, user, src)
            return True
        # in-transit hook: the kv_migration_corruption drill's site —
        # driver-side, between the two workers, exactly where real
        # transport damage would land
        art = self._corrupt_hook("serving.kv_transfer", f"rid:{rid}", art)
        placed = None
        corrupt_art = False
        from ..disagg import KVChainCorrupt
        from ..serving import EngineSaturated

        # one idempotence key per LOGICAL migration, stable across every
        # attempt and every target: a chaos-duplicated MIGRATE_IN answers
        # from the worker's idem cache instead of double-splicing, and the
        # no-hedge-target resend below dedups against a splice that DID
        # land before the reply was lost
        idem = f"mig:{rid}:{hdr['digest'][:16]}"
        # UNIFIED retry-elsewhere policy: a refusal (EngineSaturated /
        # geometry ValueError), a typed corruption (wire-transit damage is
        # per-hop — this driver still holds the artifact it exported) and
        # a clean import TIMEOUT all mean "this target didn't take it, the
        # chain is intact here": try the next-least-loaded decode replica.
        # Only the timeout arm is a HEDGE — the laggard may still splice
        # late, so the loser is rolled back below.
        timed_out: List = []
        queue = list(targets)
        i = 0
        while i < len(queue):
            rep = queue[i]
            i += 1
            try:
                rep.sup.import_migration(user, art, idem=idem)
                placed = rep
                break
            except socket.timeout:
                timed_out.append(rep)
                if not self._hedge and queue.count(rep) < 2:
                    # hedging disabled: retry the SAME replica once under
                    # the SAME idem key before considering anyone else
                    queue.insert(i, rep)
                    continue
                if i < len(queue):
                    if self._hedge:
                        # race the next-least-loaded candidate while this
                        # one lags
                        self.stats["migration_hedges"] += 1
                        if self.tracer is not None:
                            self.tracer.migration_failure(
                                rid, "hedged", tags={"replica": rep.idx})
                    continue
                if queue.count(rep) < 2:
                    # no hedge target left: resend to the SAME replica
                    # under the SAME idem key — if the first splice landed
                    # and only the reply was lost, the worker answers
                    # SPLICED from its idem cache
                    queue.append(rep)
                continue
            except KVChainCorrupt as e:
                corrupt_art = True
                self.stats["migration_corrupt"] += 1
                self.events.append(("PT-SRV-007", str(e)))
                if self.tracer is not None:
                    self.tracer.migration_failure(
                        rid, "corrupt", tags={"replica": rep.idx})
                continue
            except (EngineSaturated, ValueError):
                self.stats["migration_refused"] += 1
                if self.tracer is not None:
                    self.tracer.migration_failure(
                        rid, "refused", tags={"replica": rep.idx})
                continue
            except Exception as e:  # noqa: BLE001 — replica death boundary
                # WorkerDead, a desynced reply, an unexpected typed error
                # out of the worker: that replica's engine/stream is
                # untrusted — same catch-all as disagg.py's _migrate_one
                # ("must not escape: the rid is already retired from the
                # source"). Mark it dead, fail its work over, try the
                # next target.
                self._mark_dead(rep, f"splice of rid={rid} failed: "
                                f"{type(e).__name__}: {e}")
                self._handle_death(rep)
                if self._assigned.get(rid, src.idx) != src.idx:
                    return True     # its failover already re-placed it
                continue
        # hedge losers: any replica whose import timed out but is NOT the
        # winner may splice late — roll it back (journal migr-kv, pages
        # decref'd, allocator untouched) so the chain is live exactly
        # once. Best-effort: a loser that died or is still wedged keeps
        # its idem entry, and the rid is purged from its cache either way
        # when the cancel does land.
        for rep in timed_out:
            if rep is placed or rep.sup.dead:
                continue
            try:
                if rep.sup.migrate_cancel(rid, hdr["digest"]):
                    self.events.append(
                        ("PT-TIER-001",
                         f"rid={rid} hedge loser on replica {rep.idx} "
                         "rolled back (late splice retired)"))
            except Exception:  # noqa: BLE001 — winner already placed
                pass
        if placed is None:
            alive = self._decode_targets(rid)
            target = (alive[0] if alive
                      else self._pick_survivor(user, exclude=set()))
            if target is None:
                user.done = user.failed = True
                user.error = (f"PT-TIER-001: no surviving replica to "
                              f"place migrated rid={rid} on")
                self._trace_lost(rid, user, src.idx)
                return True
            self.stats["migration_reprefill"] += 1
            target.sup.submit(user, resume=True)
            self._assigned[rid] = target.idx
            self.events.append(
                ("PT-TIER-001",
                 f"rid={rid} chain not spliced "
                 f"({'corrupt' if corrupt_art else 'refused'}) — prefill "
                 f"re-run on replica {target.idx}"))
            return True
        self._assigned[rid] = placed.idx
        dt = time.monotonic() - t0
        self.stats["migrations"] += 1
        self.stats["migration_s"] += dt
        self.migration_samples.append(dt)
        del self.migration_samples[:-512]
        self.stats["migration_pages"] += int(hdr["pages"])
        self.stats["migration_bytes"] += len(art)
        self.events.append(
            ("PT-TIER-001",
             f"rid={rid} chain ({hdr['pages']} page(s), {len(art)} bytes) "
             f"migrated worker {src.idx} -> {placed.idx} over the wire in "
             f"{dt * 1e3:.1f}ms"))
        if self.tracer is not None:
            self.tracer.migrate(rid, src.idx, placed.idx,
                                pages=int(hdr["pages"]), nbytes=len(art),
                                t0=t0_tr, tags={"replica": placed.idx})
        return True
