"""Pluggable frame transport for the process fleet — and its chaos twin.

PR 14's proxy/worker pair talked straight to a TCP socket, which welded
the fleet to one medium and left the wire as the only subsystem the
seeded fault drills had never touched. This module is the seam ROADMAP
item 1 charters (an RDMA/ICI-shaped transport behind the KV migration
path): everything above it — :class:`~.proxy.ProcReplica`, the worker
serve loop, the routers — moves whole :class:`~.wire.Message` frames
through four verbs (``connect``/``send_frame``/``recv_frame``/``close``)
and never sees a socket.

Three implementations:

- :class:`TcpTransport` — the existing localhost socket, unchanged
  semantics: a send timeout or vanished peer is :class:`WireClosed`, a
  recv timeout propagates ``socket.timeout`` carrying the
  ``partial_read`` flag (False only when ZERO frame bytes were read, so
  callers know whether the stream position is still aligned).
- :class:`LoopbackTransport` — an in-process queue pair built by
  :func:`loopback_pair`. Frames still travel as encoded BYTES through
  the real codec (chunk boundaries and torn prefixes behave exactly like
  TCP), but the worker can live on a thread: the fast arm for tests and
  drills that would otherwise pay a process spawn + cold jit per case.
- :class:`ChaosTransport` — a decorator over either, consulting the
  PR 2 :class:`FaultPlan` at three new sites (``net.connect``,
  ``net.send``, ``net.recv``). Control actions (``stall``/``delay``/
  ``kill``/``error``) behave as everywhere else; data actions
  (``bitflip``/``truncate``/``garbage``) damage the PAYLOAD and then
  re-frame, so the frame crc is valid over corrupt bytes — the
  silent-network-damage case only end-to-end checks (the KV chain's
  per-page crc32) can catch; net actions are frame-level: ``drop``
  loses the frame, ``duplicate`` delivers it twice, ``torn`` ships a
  prefix (the receiver's next read misaligns into a typed
  ``WireCorrupt``), ``blackhole`` swallows every subsequent frame
  to/from that peer.

Determinism: every fault decision comes from the installed plan's
per-spec counters and seeded rng (``faults.wire_faults``), so the same
plan over the same frame stream injects byte-identical chaos.
"""

from __future__ import annotations

import queue
import socket
import threading
import time
from typing import Optional, Tuple

from ...distributed.resilience.faults import FaultInjected, active_plan, \
    wire_faults
from . import wire
from .wire import Message, WireClosed, WireCorrupt

__all__ = ["Transport", "TcpTransport", "LoopbackTransport",
           "ChaosTransport", "loopback_pair"]


class Transport:
    """One end of a framed, ordered, reliable-until-faulted byte stream.

    The contract every implementation (and every chaos decorator) keeps:

    - ``send_frame`` either ships one whole frame or raises
      :class:`WireClosed` (the outgoing stream position is unusable).
    - ``recv_frame`` returns exactly one validated :class:`Message`,
      raises ``socket.timeout`` (with ``partial_read``) when the peer is
      silent, :class:`WireClosed` on peer death, :class:`WireCorrupt` on
      damaged bytes.
    - ``close`` is idempotent and unblocks the peer's pending recv.
    """

    peer: str = "?"

    def connect(self) -> None:
        """Establish the stream (no-op for already-connected ends)."""

    def send_frame(self, msg: Message) -> None:
        self.send_bytes(wire.encode(msg), msg.mtype)

    def send_bytes(self, data: bytes, mtype: str = "?") -> None:
        raise NotImplementedError

    def recv_frame(self, timeout: Optional[float] = None) -> Message:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class TcpTransport(Transport):
    """The PR 14 socket, behind the seam. Wraps an already-connected
    socket (driver accept side / worker connect-back side) or an
    ``(host, port)`` address to dial on :meth:`connect`."""

    def __init__(self, sock: Optional[socket.socket] = None,
                 addr: Optional[Tuple[str, int]] = None,
                 connect_timeout_s: float = 30.0):
        if sock is None and addr is None:
            raise ValueError("TcpTransport needs a socket or an address")
        self._sock = sock
        self._addr = addr
        self._connect_timeout_s = connect_timeout_s
        if sock is not None:
            try:
                name = sock.getpeername()
                # AF_UNIX socketpairs (tests) name peers with a str/bytes
                self.peer = ("%s:%d" % name[:2] if isinstance(name, tuple)
                             else (str(name) or "socketpair"))
            except OSError:
                self.peer = "tcp:?"
        else:
            self.peer = "%s:%d" % tuple(addr)

    def connect(self) -> None:
        if self._sock is None:
            self._sock = socket.create_connection(
                self._addr, timeout=self._connect_timeout_s)

    @property
    def sock(self) -> socket.socket:
        if self._sock is None:
            raise WireClosed(f"transport to {self.peer} never connected")
        return self._sock

    def send_frame(self, msg: Message) -> None:
        wire.send_msg(self.sock, msg)

    def send_bytes(self, data: bytes, mtype: str = "?") -> None:
        # same death mapping as wire.send_msg — raw-frame sends are how
        # the chaos decorator ships torn/duplicated bytes
        try:
            self.sock.sendall(data)
        except socket.timeout as e:
            raise WireClosed(
                f"send of {mtype} stalled (frame possibly partially "
                "written — stream unusable)") from e
        except (BrokenPipeError, ConnectionResetError, OSError) as e:
            raise WireClosed(f"peer gone during send of {mtype}: "
                             f"{e}") from e

    def recv_frame(self, timeout: Optional[float] = None) -> Message:
        return wire.recv_msg(self.sock, timeout)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass


class LoopbackTransport(Transport):
    """In-process transport: encoded frame bytes over a queue pair.

    Bytes, not Message objects, deliberately — the full codec runs on
    both ends, chunk reassembly included, so loopback tests exercise the
    exact frame path TCP does (a torn prefix in the buffer misaligns the
    next frame into ``WireCorrupt``, like a real stream)."""

    _CLOSE = None          # queue sentinel: peer closed

    def __init__(self, rx: "queue.Queue", tx: "queue.Queue", peer: str):
        self._rx = rx
        self._tx = tx
        self.peer = peer
        self._buf = bytearray()
        # close() runs on the driver thread while the loopback worker is
        # blocked in recv_frame — _closed crosses threads
        self._lock = threading.Lock()
        self._closed = False

    def send_bytes(self, data: bytes, mtype: str = "?") -> None:
        with self._lock:
            closed = self._closed
        if closed:
            raise WireClosed(
                f"send of {mtype} on a closed loopback transport")
        self._tx.put(bytes(data))

    def recv_frame(self, timeout: Optional[float] = None) -> Message:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._buf:
                msg, used = wire.decode(bytes(self._buf))
                if msg is not None:
                    del self._buf[:used]
                    return msg
            with self._lock:
                closed = self._closed
            if closed:
                raise WireClosed("loopback transport closed"
                                 + (" mid-frame" if self._buf else ""))
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    e = socket.timeout(
                        f"loopback recv from {self.peer} exceeded its "
                        "deadline")
                    e.partial_read = bool(self._buf)
                    raise e
            try:
                chunk = self._rx.get(timeout=remaining)
            except queue.Empty:
                e = socket.timeout(
                    f"loopback recv from {self.peer} timed out")
                e.partial_read = bool(self._buf)
                raise e from None
            if chunk is self._CLOSE:
                if self._buf:
                    raise WireClosed(
                        f"peer {self.peer} closed the stream mid-frame "
                        f"({len(self._buf)} buffered bytes) — death")
                raise WireClosed(f"peer {self.peer} closed the stream")
            self._buf += chunk

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._tx.put(self._CLOSE)


def loopback_pair(a: str = "driver", b: str = "worker"
                  ) -> Tuple[LoopbackTransport, LoopbackTransport]:
    """Two connected loopback ends: frames sent on one arrive on the
    other. ``a``/``b`` become each end's ``peer`` name (what the OTHER
    end calls it)."""
    ab: "queue.Queue" = queue.Queue()
    ba: "queue.Queue" = queue.Queue()
    return (LoopbackTransport(rx=ba, tx=ab, peer=b),
            LoopbackTransport(rx=ab, tx=ba, peer=a))


def _damage(data: bytes, action: str, arg: float, rng) -> bytes:
    """The faults.corrupt bit/byte rules, driven by an already-fired
    spec (firing ``corrupt()`` here would advance the plan's counters a
    second time for one wire event)."""
    if action == "truncate":
        n = int(arg) or max(1, len(data) // 2)
        return data[: max(0, len(data) - n)]
    if action == "garbage":
        return bytes(rng.getrandbits(8) for _ in range(len(data)))
    buf = bytearray(data)
    if not buf:
        return data
    nbits = int(arg) or 1
    lo, hi = len(buf) // 4, max(len(buf) // 4 + 1, (3 * len(buf)) // 4)
    for _ in range(nbits):
        pos = rng.randrange(lo, hi)
        buf[pos] ^= 1 << rng.randrange(8)
    return bytes(buf)


class ChaosTransport(Transport):
    """Seeded network-fault decorator over any :class:`Transport`.

    Fires the installed :class:`FaultPlan` once per wire event —
    ``net.connect`` (detail = peer), ``net.send`` (detail =
    ``peer:MSGTYPE``), ``net.recv`` (detail = peer) — and interprets the
    due specs (module docstring for the action catalogue). With no plan
    installed every call is one global read plus the inner op."""

    def __init__(self, inner: Transport, peer: Optional[str] = None):
        self.inner = inner
        self.peer = peer if peer is not None else inner.peer
        # sticky blackhole is flipped by whichever thread's wire event
        # drew the fault and read by every subsequent send/recv
        self._lock = threading.Lock()
        self._blackholed = False

    # -- verbs ---------------------------------------------------------
    def connect(self) -> None:
        for s in wire_faults("net.connect", self.peer):
            if s.action in ("stall", "delay"):
                time.sleep(s.arg)
            elif s.action in ("kill", "drop"):
                raise FaultInjected(
                    f"fault injected: connect to {self.peer} refused")
            elif s.action == "error":
                raise RuntimeError(
                    f"fault injected: error connecting to {self.peer}")
            elif s.action == "blackhole":
                with self._lock:
                    self._blackholed = True
        self.inner.connect()

    def send_frame(self, msg: Message) -> None:
        dup = torn = False
        blob, body_damage = msg.blob, None
        for s in wire_faults("net.send", f"{self.peer}:{msg.mtype}"):
            if s.action in ("stall", "delay"):
                time.sleep(s.arg)
            elif s.action == "kill":
                raise FaultInjected(
                    f"fault injected: kill on send of {msg.mtype} to "
                    f"{self.peer}")
            elif s.action == "error":
                raise RuntimeError(
                    f"fault injected: error on send of {msg.mtype}")
            elif s.action == "blackhole":
                with self._lock:
                    self._blackholed = True
            elif s.action == "drop":
                return                      # the frame is simply gone
            elif s.action == "duplicate":
                dup = True
            elif s.action == "torn":
                torn = True
            elif s.action in ("bitflip", "truncate", "garbage"):
                plan = active_plan()
                if blob:
                    # payload damage UNDER the frame crc: the wire-level
                    # check passes, only end-to-end integrity catches it
                    blob = _damage(blob, s.action, s.arg, plan.rng)
                else:
                    body_damage = s
        with self._lock:
            blackholed = self._blackholed
        if blackholed:
            return
        if blob is not msg.blob:
            msg = Message(msg.mtype, msg.payload, blob)
        data = wire.encode(msg)
        if body_damage is not None:
            # no blob to damage: hit the framed bytes themselves (crc now
            # wrong — the receiver's typed WireCorrupt path)
            plan = active_plan()
            head = data[:wire._HEADER.size]
            data = head + _damage(data[wire._HEADER.size:],
                                  body_damage.action, body_damage.arg,
                                  plan.rng)
        if torn:
            self.inner.send_bytes(data[: max(1, len(data) // 2)],
                                  msg.mtype)
            return
        self.inner.send_bytes(data, msg.mtype)
        if dup:
            self.inner.send_bytes(data, msg.mtype)

    def send_bytes(self, data: bytes, mtype: str = "?") -> None:
        with self._lock:
            blackholed = self._blackholed
        if blackholed:
            return
        self.inner.send_bytes(data, mtype)

    def recv_frame(self, timeout: Optional[float] = None) -> Message:
        drop = False
        damage = []
        for s in wire_faults("net.recv", self.peer):
            if s.action in ("stall", "delay"):
                time.sleep(s.arg)
            elif s.action == "kill":
                raise FaultInjected(
                    f"fault injected: kill on recv from {self.peer}")
            elif s.action == "error":
                raise RuntimeError(
                    f"fault injected: error on recv from {self.peer}")
            elif s.action == "blackhole":
                with self._lock:
                    self._blackholed = True
            elif s.action == "drop":
                drop = True
            elif s.action in ("bitflip", "truncate", "garbage"):
                damage.append(s)
        with self._lock:
            blackholed = self._blackholed
        if blackholed:
            e = socket.timeout(
                f"peer {self.peer} blackholed — nothing will arrive")
            e.partial_read = False
            raise e
        msg = self.inner.recv_frame(timeout)
        if drop:
            # the reply existed but was lost in flight: consume it so the
            # stream stays aligned, then look like a silent peer
            e = socket.timeout(
                f"fault injected: recv from {self.peer} dropped "
                f"{msg.mtype}")
            e.partial_read = False
            raise e
        for s in damage:
            plan = active_plan()
            if plan is not None and msg.blob:
                msg = Message(msg.mtype, msg.payload,
                              _damage(msg.blob, s.action, s.arg, plan.rng))
        return msg

    def close(self) -> None:
        self.inner.close()
