"""PT-PROC wire protocol: length-prefixed, crc32-framed, versioned messages.

The process-per-replica fleet (docs/SERVING.md "Process fleet") exchanges
control messages between the driver and each replica worker process over a
localhost socket pair. The protocol is deliberately tiny and transparent —
SURVEY.md's fleet_executor message bus is the reference shape (typed
messages, explicit framing, a supervising driver), and the same integrity
posture as every other byte boundary in the repo (journal records,
checkpoint shards, KV-chain artifacts): every frame is crc-checked, damage
raises a TYPED error naming what broke, and silently-corrupt bytes never
reach a supervisor.

Frame layout (big-endian)::

    b"PTPF" | version u8 | type u8 | json_len u32 | blob_len u32 | crc u32
    <json payload> <binary blob>

- ``crc`` is crc32 over json+blob. A mismatch, a bad magic, an unknown
  version/type, an oversized length, or a frame truncated mid-payload
  raises :class:`WireCorrupt` (**PT-PROC-001**).
- The ``blob`` carries opaque binary payloads (KV-chain artifacts for
  tiered migration) beside the json control fields — no base64 inflation.
- Schemas are STRICT both ways: :func:`encode` and :func:`decode` validate
  that a message carries exactly its type's required fields with the
  expected json types, so a frame that round-trips is a frame both ends
  agree on (``decode(encode(m)) == m`` is pinned by tests).

Stream death vs damage: a socket that EOFs (the worker was SIGKILL'd, the
driver went away) raises :class:`WireClosed` — that is process death, the
fleet's failover trigger, not corruption. Only damaged BYTES are
PT-PROC-001.
"""

from __future__ import annotations

import json
import socket
import struct
import time
import zlib
from typing import Dict, Optional, Tuple

__all__ = ["Message", "WireClosed", "WireCorrupt", "decode", "decode_bytes",
           "encode", "recv_msg", "send_msg", "MSG_TYPES", "WIRE_VERSION"]

MAGIC = b"PTPF"
WIRE_VERSION = 1
_HEADER = struct.Struct(">4sBBIII")
#: frames larger than this are damage, not data (a corrupted length field
#: must not make recv_msg try to allocate gigabytes)
MAX_FRAME = 256 * 1024 * 1024


class WireCorrupt(RuntimeError):
    """PT-PROC-001: a frame failed its crc32, carried a bad magic/version/
    type/length, or violated its message schema — the bytes were damaged
    (or the peer speaks a different protocol). Never retried blindly: the
    stream position is untrusted from here on."""


class WireClosed(ConnectionError):
    """The peer closed the stream (clean EOF or mid-frame cut) — process
    death, the fleet failover trigger. Distinct from :class:`WireCorrupt`:
    a SIGKILL'd worker is an expected operational event, damaged bytes on
    a live stream are not."""


#: message types. Requests flow driver -> worker, replies worker -> driver;
#: ERROR is a typed refusal (the proxy re-raises the named exception class).
MSG_TYPES = {
    "HELLO": 1,        # worker -> driver, once: pid, metrics port, geometry
    "SUBMIT": 2,       # admit one request (resume=True carries delivered)
    "SUBMITTED": 3,
    "STEP": 4,         # one supervisor step
    "TOKENS": 5,       # step reply: per-rid deltas + progress marker
    "WITHDRAW": 6,     # pull a still-queued rid (drain migration)
    "WITHDRAWN": 7,
    "DRAIN": 8,        # stop admitting new work (in-flight finishes)
    "DRAINING": 9,
    "PROGRESS": 10,    # heartbeat probe / progress marker query
    "METRICS": 11,     # registry dump over the control socket
    "METRICS_TEXT": 12,
    "SHUTDOWN": 13,    # graceful close: flush journal, stop, exit 0
    "BYE": 14,
    "ERROR": 15,       # typed refusal: {etype, msg}
    "MIGRATE_OUT": 16,  # export + retire a finished-prefill KV chain
    "CHAIN": 17,        # reply: header json + artifact blob
    "MIGRATE_IN": 18,   # splice a migrated chain (artifact in the blob)
    "SPLICED": 19,
    "PROGRESS_REPLY": 20,   # PROGRESS answered with state
    "MIGRATE_CANCEL": 21,   # roll back a hedge-loser's spliced chain
    "CANCELLED": 22,
}
_TYPE_NAMES = {v: k for k, v in MSG_TYPES.items()}

#: required json fields per type: {field: type-or-types}. ``None`` in the
#: tuple marks an optional-null field. Strictness is the point — a frame
#: that decodes is a frame whose shape both ends agree on.
_OPT = type(None)
SCHEMAS: Dict[str, Dict[str, tuple]] = {
    # ``state`` mirrors the worker's load/progress marker/has_work so the
    # driver can answer router probes WITHOUT extra roundtrips: every
    # state change is driver-initiated (submit/step/withdraw) or rides a
    # step reply, so reply-piggybacked state is exact between ops
    "HELLO": {"pid": (int,), "metrics_port": (int, _OPT),
              "journal_path": (str,), "engine": (dict,), "state": (dict,)},
    # SUBMIT/MIGRATE_IN may additionally carry ``idem`` (a str idempotence
    # key, riding like the ``_seq`` stamp outside the required set): a
    # retried or chaos-duplicated delivery with a key the worker already
    # served is answered from its dedup cache, never served twice
    "SUBMIT": {"req": (dict,), "resume": (bool,), "delivered": (list,)},
    "SUBMITTED": {"rid": (int,), "load": (int,)},
    "STEP": {},
    "TOKENS": {"updates": (list,), "load": (int,), "sig": (list,),
               "behind": (list,), "ready": (list,), "has_work": (bool,),
               "cap": (list,)},
    "WITHDRAW": {"rid": (int,)},
    "WITHDRAWN": {"rec": (dict, _OPT), "load": (int,)},
    "DRAIN": {},
    "DRAINING": {"load": (int,)},
    "PROGRESS": {},
    "METRICS": {},
    "METRICS_TEXT": {"text": (str,)},
    "SHUTDOWN": {},
    "BYE": {},
    "ERROR": {"etype": (str,), "msg": (str,)},
    "MIGRATE_OUT": {"rid": (int,)},
    # ``updates``: token deltas the export's flush surfaced worker-side
    # that the driver has not seen yet — applied before the chain travels,
    # so the driver's delivered prefix always matches the artifact's
    "CHAIN": {"rid": (int,), "digest": (str,), "pages": (int,),
              "updates": (list,)},
    "MIGRATE_IN": {"req": (dict,), "delivered": (list,)},
    "SPLICED": {"rid": (int,)},
    "PROGRESS_REPLY": {"sig": (list,), "load": (int,),
                       "has_work": (bool,), "behind": (list,)},
    # hedged migration's loser side: if ``rid`` is still live from a
    # MIGRATE_IN with this chain digest, retire it (journal ``migr-kv``,
    # pages decref'd — allocator back where it started)
    "MIGRATE_CANCEL": {"rid": (int,), "digest": (str,)},
    "CANCELLED": {"rid": (int,), "rolled_back": (bool,)},
}


class Message:
    """One typed wire message: ``mtype`` (a :data:`MSG_TYPES` name), a json
    ``payload`` dict matching the type's schema, and an optional binary
    ``blob`` (KV-chain artifacts)."""

    __slots__ = ("mtype", "payload", "blob")

    def __init__(self, mtype: str, payload: Optional[dict] = None,
                 blob: bytes = b""):
        self.mtype = mtype
        self.payload = dict(payload or {})
        self.blob = bytes(blob)

    def __eq__(self, other):
        return (isinstance(other, Message) and self.mtype == other.mtype
                and self.payload == other.payload and self.blob == other.blob)

    def __repr__(self):
        return (f"Message({self.mtype!r}, {self.payload!r}"
                + (f", blob[{len(self.blob)}B]" if self.blob else "") + ")")


def _check_schema(msg: Message) -> None:
    schema = SCHEMAS.get(msg.mtype)
    if schema is None:
        raise WireCorrupt(
            f"PT-PROC-001: unknown message type {msg.mtype!r}")
    for field, kinds in schema.items():
        if field not in msg.payload:
            raise WireCorrupt(
                f"PT-PROC-001: {msg.mtype} frame missing required field "
                f"{field!r}")
        val = msg.payload[field]
        # bool is an int subclass — an int field must not accept True
        if isinstance(val, bool) and bool not in kinds:
            raise WireCorrupt(
                f"PT-PROC-001: {msg.mtype}.{field} is bool, schema wants "
                f"{tuple(k.__name__ for k in kinds)}")
        if not isinstance(val, kinds):
            raise WireCorrupt(
                f"PT-PROC-001: {msg.mtype}.{field} is "
                f"{type(val).__name__}, schema wants "
                f"{tuple(k.__name__ for k in kinds)}")


def encode(msg: Message) -> bytes:
    """Message -> framed bytes (schema-validated before a byte is built)."""
    _check_schema(msg)
    tid = MSG_TYPES[msg.mtype]
    try:
        body = json.dumps(msg.payload, separators=(",", ":"),
                          allow_nan=False).encode("utf-8")
    except (TypeError, ValueError) as e:
        raise WireCorrupt(
            f"PT-PROC-001: {msg.mtype} payload is not wire-encodable: "
            f"{e}") from None
    crc = zlib.crc32(msg.blob, zlib.crc32(body)) & 0xFFFFFFFF
    return _HEADER.pack(MAGIC, WIRE_VERSION, tid, len(body),
                        len(msg.blob), crc) + body + msg.blob


def decode_bytes(data: bytes) -> Message:
    """Strict offline decode of EXACTLY one frame (tests, buffers already
    read in full): truncation anywhere — header or payload — and trailing
    garbage are both PT-PROC-001."""
    msg, used = decode(data)
    if msg is None:
        raise WireCorrupt(
            f"PT-PROC-001: truncated frame ({len(data)} bytes)")
    if used != len(data):
        raise WireCorrupt(
            f"PT-PROC-001: {len(data) - used} trailing byte(s) after the "
            "frame")
    return msg


def decode(buf: bytes) -> Tuple[Optional[Message], int]:
    """Incremental decode: ``(message, bytes_consumed)``, or ``(None, 0)``
    when ``buf`` holds less than one complete frame. Damage (bad magic /
    version / type / length / crc / schema) raises :class:`WireCorrupt`."""
    if len(buf) < _HEADER.size:
        if buf and not MAGIC.startswith(bytes(buf[:4])[:len(buf)]):
            raise WireCorrupt("PT-PROC-001: bad frame magic "
                              f"{bytes(buf[:4])!r}")
        return None, 0
    magic, ver, tid, jlen, blen, crc = _HEADER.unpack_from(buf)
    if magic != MAGIC:
        raise WireCorrupt(f"PT-PROC-001: bad frame magic {magic!r}")
    if ver != WIRE_VERSION:
        raise WireCorrupt(
            f"PT-PROC-001: wire version {ver} (this end speaks "
            f"{WIRE_VERSION}) — driver and worker builds must match")
    if tid not in _TYPE_NAMES:
        raise WireCorrupt(f"PT-PROC-001: unknown message type id {tid}")
    if jlen + blen > MAX_FRAME:
        raise WireCorrupt(
            f"PT-PROC-001: frame length {jlen + blen} exceeds the "
            f"{MAX_FRAME}-byte ceiling — corrupted length field")
    total = _HEADER.size + jlen + blen
    if len(buf) < total:
        return None, 0
    body = bytes(buf[_HEADER.size:_HEADER.size + jlen])
    blob = bytes(buf[_HEADER.size + jlen:total])
    if (zlib.crc32(blob, zlib.crc32(body)) & 0xFFFFFFFF) != crc:
        raise WireCorrupt(
            f"PT-PROC-001: {_TYPE_NAMES[tid]} frame failed its crc32 — "
            "bytes damaged in transit")
    try:
        payload = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError):
        raise WireCorrupt(
            f"PT-PROC-001: {_TYPE_NAMES[tid]} frame crc passed but the "
            "payload does not parse — encoder bug, not line noise"
        ) from None
    if not isinstance(payload, dict):
        raise WireCorrupt(
            f"PT-PROC-001: {_TYPE_NAMES[tid]} payload is not an object")
    msg = Message(_TYPE_NAMES[tid], payload, blob)
    _check_schema(msg)
    return msg, total


# -- socket helpers ---------------------------------------------------------

def send_msg(sock: socket.socket, msg: Message) -> None:
    """Frame + send one message. A peer that vanished mid-send raises
    :class:`WireClosed` (death, not damage). A SEND timeout (the socket
    may carry a leftover recv timeout) is also :class:`WireClosed`: the
    frame may be partially written, so the outgoing stream position is
    unusable — the connection is done either way."""
    try:
        sock.sendall(encode(msg))
    except socket.timeout as e:
        raise WireClosed(
            f"send of {msg.mtype} stalled (frame possibly partially "
            "written — stream unusable)") from e
    except (BrokenPipeError, ConnectionResetError, OSError) as e:
        raise WireClosed(f"peer gone during send of {msg.mtype}: "
                         f"{e}") from e


def _recv_exact(sock: socket.socket, n: int, what: str,
                deadline: Optional[float] = None) -> bytes:
    """Read exactly ``n`` bytes. ``deadline`` (a ``time.monotonic``
    stamp) bounds the WHOLE read, not each chunk — a peer trickling one
    byte per interval must still trip the op budget, or the PT-PROC-003
    wedged-worker timeout is a fiction."""
    # a timeout AFTER any frame byte was consumed leaves the stream
    # position mid-frame — callers must NOT retry on such a socket; the
    # flag lets them distinguish "no reply yet" (stream still aligned,
    # retry + seq-drain is safe) from "reply half-read" (connection done)
    partial = what != "header"
    chunks = []
    got = 0
    while got < n:
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                e = socket.timeout(
                    f"frame {what} read exceeded its deadline "
                    f"({got}/{n} bytes)")
                e.partial_read = partial or got > 0
                raise e
            sock.settimeout(remaining)
        try:
            chunk = sock.recv(min(n - got, 1 << 20))
        except socket.timeout as e:
            # surfaced distinctly: the peer may be alive
            e.partial_read = partial or got > 0
            raise
        except (ConnectionResetError, OSError) as e:
            raise WireClosed(f"peer gone mid-{what}: {e}") from e
        if not chunk:
            if got == 0 and what == "header":
                raise WireClosed("peer closed the stream")
            raise WireClosed(
                f"peer closed the stream mid-{what} "
                f"({got}/{n} bytes) — process death")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket,
             timeout: Optional[float] = None) -> Message:
    """Read exactly one frame. ``timeout`` (seconds) bounds the whole
    read — header through last payload byte, across however many chunks;
    ``socket.timeout`` propagates so callers can treat a silent peer
    differently from a dead one. EOF raises :class:`WireClosed`, damage
    raises :class:`WireCorrupt`."""
    deadline = None
    if timeout is not None:
        sock.settimeout(timeout)
        deadline = time.monotonic() + timeout
    head = _recv_exact(sock, _HEADER.size, "header", deadline)
    magic, ver, tid, jlen, blen, crc = _HEADER.unpack_from(head)
    # validate BEFORE the body read so a garbage length cannot stall us
    if magic != MAGIC or ver != WIRE_VERSION or tid not in _TYPE_NAMES \
            or jlen + blen > MAX_FRAME:
        decode(head)                     # raises the precise WireCorrupt
        raise WireCorrupt("PT-PROC-001: malformed frame header")
    body = _recv_exact(sock, jlen + blen, "payload", deadline)
    return decode_bytes(head + body)
