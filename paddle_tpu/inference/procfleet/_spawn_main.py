"""Worker launch shim: ``python -m paddle_tpu.inference.procfleet._spawn_main``.

A separate entry module (instead of ``-m ...worker``) so runpy never
executes a module the package ``__init__`` already imported — the child
imports the package once, then runs the CLI."""

from .worker import _cli

if __name__ == "__main__":
    _cli()
