"""Picklable engine factories for process-replica workers.

A :class:`~paddle_tpu.inference.procfleet.worker.WorkerSpec` must name a
factory the SPAWNED process can import and call — a module-level function,
referenced by pickling or by ``"module:qualname"`` string. Test/drill/bench
factories live here (an importable module, not a test file or ``__main__``)
so every harness spawns workers through one audited path.

Determinism contract: a factory SEEDS the global rng before building its
model, so N worker processes build bit-identical weights — the same
property the in-process fleet gets from sharing one model object, and the
foundation of the byte-identical-failover guarantee across processes.
"""

from __future__ import annotations

__all__ = ["tiny_llama_engine", "tiny_llama_mesh_engine",
           "tiny_llama_prefix_engine"]


def tiny_llama_engine(seed: int = 13, num_hidden_layers: int = 1,
                      max_batch: int = 2, max_len: int = 32,
                      page_size: int = 8, block_size: int = 2,
                      max_queue=None, prefix_cache: bool = False, **kw):
    """CPU-sized 1-layer Llama serving engine, deterministically seeded —
    the worker-side twin of the engines tests/test_fleet.py builds."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(seed)
    cfg = LlamaConfig.tiny(num_hidden_layers=num_hidden_layers)
    model = LlamaForCausalLM(cfg)
    return ContinuousBatchingEngine(
        model, max_batch=max_batch, max_len=max_len, page_size=page_size,
        block_size=block_size, max_queue=max_queue,
        prefix_cache=prefix_cache, **kw)


def tiny_llama_prefix_engine(**kw):
    """The prefix-cache variant (KV-chain migration needs dynamic block
    tables on both tiers — inference/disagg.py)."""
    kw.setdefault("prefix_cache", True)
    return tiny_llama_engine(**kw)


def tiny_llama_mesh_engine(**kw):
    """Fused + prefix-cache variant for mesh-sharded workers: sharded
    serving requires the fused engine with a prefix cache, and the worker
    injects ``mesh=MeshConfig(tp, devices=<its group>)`` on top of these
    kwargs (``WorkerSpec.mesh`` — docs/SERVING.md "Sharded serving")."""
    kw.setdefault("prefix_cache", True)
    kw.setdefault("fused", True)
    kw.setdefault("max_batch", 4)
    return tiny_llama_engine(**kw)
