"""Driver-side proxy for one replica worker process.

:class:`ProcReplica` conforms to the replica surface
:class:`~paddle_tpu.inference.fleet.FleetRouter` consumes — submit / step /
finished / load / progress / behind / withdraw / close / abandon plus the
``.engine`` geometry namespace — so the router, the tiered router and the
SLO autoscaler drive a process-backed fleet through the code paths they
already have (docs/SERVING.md "Process fleet").

Failure semantics (the reason this module exists):

- **Death is process death.** A worker that SIGKILLs, segfaults or raises
  past its recovery budget surfaces here as :class:`WorkerDead`
  (**PT-PROC-002**) out of ``step()`` — the router's existing
  per-replica exception boundary marks the replica dead and runs its
  JOURNAL-BACKED failover against the worker's on-disk journal (shared
  directory, unchanged ``RequestJournal`` format). The proxy holds the
  caller-facing ``Request`` objects, so re-admitted streams continue
  byte-identically on survivors exactly like the in-process fleet.
- **Timeouts are typed.** Every wire op runs under a per-op timeout; a
  worker that stops answering is indistinguishable from a dead one and
  raises :class:`WorkerDead` naming the op (PT-PROC-003 in the message).
  Idempotent probes (PROGRESS / METRICS) additionally ride
  ``retry_call`` (distributed/resilience/retry.py) so one dropped
  datagram-worth of scheduling noise does not kill a healthy replica;
  mutating ops (SUBMIT/STEP/WITHDRAW) are deliberately single-shot —
  blind retry could double-apply.
- **Heartbeats.** An optional daemon thread probes PROGRESS every
  ``heartbeat_s`` so death is noticed between driver steps and
  ``pt_procfleet_heartbeats_total`` moves; the router's progress-staleness
  TTL rides the same marker it always has.

Trace stamps are made DRIVER-SIDE from the token deltas (submit → admit →
first_token → tokens → finish), on the driver's tracer and therefore on
its clock — virtual-clock replay (observability/workload.py) and the SLO
monitor see process replicas exactly like in-process ones.
"""

from __future__ import annotations

import atexit
import os
import pickle
import signal
import socket
import subprocess
import sys
import threading
import time
from types import SimpleNamespace
from typing import Dict, List, Optional, Set, Tuple

from .transport import ChaosTransport, TcpTransport, Transport, \
    loopback_pair
from .wire import Message, WireClosed, WireCorrupt
from .worker import WorkerSpec, worker_thread_main

__all__ = ["ProcReplica", "WorkerDead", "BreakerOpen", "CircuitBreaker",
           "MeshMismatch"]

# every live worker Popen, so an exiting driver never leaks processes —
# guarded: ProcReplica spawns/reaps from driver threads while atexit runs
# on the main thread
_LIVE_LOCK = threading.Lock()
_LIVE_WORKERS: Set[int] = set()          # pids
_ATEXIT_ARMED = [False]


def _kill_leftovers() -> None:
    with _LIVE_LOCK:
        pids = list(_LIVE_WORKERS)
        _LIVE_WORKERS.clear()
    for pid in pids:
        try:
            os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def _track_worker(pid: int) -> None:
    with _LIVE_LOCK:
        if not _ATEXIT_ARMED[0]:
            atexit.register(_kill_leftovers)
            _ATEXIT_ARMED[0] = True
        _LIVE_WORKERS.add(pid)


def _untrack_worker(pid: int) -> None:
    with _LIVE_LOCK:
        _LIVE_WORKERS.discard(pid)


class WorkerDead(RuntimeError):
    """PT-PROC-002: the replica worker process is gone (SIGKILL, crash,
    fatal supervisor error) or stopped answering within the op timeout —
    the router fails its work over from the on-disk journal."""


class MeshMismatch(RuntimeError):
    """PT-PROC-005: the worker's HELLO reported an engine mesh width that
    contradicts the spec the driver spawned it with (``WorkerSpec.mesh``)
    — a preset/config skew that would otherwise serve silently at the
    wrong width (wrong capacity weighting, wrong device-group accounting,
    a PT-COMM contract recorded at a width the fleet never asked for).
    Raised at spawn, before the replica joins the fleet; the worker is
    killed and reaped."""


class BreakerOpen(RuntimeError):
    """PT-PROC-004: this replica's circuit breaker is OPEN — the peer is
    slow-but-alive (consecutive failures or a latency EMA past budget),
    so ops fail FAST and the router routes around it. Deliberately not
    :class:`WorkerDead`: nothing is failed over, no journal is replayed —
    the worker keeps its in-flight state and rejoins when a HALF_OPEN
    probe (riding the piggybacked PROGRESS tick) comes back healthy."""


class CircuitBreaker:
    """Per-peer CLOSED -> OPEN -> HALF_OPEN breaker driven from
    ``_roundtrip`` outcomes (docs/SERVING.md "Transport seam").

    Two trip conditions, both about slow-but-ALIVE peers (death has its
    own path): ``fail_threshold`` consecutive retryable failures, or a
    latency EMA above ``latency_s``. While OPEN every non-probe op
    raises :class:`BreakerOpen` without touching the wire; after
    ``cooldown_s`` the state is HALF_OPEN and exactly the idempotent
    PROGRESS/METRICS probes pass — one healthy (fast) probe closes the
    breaker, a failed or still-slow one reopens it. All methods are
    called under the proxy's ``_state_lock``."""

    def __init__(self, fail_threshold: int = 3,
                 latency_s: Optional[float] = None,
                 cooldown_s: float = 5.0, ema_alpha: float = 0.4):
        self.fail_threshold = int(fail_threshold)
        self.latency_s = None if latency_s is None else float(latency_s)
        self.cooldown_s = float(cooldown_s)
        self.ema_alpha = float(ema_alpha)
        self.state = "closed"
        self.ema_s = 0.0
        self.fails = 0
        self.trips = 0
        self._opened_at = 0.0

    def allow(self, probe: bool) -> bool:
        if self.state == "closed":
            return True
        if self.state == "open":
            if time.monotonic() - self._opened_at < self.cooldown_s:
                return False
            self.state = "half_open"
        return probe                     # HALF_OPEN: probes only

    def _trip(self) -> None:
        if self.state != "open":
            self.state = "open"
            self.trips += 1
        self._opened_at = time.monotonic()

    def record(self, ok: bool, dt_s: float) -> None:
        if not ok:
            self.fails += 1
            if self.state == "half_open" or self.fails >= self.fail_threshold:
                self._trip()
            return
        self.fails = 0
        a = self.ema_alpha
        self.ema_s = dt_s if self.ema_s == 0.0 else \
            a * dt_s + (1.0 - a) * self.ema_s
        slow = self.latency_s is not None and self.ema_s > self.latency_s
        if self.state == "half_open":
            if slow:
                self._trip()             # answered, but still past budget
            else:
                self.state = "closed"
        elif self.state == "closed" and slow:
            self._trip()


def _retry_policy():
    from ...distributed.resilience.retry import RetryPolicy

    return RetryPolicy(max_attempts=2, base_delay=0.05, max_delay=0.2,
                       retry_on=(socket.timeout,))


class ProcReplica:
    """One spawned worker process + its control socket, driven from the
    fleet router's replica slot.

    >>> rep = ProcReplica(WorkerSpec(factory="pkg.mod:factory",
    ...                              journal_path=path), idx=0)
    >>> rep.submit(req); rep.step(); rep.close()
    """

    def __init__(self, spec: WorkerSpec, idx: int = 0, tracer=None,
                 trace_tags: Optional[dict] = None,
                 op_timeout_s: float = 60.0, spawn_timeout_s: float = 240.0,
                 heartbeat_s: Optional[float] = None,
                 stats: Optional[dict] = None,
                 transport: str = "tcp", chaos: bool = False,
                 breaker: Optional[dict] = None,
                 migrate_bw_bytes_per_s: float = 32.0 * 1024 * 1024):
        if transport not in ("tcp", "loopback"):
            raise ValueError(
                f"unknown transport {transport!r} (tcp | loopback)")
        self.idx = int(idx)
        self.spec = spec
        self.tracer = tracer
        self.trace_tags = dict(trace_tags or {})
        self.op_timeout_s = float(op_timeout_s)
        # MIGRATE_IN/OUT deadlines scale with payload bytes over this
        # assumed bandwidth: a legitimately big int8 chain must not read
        # as a wedged worker (or trip the breaker) under the flat budget
        self._migrate_bw = float(migrate_bw_bytes_per_s)
        self._breaker = None if breaker is None else CircuitBreaker(
            **dict(breaker))
        self.transport_retries = 0      # retryable timeouts, this peer
        self._idem_counter = 0
        self.stats = stats if stats is not None else {}
        self.requests: Dict[int, "object"] = {}   # rid -> caller Request
        self._done: Set[int] = set()
        self._finished: Dict[int, "object"] = {}
        self._submit_ts: Dict[int, float] = {}
        self._streaming: Set[int] = set()         # rids past first delta
        self._io_lock = threading.Lock()          # one req/reply in flight
        self._state_lock = threading.Lock()       # heartbeat-shared state
        self._catchup: Set[int] = set()
        self._ready: List[int] = []
        self._last_sig: tuple = ()
        # reply-piggybacked worker state: every change is driver-initiated
        # (submit/step/withdraw) or rides a step reply, so these are EXACT
        # between ops — router probes (load/progress/has_work, called per
        # submit and per tick) cost zero extra roundtrips
        self._load = 0
        self._has_work = False
        self._cap = [0, 0]              # [free slots, optimistic pages]
        self._open: Set[int] = set()    # rids submitted, not yet terminal
        self._seq = 0                   # request/reply matching (io_lock)
        self._hb_count = 0
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self.dead = False
        self.reaped = False
        self._fault_hook = None
        self._fault_cls = None

        self.process = None
        self._worker_thread: Optional[threading.Thread] = None
        self._spec_path = None
        deadline = time.monotonic() + float(spawn_timeout_s)
        if transport == "loopback":
            # in-process worker on a thread over a queue-pair transport:
            # same supervisor/journal/serve loop, no process spawn and no
            # cold jit — the fast arm for tests and chaos drills. "Process
            # death" is the transport closing; failover reads the journal
            # identically.
            drv_tr, wrk_tr = loopback_pair(
                a="driver", b=f"replica:{idx}:loopback")
            base = drv_tr
            self._worker_thread = threading.Thread(
                target=worker_thread_main, args=(spec, wrk_tr),
                name=f"pt-procfleet-worker-{idx}", daemon=True)
            self._worker_thread.start()
            self.stats["proc_spawned"] = \
                self.stats.get("proc_spawned", 0) + 1
        else:
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.bind(("127.0.0.1", 0))
            listener.listen(1)
            host, port = listener.getsockname()
            # the worker is a PLAIN subprocess (`python -m ...worker`): no
            # inherited interpreter state, no parent-__main__ re-execution —
            # the spec travels as a pickle file beside the journal, env vars
            # (JAX_PLATFORMS etc.) are applied before the child's first
            # import
            self._spec_path = spec.journal_path + ".spec"
            with open(self._spec_path, "wb") as f:
                f.write(pickle.dumps(spec))
            env = dict(os.environ)
            env["PYTHONPATH"] = os.pathsep.join(
                [p for p in sys.path if p]
                + [p for p in (env.get("PYTHONPATH") or "").split(os.pathsep)
                   if p])
            env.update({k: str(v) for k, v in (spec.env or {}).items()})
            self.process = subprocess.Popen(
                [sys.executable, "-m",
                 "paddle_tpu.inference.procfleet._spawn_main",
                 "--spec", self._spec_path, "--host", host,
                 "--port", str(port)],
                env=env, stdin=subprocess.DEVNULL)
            _track_worker(self.process.pid)
            self.stats["proc_spawned"] = \
                self.stats.get("proc_spawned", 0) + 1
            try:
                # short accept slices with a child liveness poll: a worker
                # that dies before connecting back (spec unpickle/import
                # failure) fails the spawn NOW, not after spawn_timeout_s
                while True:
                    if self.process.poll() is not None:
                        raise WireClosed(
                            f"worker exited rc={self.process.returncode} "
                            "before connecting back")
                    listener.settimeout(
                        min(0.5, max(0.05, deadline - time.monotonic())))
                    try:
                        conn, _ = listener.accept()
                        break
                    except socket.timeout:
                        if time.monotonic() >= deadline:
                            raise
            except (socket.timeout, WireClosed) as e:
                self.kill()
                self._reap()
                listener.close()
                raise WorkerDead(
                    f"PT-PROC-002: replica {idx} worker never connected "
                    f"back within {spawn_timeout_s:.0f}s "
                    f"({type(e).__name__}: {e})") from e
            finally:
                listener.close()
            base = TcpTransport(sock=conn)
        #: stable peer address for chaos matching, retry-stat tags and the
        #: breaker-state metric — ``replica:<i>@<transport endpoint>``
        self.peer = f"replica:{idx}@{base.peer}"
        self._tr: Transport = (ChaosTransport(base, peer=self.peer)
                               if chaos else base)
        try:
            self._tr.connect()
            hello = self._tr.recv_frame(
                timeout=max(0.1, deadline - time.monotonic()))
            if isinstance(base, TcpTransport):
                base.sock.settimeout(None)
        except (socket.timeout, ConnectionError, WireCorrupt) as e:
            # no handshake ever happened: nothing to wait for — kill and
            # reap immediately (the graceful wait is close()'s courtesy
            # for workers that acknowledged a SHUTDOWN)
            self.kill()
            self._reap()
            raise WorkerDead(
                f"PT-PROC-002: replica {idx} worker never said HELLO "
                f"within {spawn_timeout_s:.0f}s ({type(e).__name__}: {e})"
            ) from e
        if hello.mtype != "HELLO":
            self.kill()
            self._reap()
            raise WorkerDead(
                f"PT-PROC-002: replica {idx} opened with {hello.mtype}, "
                "not HELLO")
        self.worker_pid = int(hello.payload["pid"])
        self.metrics_port = hello.payload["metrics_port"]
        self._apply(hello.payload["state"])
        eng = dict(hello.payload["engine"])
        self.tier = eng.pop("tier", "serving")
        pending = eng.pop("pending", [])
        # in-replica mesh width (1 = unsharded worker; pre-mesh workers
        # omit the field) — read by the fleet collector's per-device-group
        # telemetry and by scale-out accounting (bench fleet ratio)
        eng.setdefault("mesh_tp", 1)
        # the HELLO width is the worker's GROUND TRUTH — it must match
        # what the driver asked for. A preset whose factory_kwargs carry
        # their own mesh while spec.mesh says otherwise would serve
        # silently at the wrong width; refuse it at spawn (PT-PROC-005).
        want_tp = int(spec.mesh or 1)
        if int(eng["mesh_tp"]) != want_tp:
            self.kill()
            self._reap()
            raise MeshMismatch(
                f"PT-PROC-005: replica {idx} worker HELLO reports engine "
                f"mesh_tp={int(eng['mesh_tp'])} but WorkerSpec.mesh asked "
                f"for tp={want_tp} — preset/config skew; fix the factory "
                f"kwargs or the fleet mesh before serving")
        #: the spec'd width, for capacity weighting after an elastic
        #: degrade (engine.mesh_tp then reports the SURVIVING width)
        self._spec_tp = want_tp
        #: the geometry surface FleetRouter reads (page_size for prefix
        #: chain keys, max_batch/max_queue for the brownout depth default)
        self.engine = SimpleNamespace(**eng)
        # worker spawned over a live journal: it replayed; we own the
        # caller-facing reconstructions (mirrors ServingSupervisor.requests)
        from ..recovery import _request_from

        for entry in pending:
            user = _request_from(entry["req"])
            user.output = [int(t) for t in entry["delivered"]]
            user._n_out = len(user.output)
            self.requests[user.rid] = user
        if heartbeat_s:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, args=(float(heartbeat_s),),
                name=f"pt-procfleet-hb-{idx}", daemon=True)
            self._hb_thread.start()

    # -- wire plumbing -----------------------------------------------------
    @property
    def metrics_url(self) -> Optional[str]:
        if self.metrics_port is None:
            return None
        return f"http://127.0.0.1:{self.metrics_port}/metrics"

    def _raise_error(self, reply: Message, what: str):
        etype = reply.payload["etype"]
        msg = reply.payload["msg"]
        from ..serving import EngineSaturated, RequestShed

        mapped = {"EngineSaturated": EngineSaturated,
                  "RequestShed": RequestShed, "ValueError": ValueError,
                  "KeyError": KeyError, "WireCorrupt": WireCorrupt}
        if etype == "KVChainCorrupt":
            from ..disagg import KVChainCorrupt

            raise KVChainCorrupt(msg)
        cls = mapped.get(etype)
        if cls is not None:
            raise cls(msg)
        # anything untyped out of a worker is replica death (a fatal
        # supervisor error past its recovery budget reports this way)
        self._note_dead()
        raise WorkerDead(
            f"PT-PROC-002: replica {self.idx} {what} failed fatally "
            f"({etype}: {msg})")

    def _record(self, ok: bool, dt_s: float) -> None:
        if self._breaker is None:
            return
        with self._state_lock:
            self._breaker.record(ok, dt_s)

    def _roundtrip(self, msg: Message, what: str,
                   timeout: Optional[float] = None,
                   expect: Tuple[str, ...] = (),
                   fatal_timeout: bool = True,
                   probe: bool = False) -> Message:
        timeout = self.op_timeout_s if timeout is None else timeout
        if self.dead:
            raise WorkerDead(
                f"PT-PROC-002: replica {self.idx} is already dead "
                f"({what} refused)")
        if self._breaker is not None:
            with self._state_lock:
                allowed = self._breaker.allow(probe)
            if not allowed:
                raise BreakerOpen(
                    f"PT-PROC-004: replica {self.idx} breaker is "
                    f"{self._breaker.state} — {what} routed around "
                    "(peer slow, not dead)")
        t0 = time.monotonic()
        try:
            with self._io_lock:
                # every request carries a sequence id the worker echoes:
                # when a probe times out and retries, the first attempt's
                # reply may still be in flight — replies carrying a stale
                # seq are drained and discarded instead of desyncing the
                # stream (a reply WITHOUT a seq matches anything: plain
                # peers in tests, and the pre-send HELLO)
                self._seq += 1
                seq = self._seq
                msg.payload["_seq"] = seq
                self._tr.send_frame(msg)
                deadline = time.monotonic() + timeout
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise socket.timeout(f"{what} reply deadline")
                    reply = self._tr.recv_frame(timeout=remaining)
                    got = reply.payload.pop("_seq", None)
                    if got is None or got == seq:
                        break
        except socket.timeout as e:
            self._record(False, time.monotonic() - t0)
            # a timeout with NO reply bytes consumed leaves the stream
            # aligned — the seq drain absorbs the late reply, so an
            # idempotent probe (or a hedged migration) may retry. A
            # timeout MID-frame leaves the position unusable: fatal
            # regardless of the retry policy.
            if not fatal_timeout and not getattr(e, "partial_read", False):
                with self._state_lock:
                    self.transport_retries += 1
                raise        # retryable: retry_call / the hedge owns it
            self._note_dead()
            raise WorkerDead(
                f"PT-PROC-003: replica {self.idx} {what} timed out after "
                f"{timeout:.1f}s — worker presumed wedged/dead") from e
        except WireCorrupt as e:
            # damaged frame on a live stream: the position is untrusted
            # from here on — this connection (and so this replica) is done
            self._note_dead()
            raise WorkerDead(
                f"PT-PROC-002: replica {self.idx} wire corrupt during "
                f"{what}: {e}") from e
        except (WireClosed, OSError) as e:
            self._note_dead()
            raise WorkerDead(
                f"PT-PROC-002: replica {self.idx} worker gone during "
                f"{what}: {e}") from e
        # the worker ANSWERED — even an ERROR reply means the peer is
        # alive and timely; only wire-level outcomes feed the breaker
        self._record(True, time.monotonic() - t0)
        if reply.mtype == "ERROR":
            self._raise_error(reply, what)
        if expect and reply.mtype not in expect:
            self._note_dead()
            raise WorkerDead(
                f"PT-PROC-002: replica {self.idx} answered {what} with "
                f"{reply.mtype}, wanted {expect} — protocol desync")
        return reply

    def _note_dead(self) -> None:
        with self._state_lock:
            self.dead = True

    # -- replica surface (what FleetRouter consumes) -----------------------
    def submit(self, req, resume: bool = False) -> int:
        # idempotence key: unique per LOGICAL admission (a later,
        # legitimate re-admit of the same rid gets a fresh key), constant
        # across duplicate deliveries of this one frame — a chaos-doubled
        # SUBMIT answers from the worker's idem cache instead of
        # double-admitting
        with self._state_lock:
            self._idem_counter += 1
            idem = f"sub:{self.idx}:{self._idem_counter}"
        payload = {"req": _admit(req), "resume": bool(resume),
                   "delivered": [int(t) for t in req.output] if resume
                   else [], "idem": idem}
        if resume and self.tracer is not None:
            self.tracer.mark_recovered(req.rid, len(req.output),
                                       self._tags(req))
        try:
            reply = self._roundtrip(Message("SUBMIT", payload), "submit",
                                    expect=("SUBMITTED",))
        except BreakerOpen as e:
            # to the router an OPEN breaker is indistinguishable from a
            # full engine: same typed refusal, same route-elsewhere
            from ..serving import EngineSaturated

            raise EngineSaturated(str(e)) from e
        self._apply({"load": reply.payload["load"], "has_work": True})
        req._n_out = len(req.output)
        with self._state_lock:
            self.requests[req.rid] = req
            self._done.discard(req.rid)
            self._open.add(req.rid)
            if resume and req.output:
                self._catchup.add(req.rid)
            self._submit_ts[req.rid] = time.monotonic()
        if self.tracer is not None:
            self.tracer.submit(req.rid, len(req.prompt),
                               req.max_new_tokens, self._tags(req))
        return req.rid

    def step(self) -> None:
        if self._fault_hook is None:
            from ...distributed.resilience.faults import (FaultInjected,
                                                          maybe_inject)

            self._fault_hook = maybe_inject
            self._fault_cls = FaultInjected
        try:
            self._fault_hook("fleet.proc_kill",
                             f"replica:{self.idx}:pid:{self.worker_pid}")
        except self._fault_cls:
            # the fault is REAL here: SIGKILL the worker process — the
            # step below then fails on the dead socket and the router's
            # journal-backed failover takes over (the drill's point)
            self.kill()
        try:
            reply = self._roundtrip(Message("STEP"), "step",
                                    expect=("TOKENS",))
        except BreakerOpen:
            # skip the tick: the worker keeps its in-flight state and the
            # streams resume when a HALF_OPEN probe closes the breaker —
            # deliberately NOT death, nothing fails over
            return
        self._apply(reply.payload)

    def _apply(self, p: dict) -> None:
        # one lock over the whole reply application: the heartbeat thread
        # probes PROGRESS (and applies its payload) while the driver — or
        # a parallel_step replica thread — applies STEP replies; the
        # tracer's own lock is always taken INSIDE this one, never the
        # reverse, so the order is acyclic
        with self._state_lock:
            if "behind" in p:
                self._catchup = {int(r) for r in p["behind"]}
            if "ready" in p:
                self._ready = [int(r) for r in p["ready"]]
            if "sig" in p:
                self._last_sig = tuple(p["sig"])
            if "load" in p:
                self._load = int(p["load"])
            if "has_work" in p:
                self._has_work = bool(p["has_work"])
            if "cap" in p:
                self._cap = [int(c) for c in p["cap"]]
            if "mesh_tp" in p:
                # the worker's elastic degrade "re-HELLO": its engine
                # resharded to a narrower surviving width and it kept
                # serving — mirror the new width (capacity weighting,
                # telemetry) instead of treating the replica as dead
                new_tp = int(p["mesh_tp"])
                if new_tp != int(getattr(self.engine, "mesh_tp", 1)):
                    self.engine.mesh_tp = new_tp
                    self.stats["proc_mesh_degrades"] = \
                        self.stats.get("proc_mesh_degrades", 0) + 1
            for up in p.get("updates", ()):
                rid = int(up["rid"])
                user = self.requests.get(rid)
                if user is None:
                    continue
                new = [int(t) for t in up["toks"]]
                if new:
                    user.output.extend(new)
                    user._n_out = len(user.output)
                    self._stamp_progress(rid, user)
                if up["done"] and rid not in self._done:
                    user.done = True
                    user.failed = bool(up["failed"])
                    user.error = up.get("error")
                    self._done.add(rid)
                    self._finished[rid] = user
                    self._catchup.discard(rid)
                    self._open.discard(rid)
                    self._submit_ts.pop(rid, None)
                    self._streaming.discard(rid)
                    if self.tracer is not None:
                        self.tracer.finish(rid, len(user.output),
                                           failed=user.failed,
                                           error=user.error,
                                           tags=self._tags(user))

    def _stamp_progress(self, rid: int, user) -> None:
        if self.tracer is None:
            return
        tags = self._tags(user)
        if rid not in self._streaming:
            self._streaming.add(rid)
            wait = time.monotonic() - self._submit_ts.get(
                rid, time.monotonic())
            self.tracer.admit(rid, queue_wait_s=max(0.0, wait), tags=tags)
            self.tracer.first_token(rid, tags=tags)
        self.tracer.tokens(rid, len(user.output), tags=tags)

    def _tags(self, user) -> dict:
        tags = dict(self.trace_tags)
        tags.setdefault("replica", self.idx)
        if getattr(user, "tenant", None) is not None:
            tags.setdefault("tenant", user.tenant)
        return tags

    def _progress_probe(self, what: str) -> dict:
        from ...distributed.resilience.retry import RetryError, retry_call

        try:
            # stats tagged BY PEER: `scrape_metrics` / RetryStats then
            # show which replica's wire is flaky, not just that one is
            reply = retry_call(self._roundtrip, Message("PROGRESS"), what,
                               expect=("PROGRESS_REPLY",),
                               fatal_timeout=False, probe=True,
                               policy=_retry_policy(),
                               what=f"procfleet.{what}@{self.peer}")
        except (socket.timeout, RetryError) as e:
            self._note_dead()
            raise WorkerDead(
                f"PT-PROC-003: replica {self.idx} {what} probe kept "
                f"timing out — worker presumed wedged/dead") from e
        p = reply.payload
        self._apply(p)
        return p

    def progress(self) -> tuple:
        """The fleet heartbeat marker (mirrors
        ``ServingSupervisor.progress``): changes whenever any worker-side
        stream advances, a request completes, the engine rebuilds, or the
        load changes. Served from reply-piggybacked state — the marker
        refreshes with every STEP reply, so a worker that keeps stepping
        without advancing any stream still trips the router's staleness
        TTL, and one that stops answering dies on the STEP timeout."""
        with self._state_lock:
            return self._last_sig

    def load(self) -> int:
        with self._state_lock:
            return self._load

    def has_work(self) -> bool:
        with self._state_lock:
            return bool(self._open) or self._has_work

    def behind(self, rid: int) -> bool:
        with self._state_lock:
            return rid in self._catchup

    def capacity(self) -> List[int]:
        """``[free slots, optimistic free pages]`` from the latest
        reply — the tiered router's pre-handoff capacity gate (a chain
        must never be retired toward a worker that cannot hold it)."""
        with self._state_lock:
            return list(self._cap)

    def capacity_weight(self) -> float:
        """Relative serving capacity vs the width this replica was
        spawned at: 1.0 until an elastic mesh degrade, then
        ``surviving_tp / spec_tp`` — the fleet router divides load by it
        so a shrunken replica reads proportionally busier and new work
        drifts toward full-width survivors WITHOUT failover churn
        (docs/RESILIENCE.md "Elastic serving mesh")."""
        with self._state_lock:
            tp = int(getattr(self.engine, "mesh_tp", 1))
        return max(tp, 1) / max(self._spec_tp, 1)

    def migration_ready(self) -> List[int]:
        """rids whose prefill finished on this worker (populated from the
        latest STEP reply) — the tiered router's migration pump input."""
        with self._state_lock:
            return list(self._ready)

    def withdraw(self, rid: int) -> Optional[dict]:
        reply = self._roundtrip(Message("WITHDRAW", {"rid": int(rid)}),
                                "withdraw", expect=("WITHDRAWN",))
        self._apply({"load": reply.payload["load"]})
        rec = reply.payload["rec"]
        if rec is not None:
            with self._state_lock:
                self.requests.pop(rid, None)
                self._done.discard(rid)
                self._open.discard(rid)
                self._submit_ts.pop(rid, None)
        return rec

    def drain_mark(self) -> int:
        """Tell the worker to refuse NEW (non-resumed) admissions — defense
        in depth under a router drain; returns the worker's in-flight
        load."""
        reply = self._roundtrip(Message("DRAIN"), "drain",
                                expect=("DRAINING",))
        self._apply({"load": reply.payload["load"]})
        return int(reply.payload["load"])

    def metrics_text(self) -> str:
        """The worker registry's Prometheus dump over the control socket
        (the HTTP endpoint at :attr:`metrics_url` serves the same text)."""
        from ...distributed.resilience.retry import RetryError, retry_call

        try:
            reply = retry_call(self._roundtrip, Message("METRICS"),
                               "metrics", expect=("METRICS_TEXT",),
                               fatal_timeout=False, probe=True,
                               policy=_retry_policy(),
                               what=f"procfleet.metrics@{self.peer}")
        except BreakerOpen:
            return ""        # scrape must not break over a tripped peer
        except (socket.timeout, RetryError) as e:
            self._note_dead()
            raise WorkerDead(
                f"PT-PROC-003: replica {self.idx} metrics probe kept "
                "timing out — worker presumed wedged/dead") from e
        return reply.payload["text"]

    def finished(self) -> Dict[int, "object"]:
        with self._state_lock:
            out, self._finished = self._finished, {}
        return out

    # -- tiered migration over the wire ------------------------------------
    def _migration_timeout(self, nbytes: int) -> float:
        """Per-op deadline SIZED TO THE PAYLOAD: the flat budget plus the
        wire time those bytes take at the assumed bandwidth — a large int8
        chain must not read as a wedged worker under a flat timeout, and a
        small one must not get a big chain's slack."""
        return self.op_timeout_s + float(max(0, nbytes)) / self._migrate_bw

    def _chain_bytes_bound(self) -> int:
        """Upper bound on any exported chain's size, from the HELLO
        geometry (layers x K/V x heads x page x head_dim x itemsize x max
        pages); 0 when the worker has no paged pool (flat timeout)."""
        eng = self.engine
        layers = getattr(eng, "layers", None)
        if layers is None:
            return 0
        dtype = str(getattr(eng, "dtype", ""))
        itemsize = 1 if "int8" in dtype else \
            2 if ("bfloat16" in dtype or "float16" in dtype) else 4
        return (int(layers) * 2 * int(eng.kvh) * int(eng.page_size)
                * int(eng.hd) * itemsize * int(eng.maxp))

    def export_migration(self, rid: int) -> Tuple[dict, bytes]:
        """MIGRATE_OUT: the worker flushes, exports rid's KV chain,
        journals ``migr-kv`` and releases the slot; returns
        ``(header-lite, artifact bytes)``. After this returns, the rid is
        no longer this worker's responsibility."""
        reply = self._roundtrip(
            Message("MIGRATE_OUT", {"rid": int(rid)}), "migrate_out",
            timeout=self._migration_timeout(self._chain_bytes_bound()),
            expect=("CHAIN",))
        # deltas the export's flush surfaced land BEFORE ownership moves:
        # the caller's delivered prefix now equals the artifact's
        self._apply({"updates": reply.payload["updates"]})
        with self._state_lock:
            self.requests.pop(rid, None)
            self._open.discard(rid)
            self._submit_ts.pop(rid, None)
        return dict(reply.payload), reply.blob

    def import_migration(self, user, artifact: bytes,
                         idem: Optional[str] = None) -> int:
        """MIGRATE_IN: splice an exported chain into this worker and
        resume decode at the recorded position. Raises ``KVChainCorrupt``
        / ``EngineSaturated`` exactly like the in-process splice.

        The timeout is sized to ``len(artifact)`` and is NOT fatal: a
        clean deadline (no reply bytes consumed) raises ``socket.timeout``
        with the replica alive so the router can HEDGE the splice onto
        another worker — the seq drain absorbs this attempt's late
        SPLICED, and ``idem`` (stable across attempts at one target) keeps
        a chaos-duplicated frame from double-splicing."""
        payload = {"req": _admit(user),
                   "delivered": [int(t) for t in user.output]}
        if idem is not None:
            payload["idem"] = str(idem)
        reply = self._roundtrip(
            Message("MIGRATE_IN", payload, blob=artifact),
            "migrate_in",
            timeout=self._migration_timeout(len(artifact)),
            expect=("SPLICED",), fatal_timeout=False)
        user._n_out = len(user.output)
        with self._state_lock:
            self.requests[user.rid] = user
            self._done.discard(user.rid)
            self._open.add(user.rid)
            self._submit_ts.setdefault(user.rid, time.monotonic())
            # the prefill side already stamped admit/first_token — a
            # migrated stream continues, it does not re-admit
            self._streaming.add(user.rid)
        return int(reply.payload["rid"])

    def migrate_cancel(self, rid: int, digest: str) -> bool:
        """Roll back a hedge-loser's splice: if ``rid`` is still live on
        this worker from a MIGRATE_IN carrying ``digest``, the worker
        retires it (journal ``migr-kv``, pages decref'd — its allocator
        ends where it started). Returns whether anything was rolled
        back. Best-effort at call sites: the WINNER is already placed."""
        reply = self._roundtrip(
            Message("MIGRATE_CANCEL",
                    {"rid": int(rid), "digest": str(digest)}),
            "migrate_cancel", expect=("CANCELLED",))
        return bool(reply.payload["rolled_back"])

    def breaker_state(self) -> str:
        """``closed`` / ``open`` / ``half_open`` (``closed`` when no
        breaker is configured) — the ``pt_transport_breaker_state``
        gauge and the router's hedge-target filter read this."""
        with self._state_lock:
            return "closed" if self._breaker is None else \
                self._breaker.state

    # -- lifecycle ---------------------------------------------------------
    def _alive(self) -> bool:
        if self.process is None:
            t = self._worker_thread
            return t is not None and t.is_alive()
        return self.process.poll() is None

    def _wait(self, timeout: float) -> bool:
        if self.process is None:
            t = self._worker_thread
            if t is None:
                return True
            t.join(timeout=timeout)
            return not t.is_alive()
        try:
            self.process.wait(timeout=timeout)
            return True
        except subprocess.TimeoutExpired:
            return False

    def kill(self) -> None:
        """SIGKILL the worker — real process death (fault drills; also the
        wedged-worker arm of ``abandon``). In loopback mode the kill is
        slamming the transport shut: the worker thread's serve loop reads
        WireClosed, abandons (no flush) and exits — failover reads the
        journal identically to a killed process."""
        if self.process is None:
            try:
                self._tr.close()
            except (OSError, AttributeError):
                pass
            self._wait(5.0)
            self._note_dead()
            return
        if self._alive():
            os.kill(self.process.pid, signal.SIGKILL)
            self._wait(10.0)
        self._note_dead()

    def close(self) -> None:
        """Graceful reap: SHUTDOWN (worker flushes + closes its journal),
        wait for exit, reap. Falls back to a kill if the worker does not
        comply in time."""
        if self.reaped:
            return
        acked = False
        if not self.dead and self._alive():
            try:
                self._roundtrip(Message("SHUTDOWN"), "shutdown",
                                timeout=self.op_timeout_s, expect=("BYE",))
                acked = True
            except (WorkerDead, WireCorrupt, BreakerOpen):
                pass    # an OPEN breaker at teardown falls back to kill
        if not acked:
            # the worker never acknowledged a shutdown: waiting for a
            # voluntary exit is a dead 5s — kill like abandon() does
            self.kill()
        self._reap(force=True)

    def abandon(self) -> None:
        """Ungraceful release (router ``_mark_dead``): no SHUTDOWN, no
        flush, no grace — SIGKILL whatever is left and reap immediately
        (a wedged worker must not stall the fleet's failover for a
        termination courtesy it will never answer). The on-disk journal
        is what failover trusts, exactly like the in-process path."""
        self.kill()
        self._reap()

    def _reap(self, force: bool = False) -> None:
        if self.reaped:
            return
        self._hb_stop.set()
        self._note_dead()
        if self.process is not None:
            if self._alive() and not self._wait(5.0) and force:
                self.process.terminate()
                if not self._wait(5.0):
                    os.kill(self.process.pid, signal.SIGKILL)
                    self._wait(5.0)
            _untrack_worker(self.process.pid)
        try:
            self._tr.close()
        except (OSError, AttributeError):
            pass
        if self.process is None:
            # thread-worker: the transport close above IS the kill; give
            # the serve loop a beat to unwind
            self._wait(5.0)
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
        if self._spec_path is not None:
            try:
                os.unlink(self._spec_path)
            except OSError:
                pass
        self.reaped = True
        with self._state_lock:
            # stats is shared with the heartbeat/step threads via _apply's
            # mesh_tp re-HELLO bump, which runs under this lock too
            self.stats["proc_reaped"] = \
                self.stats.get("proc_reaped", 0) + 1

    def heartbeat_count(self) -> int:
        with self._state_lock:
            return self._hb_count

    def _heartbeat_loop(self, interval_s: float) -> None:
        while not self._hb_stop.wait(interval_s):
            if self.dead:
                return
            try:
                self._progress_probe("heartbeat")
            except BreakerOpen:
                continue     # cooling down: routed around, not dead
            except Exception:  # noqa: BLE001 — probe failure = death signal
                self._note_dead()
                return
            with self._state_lock:
                self._hb_count += 1


def _admit(req) -> dict:
    from ..recovery import _admit_record

    return _admit_record(req)
