"""Driver-side proxy for one replica worker process.

:class:`ProcReplica` conforms to the replica surface
:class:`~paddle_tpu.inference.fleet.FleetRouter` consumes — submit / step /
finished / load / progress / behind / withdraw / close / abandon plus the
``.engine`` geometry namespace — so the router, the tiered router and the
SLO autoscaler drive a process-backed fleet through the code paths they
already have (docs/SERVING.md "Process fleet").

Failure semantics (the reason this module exists):

- **Death is process death.** A worker that SIGKILLs, segfaults or raises
  past its recovery budget surfaces here as :class:`WorkerDead`
  (**PT-PROC-002**) out of ``step()`` — the router's existing
  per-replica exception boundary marks the replica dead and runs its
  JOURNAL-BACKED failover against the worker's on-disk journal (shared
  directory, unchanged ``RequestJournal`` format). The proxy holds the
  caller-facing ``Request`` objects, so re-admitted streams continue
  byte-identically on survivors exactly like the in-process fleet.
- **Timeouts are typed.** Every wire op runs under a per-op timeout; a
  worker that stops answering is indistinguishable from a dead one and
  raises :class:`WorkerDead` naming the op (PT-PROC-003 in the message).
  Idempotent probes (PROGRESS / METRICS) additionally ride
  ``retry_call`` (distributed/resilience/retry.py) so one dropped
  datagram-worth of scheduling noise does not kill a healthy replica;
  mutating ops (SUBMIT/STEP/WITHDRAW) are deliberately single-shot —
  blind retry could double-apply.
- **Heartbeats.** An optional daemon thread probes PROGRESS every
  ``heartbeat_s`` so death is noticed between driver steps and
  ``pt_procfleet_heartbeats_total`` moves; the router's progress-staleness
  TTL rides the same marker it always has.

Trace stamps are made DRIVER-SIDE from the token deltas (submit → admit →
first_token → tokens → finish), on the driver's tracer and therefore on
its clock — virtual-clock replay (observability/workload.py) and the SLO
monitor see process replicas exactly like in-process ones.
"""

from __future__ import annotations

import atexit
import os
import pickle
import signal
import socket
import subprocess
import sys
import threading
import time
from types import SimpleNamespace
from typing import Dict, List, Optional, Set, Tuple

from .wire import Message, WireClosed, WireCorrupt, recv_msg, send_msg
from .worker import WorkerSpec

__all__ = ["ProcReplica", "WorkerDead"]

# every live worker Popen, so an exiting driver never leaks processes —
# guarded: ProcReplica spawns/reaps from driver threads while atexit runs
# on the main thread
_LIVE_LOCK = threading.Lock()
_LIVE_WORKERS: Set[int] = set()          # pids
_ATEXIT_ARMED = [False]


def _kill_leftovers() -> None:
    with _LIVE_LOCK:
        pids = list(_LIVE_WORKERS)
        _LIVE_WORKERS.clear()
    for pid in pids:
        try:
            os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def _track_worker(pid: int) -> None:
    with _LIVE_LOCK:
        if not _ATEXIT_ARMED[0]:
            atexit.register(_kill_leftovers)
            _ATEXIT_ARMED[0] = True
        _LIVE_WORKERS.add(pid)


def _untrack_worker(pid: int) -> None:
    with _LIVE_LOCK:
        _LIVE_WORKERS.discard(pid)


class WorkerDead(RuntimeError):
    """PT-PROC-002: the replica worker process is gone (SIGKILL, crash,
    fatal supervisor error) or stopped answering within the op timeout —
    the router fails its work over from the on-disk journal."""


def _retry_policy():
    from ...distributed.resilience.retry import RetryPolicy

    return RetryPolicy(max_attempts=2, base_delay=0.05, max_delay=0.2,
                       retry_on=(socket.timeout,))


class ProcReplica:
    """One spawned worker process + its control socket, driven from the
    fleet router's replica slot.

    >>> rep = ProcReplica(WorkerSpec(factory="pkg.mod:factory",
    ...                              journal_path=path), idx=0)
    >>> rep.submit(req); rep.step(); rep.close()
    """

    def __init__(self, spec: WorkerSpec, idx: int = 0, tracer=None,
                 trace_tags: Optional[dict] = None,
                 op_timeout_s: float = 60.0, spawn_timeout_s: float = 240.0,
                 heartbeat_s: Optional[float] = None,
                 stats: Optional[dict] = None):
        self.idx = int(idx)
        self.spec = spec
        self.tracer = tracer
        self.trace_tags = dict(trace_tags or {})
        self.op_timeout_s = float(op_timeout_s)
        self.stats = stats if stats is not None else {}
        self.requests: Dict[int, "object"] = {}   # rid -> caller Request
        self._done: Set[int] = set()
        self._finished: Dict[int, "object"] = {}
        self._submit_ts: Dict[int, float] = {}
        self._streaming: Set[int] = set()         # rids past first delta
        self._io_lock = threading.Lock()          # one req/reply in flight
        self._state_lock = threading.Lock()       # heartbeat-shared state
        self._catchup: Set[int] = set()
        self._ready: List[int] = []
        self._last_sig: tuple = ()
        # reply-piggybacked worker state: every change is driver-initiated
        # (submit/step/withdraw) or rides a step reply, so these are EXACT
        # between ops — router probes (load/progress/has_work, called per
        # submit and per tick) cost zero extra roundtrips
        self._load = 0
        self._has_work = False
        self._cap = [0, 0]              # [free slots, optimistic pages]
        self._open: Set[int] = set()    # rids submitted, not yet terminal
        self._seq = 0                   # request/reply matching (io_lock)
        self._hb_count = 0
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self.dead = False
        self.reaped = False
        self._fault_hook = None
        self._fault_cls = None

        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        host, port = listener.getsockname()
        # the worker is a PLAIN subprocess (`python -m ...worker`): no
        # inherited interpreter state, no parent-__main__ re-execution —
        # the spec travels as a pickle file beside the journal, env vars
        # (JAX_PLATFORMS etc.) are applied before the child's first import
        self._spec_path = spec.journal_path + ".spec"
        with open(self._spec_path, "wb") as f:
            f.write(pickle.dumps(spec))
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [p for p in sys.path if p]
            + [p for p in (env.get("PYTHONPATH") or "").split(os.pathsep)
               if p])
        env.update({k: str(v) for k, v in (spec.env or {}).items()})
        self.process = subprocess.Popen(
            [sys.executable, "-m",
             "paddle_tpu.inference.procfleet._spawn_main",
             "--spec", self._spec_path, "--host", host,
             "--port", str(port)],
            env=env, stdin=subprocess.DEVNULL)
        _track_worker(self.process.pid)
        self.stats["proc_spawned"] = self.stats.get("proc_spawned", 0) + 1
        try:
            deadline = time.monotonic() + float(spawn_timeout_s)
            # short accept slices with a child liveness poll: a worker
            # that dies before connecting back (spec unpickle/import
            # failure) fails the spawn NOW, not after spawn_timeout_s
            while True:
                if self.process.poll() is not None:
                    raise WireClosed(
                        f"worker exited rc={self.process.returncode} "
                        "before connecting back")
                listener.settimeout(
                    min(0.5, max(0.05, deadline - time.monotonic())))
                try:
                    self._sock, _ = listener.accept()
                    break
                except socket.timeout:
                    if time.monotonic() >= deadline:
                        raise
            hello = recv_msg(
                self._sock,
                timeout=max(0.1, deadline - time.monotonic()))
            self._sock.settimeout(None)
        except (socket.timeout, WireClosed, WireCorrupt) as e:
            # no handshake ever happened: nothing to wait for — kill and
            # reap immediately (the graceful wait is close()'s courtesy
            # for workers that acknowledged a SHUTDOWN)
            self.kill()
            self._reap()
            listener.close()
            raise WorkerDead(
                f"PT-PROC-002: replica {idx} worker never said HELLO "
                f"within {spawn_timeout_s:.0f}s ({type(e).__name__}: {e})"
            ) from e
        finally:
            listener.close()
        if hello.mtype != "HELLO":
            self.kill()
            self._reap()
            raise WorkerDead(
                f"PT-PROC-002: replica {idx} opened with {hello.mtype}, "
                "not HELLO")
        self.worker_pid = int(hello.payload["pid"])
        self.metrics_port = hello.payload["metrics_port"]
        self._apply(hello.payload["state"])
        eng = dict(hello.payload["engine"])
        self.tier = eng.pop("tier", "serving")
        pending = eng.pop("pending", [])
        #: the geometry surface FleetRouter reads (page_size for prefix
        #: chain keys, max_batch/max_queue for the brownout depth default)
        self.engine = SimpleNamespace(**eng)
        # worker spawned over a live journal: it replayed; we own the
        # caller-facing reconstructions (mirrors ServingSupervisor.requests)
        from ..recovery import _request_from

        for entry in pending:
            user = _request_from(entry["req"])
            user.output = [int(t) for t in entry["delivered"]]
            user._n_out = len(user.output)
            self.requests[user.rid] = user
        if heartbeat_s:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, args=(float(heartbeat_s),),
                name=f"pt-procfleet-hb-{idx}", daemon=True)
            self._hb_thread.start()

    # -- wire plumbing -----------------------------------------------------
    @property
    def metrics_url(self) -> Optional[str]:
        if self.metrics_port is None:
            return None
        return f"http://127.0.0.1:{self.metrics_port}/metrics"

    def _raise_error(self, reply: Message, what: str):
        etype = reply.payload["etype"]
        msg = reply.payload["msg"]
        from ..serving import EngineSaturated, RequestShed

        mapped = {"EngineSaturated": EngineSaturated,
                  "RequestShed": RequestShed, "ValueError": ValueError,
                  "KeyError": KeyError, "WireCorrupt": WireCorrupt}
        if etype == "KVChainCorrupt":
            from ..disagg import KVChainCorrupt

            raise KVChainCorrupt(msg)
        cls = mapped.get(etype)
        if cls is not None:
            raise cls(msg)
        # anything untyped out of a worker is replica death (a fatal
        # supervisor error past its recovery budget reports this way)
        self._note_dead()
        raise WorkerDead(
            f"PT-PROC-002: replica {self.idx} {what} failed fatally "
            f"({etype}: {msg})")

    def _roundtrip(self, msg: Message, what: str,
                   timeout: Optional[float] = None,
                   expect: Tuple[str, ...] = (),
                   fatal_timeout: bool = True) -> Message:
        timeout = self.op_timeout_s if timeout is None else timeout
        if self.dead:
            raise WorkerDead(
                f"PT-PROC-002: replica {self.idx} is already dead "
                f"({what} refused)")
        try:
            with self._io_lock:
                # every request carries a sequence id the worker echoes:
                # when a probe times out and retries, the first attempt's
                # reply may still be in flight — replies carrying a stale
                # seq are drained and discarded instead of desyncing the
                # stream (a reply WITHOUT a seq matches anything: plain
                # peers in tests, and the pre-send HELLO)
                self._seq += 1
                seq = self._seq
                msg.payload["_seq"] = seq
                send_msg(self._sock, msg)
                deadline = time.monotonic() + timeout
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise socket.timeout(f"{what} reply deadline")
                    reply = recv_msg(self._sock, timeout=remaining)
                    got = reply.payload.pop("_seq", None)
                    if got is None or got == seq:
                        break
        except socket.timeout as e:
            # a timeout with NO reply bytes consumed leaves the stream
            # aligned — the seq drain absorbs the late reply, so an
            # idempotent probe may retry. A timeout MID-frame leaves the
            # position unusable: fatal regardless of the retry policy.
            if not fatal_timeout and not getattr(e, "partial_read", False):
                raise        # idempotent probe: retry_call owns the retry
            self._note_dead()
            raise WorkerDead(
                f"PT-PROC-003: replica {self.idx} {what} timed out after "
                f"{timeout:.1f}s — worker presumed wedged/dead") from e
        except WireCorrupt as e:
            # damaged frame on a live stream: the position is untrusted
            # from here on — this connection (and so this replica) is done
            self._note_dead()
            raise WorkerDead(
                f"PT-PROC-002: replica {self.idx} wire corrupt during "
                f"{what}: {e}") from e
        except (WireClosed, OSError) as e:
            self._note_dead()
            raise WorkerDead(
                f"PT-PROC-002: replica {self.idx} worker gone during "
                f"{what}: {e}") from e
        if reply.mtype == "ERROR":
            self._raise_error(reply, what)
        if expect and reply.mtype not in expect:
            self._note_dead()
            raise WorkerDead(
                f"PT-PROC-002: replica {self.idx} answered {what} with "
                f"{reply.mtype}, wanted {expect} — protocol desync")
        return reply

    def _note_dead(self) -> None:
        with self._state_lock:
            self.dead = True

    # -- replica surface (what FleetRouter consumes) -----------------------
    def submit(self, req, resume: bool = False) -> int:
        payload = {"req": _admit(req), "resume": bool(resume),
                   "delivered": [int(t) for t in req.output] if resume
                   else []}
        if resume and self.tracer is not None:
            self.tracer.mark_recovered(req.rid, len(req.output),
                                       self._tags(req))
        reply = self._roundtrip(Message("SUBMIT", payload), "submit",
                                expect=("SUBMITTED",))
        self._apply({"load": reply.payload["load"], "has_work": True})
        req._n_out = len(req.output)
        with self._state_lock:
            self.requests[req.rid] = req
            self._done.discard(req.rid)
            self._open.add(req.rid)
            if resume and req.output:
                self._catchup.add(req.rid)
            self._submit_ts[req.rid] = time.monotonic()
        if self.tracer is not None:
            self.tracer.submit(req.rid, len(req.prompt),
                               req.max_new_tokens, self._tags(req))
        return req.rid

    def step(self) -> None:
        if self._fault_hook is None:
            from ...distributed.resilience.faults import (FaultInjected,
                                                          maybe_inject)

            self._fault_hook = maybe_inject
            self._fault_cls = FaultInjected
        try:
            self._fault_hook("fleet.proc_kill",
                             f"replica:{self.idx}:pid:{self.worker_pid}")
        except self._fault_cls:
            # the fault is REAL here: SIGKILL the worker process — the
            # step below then fails on the dead socket and the router's
            # journal-backed failover takes over (the drill's point)
            self.kill()
        reply = self._roundtrip(Message("STEP"), "step",
                                expect=("TOKENS",))
        self._apply(reply.payload)

    def _apply(self, p: dict) -> None:
        # one lock over the whole reply application: the heartbeat thread
        # probes PROGRESS (and applies its payload) while the driver — or
        # a parallel_step replica thread — applies STEP replies; the
        # tracer's own lock is always taken INSIDE this one, never the
        # reverse, so the order is acyclic
        with self._state_lock:
            if "behind" in p:
                self._catchup = {int(r) for r in p["behind"]}
            if "ready" in p:
                self._ready = [int(r) for r in p["ready"]]
            if "sig" in p:
                self._last_sig = tuple(p["sig"])
            if "load" in p:
                self._load = int(p["load"])
            if "has_work" in p:
                self._has_work = bool(p["has_work"])
            if "cap" in p:
                self._cap = [int(c) for c in p["cap"]]
            for up in p.get("updates", ()):
                rid = int(up["rid"])
                user = self.requests.get(rid)
                if user is None:
                    continue
                new = [int(t) for t in up["toks"]]
                if new:
                    user.output.extend(new)
                    user._n_out = len(user.output)
                    self._stamp_progress(rid, user)
                if up["done"] and rid not in self._done:
                    user.done = True
                    user.failed = bool(up["failed"])
                    user.error = up.get("error")
                    self._done.add(rid)
                    self._finished[rid] = user
                    self._catchup.discard(rid)
                    self._open.discard(rid)
                    self._submit_ts.pop(rid, None)
                    self._streaming.discard(rid)
                    if self.tracer is not None:
                        self.tracer.finish(rid, len(user.output),
                                           failed=user.failed,
                                           error=user.error,
                                           tags=self._tags(user))

    def _stamp_progress(self, rid: int, user) -> None:
        if self.tracer is None:
            return
        tags = self._tags(user)
        if rid not in self._streaming:
            self._streaming.add(rid)
            wait = time.monotonic() - self._submit_ts.get(
                rid, time.monotonic())
            self.tracer.admit(rid, queue_wait_s=max(0.0, wait), tags=tags)
            self.tracer.first_token(rid, tags=tags)
        self.tracer.tokens(rid, len(user.output), tags=tags)

    def _tags(self, user) -> dict:
        tags = dict(self.trace_tags)
        tags.setdefault("replica", self.idx)
        if getattr(user, "tenant", None) is not None:
            tags.setdefault("tenant", user.tenant)
        return tags

    def _progress_probe(self, what: str) -> dict:
        from ...distributed.resilience.retry import RetryError, retry_call

        try:
            reply = retry_call(self._roundtrip, Message("PROGRESS"), what,
                               expect=("PROGRESS_REPLY",),
                               fatal_timeout=False,
                               policy=_retry_policy(),
                               what=f"procfleet.{what}")
        except (socket.timeout, RetryError) as e:
            self._note_dead()
            raise WorkerDead(
                f"PT-PROC-003: replica {self.idx} {what} probe kept "
                f"timing out — worker presumed wedged/dead") from e
        p = reply.payload
        self._apply(p)
        return p

    def progress(self) -> tuple:
        """The fleet heartbeat marker (mirrors
        ``ServingSupervisor.progress``): changes whenever any worker-side
        stream advances, a request completes, the engine rebuilds, or the
        load changes. Served from reply-piggybacked state — the marker
        refreshes with every STEP reply, so a worker that keeps stepping
        without advancing any stream still trips the router's staleness
        TTL, and one that stops answering dies on the STEP timeout."""
        with self._state_lock:
            return self._last_sig

    def load(self) -> int:
        with self._state_lock:
            return self._load

    def has_work(self) -> bool:
        with self._state_lock:
            return bool(self._open) or self._has_work

    def behind(self, rid: int) -> bool:
        with self._state_lock:
            return rid in self._catchup

    def capacity(self) -> List[int]:
        """``[free slots, optimistic free pages]`` from the latest
        reply — the tiered router's pre-handoff capacity gate (a chain
        must never be retired toward a worker that cannot hold it)."""
        with self._state_lock:
            return list(self._cap)

    def migration_ready(self) -> List[int]:
        """rids whose prefill finished on this worker (populated from the
        latest STEP reply) — the tiered router's migration pump input."""
        with self._state_lock:
            return list(self._ready)

    def withdraw(self, rid: int) -> Optional[dict]:
        reply = self._roundtrip(Message("WITHDRAW", {"rid": int(rid)}),
                                "withdraw", expect=("WITHDRAWN",))
        self._apply({"load": reply.payload["load"]})
        rec = reply.payload["rec"]
        if rec is not None:
            with self._state_lock:
                self.requests.pop(rid, None)
                self._done.discard(rid)
                self._open.discard(rid)
                self._submit_ts.pop(rid, None)
        return rec

    def drain_mark(self) -> int:
        """Tell the worker to refuse NEW (non-resumed) admissions — defense
        in depth under a router drain; returns the worker's in-flight
        load."""
        reply = self._roundtrip(Message("DRAIN"), "drain",
                                expect=("DRAINING",))
        self._apply({"load": reply.payload["load"]})
        return int(reply.payload["load"])

    def metrics_text(self) -> str:
        """The worker registry's Prometheus dump over the control socket
        (the HTTP endpoint at :attr:`metrics_url` serves the same text)."""
        from ...distributed.resilience.retry import RetryError, retry_call

        try:
            reply = retry_call(self._roundtrip, Message("METRICS"),
                               "metrics", expect=("METRICS_TEXT",),
                               fatal_timeout=False,
                               policy=_retry_policy(),
                               what="procfleet.metrics")
        except (socket.timeout, RetryError) as e:
            self._note_dead()
            raise WorkerDead(
                f"PT-PROC-003: replica {self.idx} metrics probe kept "
                "timing out — worker presumed wedged/dead") from e
        return reply.payload["text"]

    def finished(self) -> Dict[int, "object"]:
        with self._state_lock:
            out, self._finished = self._finished, {}
        return out

    # -- tiered migration over the wire ------------------------------------
    def export_migration(self, rid: int) -> Tuple[dict, bytes]:
        """MIGRATE_OUT: the worker flushes, exports rid's KV chain,
        journals ``migr-kv`` and releases the slot; returns
        ``(header-lite, artifact bytes)``. After this returns, the rid is
        no longer this worker's responsibility."""
        reply = self._roundtrip(Message("MIGRATE_OUT", {"rid": int(rid)}),
                                "migrate_out", expect=("CHAIN",))
        # deltas the export's flush surfaced land BEFORE ownership moves:
        # the caller's delivered prefix now equals the artifact's
        self._apply({"updates": reply.payload["updates"]})
        with self._state_lock:
            self.requests.pop(rid, None)
            self._open.discard(rid)
            self._submit_ts.pop(rid, None)
        return dict(reply.payload), reply.blob

    def import_migration(self, user, artifact: bytes) -> int:
        """MIGRATE_IN: splice an exported chain into this worker and
        resume decode at the recorded position. Raises ``KVChainCorrupt``
        / ``EngineSaturated`` exactly like the in-process splice."""
        reply = self._roundtrip(
            Message("MIGRATE_IN",
                    {"req": _admit(user),
                     "delivered": [int(t) for t in user.output]},
                    blob=artifact),
            "migrate_in", expect=("SPLICED",))
        user._n_out = len(user.output)
        with self._state_lock:
            self.requests[user.rid] = user
            self._done.discard(user.rid)
            self._open.add(user.rid)
            self._submit_ts.setdefault(user.rid, time.monotonic())
            # the prefill side already stamped admit/first_token — a
            # migrated stream continues, it does not re-admit
            self._streaming.add(user.rid)
        return int(reply.payload["rid"])

    # -- lifecycle ---------------------------------------------------------
    def _alive(self) -> bool:
        return self.process.poll() is None

    def _wait(self, timeout: float) -> bool:
        try:
            self.process.wait(timeout=timeout)
            return True
        except subprocess.TimeoutExpired:
            return False

    def kill(self) -> None:
        """SIGKILL the worker — real process death (fault drills; also the
        wedged-worker arm of ``abandon``)."""
        if self._alive():
            os.kill(self.process.pid, signal.SIGKILL)
            self._wait(10.0)
        self._note_dead()

    def close(self) -> None:
        """Graceful reap: SHUTDOWN (worker flushes + closes its journal),
        wait for exit, reap. Falls back to a kill if the worker does not
        comply in time."""
        if self.reaped:
            return
        acked = False
        if not self.dead and self._alive():
            try:
                self._roundtrip(Message("SHUTDOWN"), "shutdown",
                                timeout=self.op_timeout_s, expect=("BYE",))
                acked = True
            except (WorkerDead, WireCorrupt):
                pass
        if not acked:
            # the worker never acknowledged a shutdown: waiting for a
            # voluntary exit is a dead 5s — kill like abandon() does
            self.kill()
        self._reap(force=True)

    def abandon(self) -> None:
        """Ungraceful release (router ``_mark_dead``): no SHUTDOWN, no
        flush, no grace — SIGKILL whatever is left and reap immediately
        (a wedged worker must not stall the fleet's failover for a
        termination courtesy it will never answer). The on-disk journal
        is what failover trusts, exactly like the in-process path."""
        self.kill()
        self._reap()

    def _reap(self, force: bool = False) -> None:
        if self.reaped:
            return
        self._hb_stop.set()
        self._note_dead()
        if self._alive() and not self._wait(5.0) and force:
            self.process.terminate()
            if not self._wait(5.0):
                os.kill(self.process.pid, signal.SIGKILL)
                self._wait(5.0)
        _untrack_worker(self.process.pid)
        try:
            self._sock.close()
        except (OSError, AttributeError):
            pass
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
        try:
            os.unlink(self._spec_path)
        except OSError:
            pass
        self.reaped = True
        self.stats["proc_reaped"] = self.stats.get("proc_reaped", 0) + 1

    def heartbeat_count(self) -> int:
        with self._state_lock:
            return self._hb_count

    def _heartbeat_loop(self, interval_s: float) -> None:
        while not self._hb_stop.wait(interval_s):
            if self.dead:
                return
            try:
                self._progress_probe("heartbeat")
            except Exception:  # noqa: BLE001 — probe failure = death signal
                self._note_dead()
                return
            with self._state_lock:
                self._hb_count += 1


def _admit(req) -> dict:
    from ..recovery import _admit_record

    return _admit_record(req)
