"""Replica worker: one spawned process owning one ServingSupervisor.

``worker_main`` is the spawn target (docs/SERVING.md "Process fleet" state
machine: spawn → hello → serve → drain → reap). The worker

- builds a :class:`~paddle_tpu.inference.recovery.ServingSupervisor` from
  the spec's picklable engine factory (its OWN model, its OWN device
  memory — process-per-replica is what makes replica death process death),
- journals to the driver-shared on-disk path in the UNCHANGED
  ``RequestJournal`` format — the driver's journal-backed failover reads a
  SIGKILL'd worker's journal exactly like an in-process replica's,
- serves the PT-PROC message loop over a localhost socket
  (procfleet/wire.py), single-threaded by design: the supervisor, engine
  and journal are only ever touched from this loop,
- exposes its own :class:`~paddle_tpu.observability.MetricsServer` on an
  ephemeral port, reported in its HELLO — the driver aggregates every
  worker's ``/metrics`` under ``replica=i`` labels
  (docs/OBSERVABILITY.md remote-scrape topology).

Failure posture: a supervisor step that raises past its recovery budget is
replica death — the worker sends a typed ERROR, abandons (no journal
flush beyond what the flush barrier already guaranteed) and exits nonzero;
the driver fails its work over from the on-disk journal. A SIGKILL skips
even the ERROR — the driver sees the stream close (``WireClosed``) and
takes the same path.
"""

from __future__ import annotations

import collections
import dataclasses
import importlib
import os
import pickle
import socket
import sys
from typing import Callable, Dict, List, Optional, Tuple, Union

from .transport import TcpTransport, Transport
from .wire import Message, WireClosed, WireCorrupt

__all__ = ["WorkerSpec", "resolve_factory", "worker_main",
           "worker_thread_main"]

#: idempotence-key dedup depth: a duplicated/retried delivery arrives
#: within one op window of the original, so a small bounded cache is the
#: whole contract (the JOURNAL carries single-serve across crashes; this
#: carries it across the wire)
_IDEM_CACHE = 128


@dataclasses.dataclass
class WorkerSpec:
    """Everything a worker process needs to become a serving replica.

    - ``factory``: engine factory the CHILD imports — a module-level
      callable (pickled by reference) or a ``"module:qualname"`` string;
      called with ``factory_kwargs`` and must return a
      ``ContinuousBatchingEngine``. Factories seed their own rng so every
      replica builds bit-identical weights (procfleet/presets.py).
    - ``journal_path``: the driver-shared on-disk journal (the SAME
      ``replica{i}.g{gen}.jrnl`` naming the in-process fleet uses).
    - ``sup_kwargs``: forwarded to ``ServingSupervisor`` (step_budget_s,
      max_recoveries, fsync, watchdog_grace_steps).
    - ``metrics_port``: 0 binds an ephemeral port (reported in HELLO);
      ``None`` disables the worker's metrics endpoint.
    - ``env``: extra environment applied before heavy imports
      (e.g. ``JAX_PLATFORMS=cpu`` to pin workers to host devices).
    - ``tier``: informational tag echoed in telemetry.
    - ``mesh``: in-replica tensor-parallel width — the worker builds its
      engine with ``MeshConfig(tp=mesh)`` over its own device group, so
      fleet scale-out composes with in-replica sharding (docs/SERVING.md
      "Sharded serving"). Spawned workers own a fresh runtime: on cpu
      platforms the worker forces ``mesh`` XLA host devices before the
      backend initializes; accelerator platforms bind their visible
      devices.
    - ``device_group``: explicit device indices (into the worker
      runtime's ``jax.devices()``) for the mesh — loopback worker
      threads share ONE process runtime, so the driver hands each
      replica a disjoint slice; None = the first ``mesh`` devices.
    """

    factory: Union[str, Callable]
    journal_path: str
    factory_kwargs: dict = dataclasses.field(default_factory=dict)
    sup_kwargs: dict = dataclasses.field(default_factory=dict)
    metrics_port: Optional[int] = 0
    env: dict = dataclasses.field(default_factory=dict)
    tier: str = "serving"
    mesh: Optional[int] = None
    device_group: Optional[Tuple[int, ...]] = None
    #: worker-side KV-chain verification (KVChainCodec(verify_crc=...)).
    #: False is the net_flaky_migration drill's control arm: what a
    #: checksum-less transfer does to bitflipped migration bytes
    verify_crc: bool = True


def resolve_factory(spec: WorkerSpec) -> Callable:
    fac = spec.factory
    if isinstance(fac, str):
        mod, _, qual = fac.partition(":")
        if not mod or not qual:
            raise ValueError(
                f"factory reference {fac!r} must be 'module:qualname'")
        obj = importlib.import_module(mod)
        for part in qual.split("."):
            obj = getattr(obj, part)
        fac = obj
    if not callable(fac):
        raise TypeError(f"worker factory {fac!r} is not callable")
    kwargs = dict(spec.factory_kwargs)
    if spec.mesh:
        # bind this replica's device group and shard the engine over it
        # (MeshConfig is built HERE, in the worker runtime — device
        # handles don't pickle across the spawn boundary)
        import jax

        from ..serving import MeshConfig

        tp = int(spec.mesh)
        devs = jax.devices()
        idxs = (list(spec.device_group) if spec.device_group is not None
                else list(range(min(tp, len(devs)))))
        if len(idxs) < tp or any(int(i) >= len(devs) for i in idxs):
            raise ValueError(
                f"worker mesh tp={tp} wants device group {idxs} but this "
                f"runtime has {len(devs)} devices")
        kwargs["mesh"] = MeshConfig(
            tp=tp, devices=[devs[int(i)] for i in idxs])

        def build(mesh_tp: Optional[int] = tp):
            # width-aware factory: the elastic supervisor's PT-SRV-008
            # degrade rebuilds at the widest SURVIVING width — a prefix
            # of this worker's device group — or unsharded (mesh_tp
            # None) when no narrower width divides the head counts
            # (docs/RESILIENCE.md "Elastic serving mesh")
            kw = dict(kwargs)
            if mesh_tp is None:
                kw["mesh"] = None
            elif int(mesh_tp) != tp:
                kw["mesh"] = MeshConfig(
                    tp=int(mesh_tp),
                    devices=[devs[int(i)] for i in idxs[:int(mesh_tp)]])
            return fac(**kw)

        return build
    return lambda: fac(**kwargs)


def _engine_hello(engine) -> dict:
    """The geometry the driver-side proxy mirrors as ``.engine`` (the
    surface FleetRouter reads: page_size for prefix-chain keys, max_batch/
    max_queue for the brownout depth default) plus the pool shape the
    tiered router's migration pre-check needs."""
    out = {"page_size": int(engine.page_size),
           "max_batch": int(engine.max_batch),
           "max_queue": (None if engine.max_queue is None
                         else int(engine.max_queue)),
           "max_len": int(engine.max_len),
           "prefix_cache": engine.prefix_cache is not None,
           # in-replica mesh width (1 = unsharded): the proxy mirrors it,
           # the fleet collector labels per-device-group telemetry by it
           "mesh_tp": (int(engine.mesh.tp)
                       if getattr(engine, "mesh", None) is not None else 1)}
    if engine.prefix_cache is not None:
        kv = engine.caches["kv"]
        kvh, page, hd = (int(d) for d in kv[0][0].shape[1:])
        out.update(layers=len(kv), kvh=kvh, hd=hd,
                   dtype=str(kv[0][0].dtype), maxp=int(engine._maxp),
                   num_blocks=int(engine._alloc.num_blocks))
    return out


class _WorkerLoop:
    """The serve loop, factored for testability (handlers take/return
    Messages; ``worker_main`` owns the socket + process lifecycle)."""

    def __init__(self, sup, registry=None, verify_crc: bool = True):
        self.sup = sup
        self.registry = registry
        self.draining = False
        self.verify_crc = bool(verify_crc)
        # rid -> tokens already wired, for OPEN rids only: entries are
        # pruned when the done update ships (or the rid withdraws /
        # migrates out), so the per-step scan is O(live), not O(lifetime)
        # — same discipline recovery.py's _sync_progress documents
        self._sent: Dict[int, int] = {}
        # idempotence keys already served -> their success reply. A
        # duplicated or retried SUBMIT/MIGRATE_IN is answered from here
        # without touching the supervisor: at-most-once ADMISSION per key
        # (the reply's piggybacked load may be stale; admission may not)
        self._idem: "collections.OrderedDict[str, Message]" = \
            collections.OrderedDict()
        self._codec = None
        # last mesh width reported to the driver: an elastic PT-SRV-008
        # degrade shrinks the engine's mesh IN PLACE (the worker absorbs
        # it and keeps serving) — the next TOKENS reply piggybacks the
        # new width, a "re-HELLO" without a reconnect, so the router
        # re-weights capacity instead of declaring the worker dead
        self._last_mesh_tp = self._engine_mesh_tp()

    def _engine_mesh_tp(self) -> int:
        eng = self.sup.engine
        return (int(eng.mesh.tp)
                if getattr(eng, "mesh", None) is not None else 1)

    # -- per-type handlers -------------------------------------------------
    def handle(self, msg: Message) -> Message:
        from ..serving import EngineSaturated, RequestShed

        try:
            fn = getattr(self, "_on_" + msg.mtype.lower())
        except AttributeError:
            return Message("ERROR", {
                "etype": "WireCorrupt",
                "msg": f"PT-PROC-001: {msg.mtype} is not a request the "
                       "worker serves"})
        try:
            return fn(msg)
        except (EngineSaturated, RequestShed, ValueError, KeyError) as e:
            # typed refusals: the proxy re-raises the named class — the
            # router's fall-through routing depends on the distinction
            return Message("ERROR", {"etype": type(e).__name__,
                                     "msg": str(e)})

    def _idem_hit(self, msg: Message) -> Optional[Message]:
        key = msg.payload.get("idem")
        cached = None if key is None else self._idem.get(key)
        if cached is None:
            return None
        # a fresh copy: the serve loop stamps each reply with ITS
        # request's _seq, and the cache must stay seq-free
        return Message(cached.mtype, dict(cached.payload), cached.blob)

    def _idem_store(self, msg: Message, reply: Message) -> None:
        key = msg.payload.get("idem")
        if key is None:
            return
        self._idem[key] = Message(reply.mtype, dict(reply.payload),
                                  reply.blob)
        while len(self._idem) > _IDEM_CACHE:
            self._idem.popitem(last=False)

    def _on_submit(self, msg: Message) -> Message:
        from ..recovery import _request_from
        from ..serving import EngineSaturated

        dup = self._idem_hit(msg)
        if dup is not None:
            return dup
        if self.draining and not msg.payload["resume"]:
            raise EngineSaturated(
                "worker is draining — new admissions refused (resumed/"
                "migrated work still lands)")
        user = _request_from(msg.payload["req"])
        delivered = [int(t) for t in msg.payload["delivered"]]
        if msg.payload["resume"]:
            user.output = list(delivered)
            user._n_out = len(delivered)
        self.sup.submit(user, resume=bool(msg.payload["resume"]))
        self._sent[user.rid] = len(delivered)
        reply = Message("SUBMITTED", {"rid": int(user.rid),
                                      "load": int(self.sup.load())})
        self._idem_store(msg, reply)
        return reply

    def _updates(self) -> List[dict]:
        ups = []
        for rid, sent in list(self._sent.items()):
            user = self.sup.requests.get(rid)
            if user is None:
                self._sent.pop(rid, None)
                continue
            new = user.output[sent:]
            if not new and not user.done:
                continue
            up = {"rid": int(rid), "toks": [int(t) for t in new],
                  "done": bool(user.done), "failed": bool(user.failed),
                  "error": user.error, "n_out": len(user.output)}
            if user.done:
                self._sent.pop(rid, None)   # terminal shipped: stop
                #                             tracking (O(live) scan)
            else:
                self._sent[rid] = len(user.output)
            ups.append(up)
        return ups

    def _behind(self) -> List[int]:
        return [int(rid) for rid in list(self.sup._live)
                if self.sup.behind(rid)]

    def _ready(self) -> List[int]:
        eng = self.sup.engine
        if eng.prefix_cache is None:
            return []
        return [int(rid) for rid in eng.migration_ready()
                if rid in self.sup._live and rid not in self.sup._verify]

    def _capacity(self) -> List[int]:
        """``[free_slots, optimistic free pages]`` for the tiered
        router's pre-handoff capacity gate (mirrors the in-process
        ``_compatible``: free + radix-registered is optimistic — the
        import's EngineSaturated fallback stays load-bearing)."""
        eng = self.sup.engine
        if eng.prefix_cache is None:
            return [0, 0]
        return [len(eng._free_slots),
                int(eng._alloc.free_blocks) + len(eng._radix)]

    def _on_step(self, msg: Message) -> Message:
        self.sup.step()
        payload = {
            "updates": self._updates(), "load": int(self.sup.load()),
            "sig": list(self.sup.progress()), "behind": self._behind(),
            "ready": self._ready(), "cap": self._capacity(),
            "has_work": bool(self.sup.has_work())}
        tp = self._engine_mesh_tp()
        if tp != self._last_mesh_tp:
            self._last_mesh_tp = tp
            payload["mesh_tp"] = tp
        return Message("TOKENS", payload)

    def _on_progress(self, msg: Message) -> Message:
        return Message("PROGRESS_REPLY", {
            "sig": list(self.sup.progress()), "load": int(self.sup.load()),
            "has_work": bool(self.sup.has_work()),
            "behind": self._behind()})

    def _on_withdraw(self, msg: Message) -> Message:
        rid = int(msg.payload["rid"])
        rec = self.sup.withdraw(rid)
        if rec is not None:
            self._sent.pop(rid, None)
        return Message("WITHDRAWN", {"rec": rec,
                                     "load": int(self.sup.load())})

    def _on_drain(self, msg: Message) -> Message:
        self.draining = True
        return Message("DRAINING", {"load": int(self.sup.load())})

    def _on_metrics(self, msg: Message) -> Message:
        text = "" if self.registry is None else self.registry.dump()
        return Message("METRICS_TEXT", {"text": text})

    def _on_shutdown(self, msg: Message) -> Message:
        return Message("BYE", {})

    # -- tiered migration (inference/disagg.py over the wire) --------------
    def _codec_(self):
        if self._codec is None:
            from ..disagg import KVChainCodec

            self._codec = KVChainCodec(verify_crc=self.verify_crc)
        return self._codec

    def _on_migrate_out(self, msg: Message) -> Message:
        rid = int(msg.payload["rid"])
        codec = self._codec_()
        # flush-before-surface, then export; retire ONLY once the bytes
        # are safely built — a failure above leaves the rid owned here
        self.sup._sync_progress()
        twin = self.sup._live.get(rid)
        if twin is None or twin.done:
            raise KeyError(f"rid {rid} is not exportable (done or gone)")
        art = codec.export_chain(self.sup.engine, rid)
        hdr = codec.peek(art)
        # wire everything the flush just surfaced BEFORE the chain leaves:
        # the driver's delivered prefix must equal the artifact's
        # (collected only once export cannot fail anymore — _updates()
        # advances the sent marks, so a later refusal would lose deltas)
        ups = self._updates()
        self.sup.retire_migrated(rid, hdr["digest"])
        self._sent.pop(rid, None)
        return Message("CHAIN", {"rid": rid, "digest": str(hdr["digest"]),
                                 "pages": int(hdr["n_written"]),
                                 "updates": ups},
                       blob=art)

    def _on_migrate_in(self, msg: Message) -> Message:
        from ..disagg import KVChainCorrupt
        from ..recovery import _request_from

        dup = self._idem_hit(msg)
        if dup is not None:
            return dup
        user = _request_from(msg.payload["req"])
        delivered = [int(t) for t in msg.payload["delivered"]]
        user.output = list(delivered)
        user._n_out = len(delivered)
        try:
            self.sup.submit_migrated(user, msg.blob, self._codec_())
        except KVChainCorrupt as e:
            return Message("ERROR", {"etype": "KVChainCorrupt",
                                     "msg": str(e)})
        self._sent[user.rid] = len(delivered)
        reply = Message("SPLICED", {"rid": int(user.rid)})
        self._idem_store(msg, reply)
        return reply

    def _on_migrate_cancel(self, msg: Message) -> Message:
        """Hedged migration's loser side: the driver placed this rid's
        chain elsewhere first. If the MIGRATE_IN actually landed here
        (the race's ambiguous outcome), retire it — journal ``migr-kv``,
        ACTIVE slot released, pages decref'd: the allocator is exactly
        where it was before the splice. Idempotent: an rid that never
        landed (or already left) rolls back nothing."""
        rid = int(msg.payload["rid"])
        twin = self.sup._live.get(rid)
        rolled = False
        if twin is not None and not twin.done:
            self.sup.retire_migrated(rid, str(msg.payload["digest"]))
            self._sent.pop(rid, None)
            rolled = True
        # the key that admitted it must not answer a later duplicate
        # with SPLICED for work this worker no longer owns
        for key in [k for k, v in self._idem.items()
                    if v.payload.get("rid") == rid]:
            self._idem.pop(key, None)
        return Message("CANCELLED", {"rid": rid,
                                     "rolled_back": rolled})


def _hello_msg(spec: WorkerSpec, sup, loop: _WorkerLoop,
               metrics_port: Optional[int]) -> Message:
    """The HELLO frame, including journal-restart pending work (a worker
    (re)started over a live journal replays it in the supervisor
    constructor): the reconstructed admits + delivered marks let the
    driver-side proxy own the caller-facing objects."""
    from ..recovery import _admit_record

    pending = []
    for rid, user in sup.requests.items():
        loop._sent[rid] = len(user.output)
        pending.append({"req": _admit_record(user),
                        "delivered": [int(t) for t in user.output]})
    return Message("HELLO", {
        "pid": int(os.getpid()), "metrics_port": metrics_port,
        "journal_path": str(spec.journal_path),
        "engine": dict(_engine_hello(sup.engine), tier=str(spec.tier),
                       pending=pending),
        "state": {"load": int(sup.load()),
                  "sig": list(sup.progress()),
                  "has_work": bool(sup.has_work()),
                  "cap": loop._capacity()}})


def _serve(tr: Transport, sup, loop: _WorkerLoop) -> int:
    """The message loop over any transport. Returns the worker's exit
    code: 0 = clean SHUTDOWN, 2 = driver gone / stream damaged, 3 =
    fatal handler failure (replica death). Codes 2/3 abandon the
    supervisor — no journal flush beyond what the flush barrier already
    guaranteed, exactly the recovery contract failover replays."""
    while True:
        try:
            msg = tr.recv_frame()
        except (WireClosed, WireCorrupt):
            # driver gone (or stream damaged — same retreat)
            sup.abandon()
            return 2
        if msg.mtype == "SHUTDOWN":
            sup.close()
            bye = Message("BYE", {})
            if "_seq" in msg.payload:
                bye.payload["_seq"] = msg.payload["_seq"]
            tr.send_frame(bye)
            return 0
        try:
            reply = loop.handle(msg)
        except Exception as e:  # noqa: BLE001 — replica death boundary
            # a step crash past the recovery budget (or any unexpected
            # handler failure): this replica is DEAD — tell the driver
            # why if the pipe still works, then exit without flushing
            try:
                tr.send_frame(Message(
                    "ERROR", {"etype": type(e).__name__,
                              "msg": f"worker fatal: {e}"}))
            except (WireClosed, WireCorrupt, OSError):
                pass
            sup.abandon()
            return 3
        # echo the request's sequence id: a driver that timed out and
        # retried matches replies to attempts and discards stale ones
        if "_seq" in msg.payload:
            reply.payload["_seq"] = msg.payload["_seq"]
        tr.send_frame(reply)


def worker_main(spec_bytes: bytes, host: str, port: int) -> None:
    """Worker entry: connect back to the driver, build the supervisor,
    HELLO, serve until SHUTDOWN / driver loss / fatal supervisor error.
    Launched as ``python -m paddle_tpu.inference.procfleet.worker`` by
    :class:`~.proxy.ProcReplica` (a plain subprocess: no inherited
    interpreter state, no parent-__main__ re-execution — the child is
    exactly what production process isolation gives you)."""
    spec: WorkerSpec = pickle.loads(spec_bytes)
    for k, v in (spec.env or {}).items():
        os.environ[k] = str(v)
    if spec.mesh and int(spec.mesh) > 1 and spec.device_group is None:
        # mesh-sharded replica on host (cpu) devices: this fresh runtime
        # must expose tp devices, and XLA reads the flag at backend init
        # — force it BEFORE anything touches jax. Accelerator platforms
        # (no cpu pin) bind their own visible devices instead.
        if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count="
                    f"{int(spec.mesh)}").strip()
    if os.environ.get("JAX_PLATFORMS"):
        # axon TPU containers force-set jax_platforms programmatically,
        # overriding the env var — override it back before any backend
        # initializes (same discipline as tests/conftest.py), so a spec
        # that pins workers to host devices actually gets them
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    sock = socket.create_connection((host, int(port)), timeout=30)
    sock.settimeout(None)
    tr = TcpTransport(sock=sock)
    server = None
    try:
        from ..recovery import ServingSupervisor
        from paddle_tpu.observability import (MetricsRegistry, MetricsServer,
                                              retry_collector,
                                              supervisor_collector)

        build = resolve_factory(spec)
        sup = ServingSupervisor(build, spec.journal_path,
                                **dict(spec.sup_kwargs))
        registry = MetricsRegistry()
        registry.register_collector(supervisor_collector(sup))
        registry.register_collector(retry_collector())
        g = registry.gauge("pt_procfleet_worker_up",
                           "1 while this worker process serves")
        g.set(1.0, tier=str(spec.tier))
        metrics_port = None
        if spec.metrics_port is not None:
            server = MetricsServer(registry, port=int(spec.metrics_port))
            metrics_port = server.port
        loop = _WorkerLoop(sup, registry, verify_crc=spec.verify_crc)
        tr.send_frame(_hello_msg(spec, sup, loop, metrics_port))
        code = _serve(tr, sup, loop)
        if code != 0:
            os._exit(code)
    finally:
        if server is not None:
            server.close()
        tr.close()
    sys.exit(0)


def worker_thread_main(spec: WorkerSpec, tr: Transport) -> None:
    """Loopback twin of :func:`worker_main`: the same supervisor, journal
    format, HELLO and serve loop, over an in-process
    :class:`~.transport.LoopbackTransport` on this thread — the fast arm
    for tests/drills that would otherwise pay a process spawn + cold jit
    per case. Differences are exactly the process boundary: ``spec.env``
    is NOT applied (one shared interpreter), there is no per-worker
    metrics server (the driver's registry already sees this process),
    and "process death" is the transport closing, which failover reads
    through the journal identically. Thread-safety: the supervisor,
    engine and journal are touched only from this thread — the serve
    loop is single-threaded by design, same as the process worker."""
    try:
        from ..recovery import ServingSupervisor

        build = resolve_factory(spec)
        sup = ServingSupervisor(build, spec.journal_path,
                                **dict(spec.sup_kwargs))
        loop = _WorkerLoop(sup, None, verify_crc=spec.verify_crc)
        tr.send_frame(_hello_msg(spec, sup, loop, None))
        _serve(tr, sup, loop)
    except (WireClosed, WireCorrupt):
        pass                    # driver closed while we were replying
    except Exception as e:  # noqa: BLE001 — replica death boundary
        # construction failed (bad factory, journal IO): tell the driver
        # like the process worker's fatal path would
        try:
            tr.send_frame(Message("ERROR", {
                "etype": type(e).__name__, "msg": f"worker fatal: {e}"}))
        except Exception:       # noqa: BLE001 — already dying
            pass
    finally:
        tr.close()


def _cli(argv: Optional[List[str]] = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(
        description="procfleet replica worker (spawned by ProcReplica)")
    ap.add_argument("--spec", required=True,
                    help="path to the pickled WorkerSpec")
    ap.add_argument("--host", required=True)
    ap.add_argument("--port", required=True, type=int)
    args = ap.parse_args(argv)
    with open(args.spec, "rb") as f:
        spec_bytes = f.read()
    worker_main(spec_bytes, args.host, args.port)


if __name__ == "__main__":
    _cli()
