"""Disaggregated prefill/decode serving tiers with KV-block migration.

Production serving at heavy traffic splits prefill (compute-bound, bursty)
from decode (latency-bound, steady) onto separate replicas — SURVEY.md's
inference layer (AnalysisPredictor pools + the fleet_executor message bus
for distributed inference) is the reference shape, ROADMAP open item 3 the
charter. Every primitive already existed: chunked prefill advances slots
one chunk per step, pages are refcounted with COW ``copy_pages``
(ops/paged_attention.py), the journal re-admits work on another replica
byte-identically (fleet failover is exactly a KV-less migration), and the
router already does radix-affinity placement. This module adds the missing
piece — moving a finished prefill's KV pages between replica pools:

- :class:`KVChainCodec` — serialize a slot's finished-prefill state (page
  chain in block-table order, absolute position, prompt token ids,
  delivered tokens, sampling key state) into a self-describing artifact
  with per-page crc32 and a chain digest, and splice it into a destination
  engine's ``BlockAllocator`` pool: fresh pages at refcount 1, the table
  row mapped, the device position/last-token carry restored, and the
  prompt chain radix-inserted so migrated prefixes become cache-visible.
  Pool/slot shortfall raises ``EngineSaturated`` (the router retries
  elsewhere); a crc or digest mismatch raises the typed
  :class:`KVChainCorrupt` (**PT-SRV-007**) — corrupt bytes never touch an
  engine.
- :class:`TieredRouter` — a :class:`~paddle_tpu.inference.fleet.FleetRouter`
  whose replicas are partitioned into a PREFILL tier (new submissions
  route here; pack prompts at full batch width) and a DECODE tier: at
  prefill-complete (first token scheduled) the chain migrates to the
  least-loaded decode replica, which resumes decode at the recorded
  position. Sample keys are stateless (``fold_in(seed, position)``) and
  the spliced pages are byte-identical, so the continued stream is
  **byte-identical** (greedy and seeded) to a single-replica run.
- Crash safety — the handoff is journaled on both sides: the source
  appends ``migr-kv`` (with the chain digest) so its failover never
  re-serves the rid, and the destination journals the admit + delivered
  high-water mark so ITS failover re-runs prefill and verifies the
  delivered prefix byte-for-byte (PT-SRV-005). Mid-migration
  engine/replica faults therefore either re-run prefill or re-splice —
  never double-serve — riding the existing
  ``ServingSupervisor``/``RequestJournal`` machinery. The ordering is
  deliberately at-most-once: a whole-process crash in the brief window
  between the two journal writes drops the rid on restart rather than
  risking the admit-first ordering's double-serve.

Failure edges (docs/SERVING.md "Disaggregated tiers" state machine):

====================  ===================================================
pool/slot shortfall   ``EngineSaturated`` at import → retry the next
                      decode replica → fall back to re-running prefill
                      under resume semantics (never refused)
corrupt in transit    ``KVChainCorrupt`` (PT-SRV-007) → prefill re-run on
                      the decode side, delivered prefix verified — the
                      ``kv_migration_corruption`` drill
decode replica dies   journal-backed failover (PT-FLT-001): re-runs
                      prefill on a survivor, verifies, streams on
prefill replica dies  its journal's ``migr-kv`` records keep migrated
                      rids out of the replay set — no double service
no decode tier left   candidates stay on the prefill tier and decode in
                      place (tiers are an optimization, not a capability
                      split)
====================  ===================================================

Observability: every successful handoff stamps a ``migrate`` span on the
request's trace lane and feeds the ``pt_migration_*`` counter/histogram
families (observability/tracing.py; REQUIRED by ``tools/scrape_metrics.py
--selftest``); router-level stats ride ``pt_fleet_*`` via the fleet
collector. ``bench.py bench_disagg`` A/Bs a unified fleet against a
1-prefill+1-decode tier under the bursty open-loop schedule
(``serving_disagg_ttft_p99_under_burst_ms`` /
``serving_kv_migration_time_s``, both SECONDARY-guarded).
"""

from __future__ import annotations

import hashlib
import json
import time
import zlib
from typing import Callable, List, Optional, Set

import numpy as np

from ..ops.paged_attention import (gather_chain_pages, gather_chain_scales,
                                   scatter_chain_pages)
from .fleet import FleetRouter, ReplicaState, _Replica
from .recovery import _admit_record, _request_from
from .serving import ContinuousBatchingEngine, EngineSaturated, Request

__all__ = ["KVChainCodec", "KVChainCorrupt", "TieredRouter"]


class KVChainCorrupt(RuntimeError):
    """PT-SRV-007: a migrated KV-chain artifact failed its per-page crc32,
    its chain digest, or structural validation — the bytes were damaged in
    transit. The splice is refused with the destination engine untouched;
    the router re-runs prefill on the decode side instead (the delivered
    prefix is then regenerated and verified byte-for-byte)."""


class KVChainCodec:
    """Serialize / splice a slot's finished-prefill KV state.

    Artifact layout (self-describing, version-tagged)::

        b"PTKV1" + <8-hex header length> + <header json> + <page payload>

    The header carries the full admit record (prompt ids, sampling key
    state — seed/temperature/top-p/top-k — deadline, priority, tenant),
    the absolute resume position, the delivered token ids, the pool
    geometry (layers, kv heads, page size, head dim, dtype), the chain
    shape (``n_blocks`` total, ``n_written`` pages of real k/v), a crc32
    per written page (over every layer's k+v bytes for that page) and a
    blake2b chain digest over the canonical digest-less header + the
    payload — header fields (delivered tokens, sampling key state) are
    integrity-protected exactly like the page bytes. The payload is
    each layer's k then v pages for the written prefix of the chain, in
    block-table order.

    ``verify_crc=False`` is the fault drill's control arm ONLY: it splices
    whatever bytes arrive, demonstrating the silent stream corruption the
    verification exists to prevent. Never disable it in production.
    """

    MAGIC = b"PTKV1"

    def __init__(self, verify_crc: bool = True):
        self.verify_crc = bool(verify_crc)

    # -- export ------------------------------------------------------------
    def export_chain(self, engine: ContinuousBatchingEngine,
                     rid: int) -> bytes:
        """Serialize ``rid``'s slot state from a prefix-cache engine. The
        slot must be DECODING (prefill complete, >= 1 token scheduled);
        the source engine is not disturbed — callers release the slot
        (``withdraw_active``) only after the bytes are safely out."""
        if engine.prefix_cache is None:
            raise ValueError("KV-chain export needs a prefix-cache engine")
        slot = engine.slot_of(rid)
        if slot is None:
            raise KeyError(f"rid {rid} holds no active slot")
        req = engine._slots[slot]
        engine._drain_pending()
        if req._n_out < 1 or len(req.output) < req._n_out:
            raise RuntimeError(
                f"rid {rid}: export before the first token materialized "
                f"({len(req.output)}/{req._n_out})")
        pos = int(engine._pos[slot])
        page = engine.page_size
        blocks = list(engine._slot_blocks[slot])
        n_cached = pos - 1                  # tokens already in the cache
        n_written = -(-n_cached // page)
        kv = engine.caches["kv"]
        pages = gather_chain_pages(kv, blocks[:n_written])
        # int8 block format: the payload is the RAW int8 page bytes (crc
        # covers them exactly as stored); the per-block dequant scales ride
        # the header, integrity-protected by the chain digest like every
        # other header field
        scales = gather_chain_scales(kv, blocks[:n_written])
        kvh, _, hd = pages[0][0].shape[1:]
        dtype = np.asarray(pages[0][0]).dtype
        # serialize each side ONCE; the per-page crcs are computed over
        # offsets into those bytes (mirroring _verify's layout walk) —
        # chains run to tens of MB at production shapes, so a second
        # .tobytes() pass would double the handoff's memcpy cost
        page_bytes = int(kvh) * page * int(hd) * dtype.itemsize
        pieces: List[bytes] = []
        for pk, pv in pages:
            pieces.append(pk.tobytes())
            pieces.append(pv.tobytes())
        page_crc: List[int] = []
        for j in range(n_written):
            crc = 0
            for side in pieces:
                off = j * page_bytes
                crc = zlib.crc32(side[off:off + page_bytes], crc)
            page_crc.append(crc & 0xFFFFFFFF)
        hdr = dict(_admit_record(req))
        hdr.update(v=1, pos=pos,
                   delivered=[int(t) for t in req.output],
                   page_size=page, layers=len(kv), kvh=int(kvh),
                   hd=int(hd), dtype=str(dtype), n_blocks=len(blocks),
                   n_written=n_written, page_crc=page_crc)
        if scales is not None:
            hdr["kv_scales"] = [[np.asarray(s, np.float32).tolist()
                                 for s in pair] for pair in scales]
        # the chain digest covers the CANONICAL header (digest-excluded) +
        # every payload byte: a transit flip anywhere — a delivered token
        # id, the seed, a sampling knob, a page — is a PT-SRV-007
        # rejection, not a silently-diverging resumed stream
        hdr["digest"] = self._digest(hdr, pieces)
        hj = json.dumps(hdr, separators=(",", ":")).encode("utf-8")
        return self.MAGIC + (b"%08x" % len(hj)) + hj + b"".join(pieces)

    @staticmethod
    def _digest(hdr: dict, payload_parts) -> str:
        """blake2b over the canonical (sorted-keys, digest-less) header
        json + the payload bytes — export and verify share this so the
        wire header's json round trip cannot skew the comparison."""
        probe = {k: v for k, v in hdr.items() if k != "digest"}
        dig = hashlib.blake2b(digest_size=16)
        dig.update(json.dumps(probe, sort_keys=True,
                              separators=(",", ":")).encode("utf-8"))
        for part in payload_parts:
            dig.update(part)
        return dig.hexdigest()

    # -- parsing / verification -------------------------------------------
    def peek(self, artifact: bytes) -> dict:
        """Header only (structural validation, no crc work)."""
        return self._parse(artifact)[0]

    def _parse(self, artifact):
        """Split an artifact into (header dict, payload view). The payload
        stays a zero-copy memoryview — chains run to tens of MB, and this
        runs once for ``peek`` plus once per import attempt; crc32,
        blake2b and np.frombuffer all consume the view directly."""
        m = len(self.MAGIC)
        if not isinstance(artifact, (bytes, bytearray, memoryview)):
            raise KVChainCorrupt(
                "PT-SRV-007: not a KV-chain artifact (bad magic)")
        mv = memoryview(artifact)
        if len(mv) < m + 8 or bytes(mv[:m]) != self.MAGIC:
            raise KVChainCorrupt(
                "PT-SRV-007: not a KV-chain artifact (bad magic)")
        try:
            hlen = int(bytes(mv[m:m + 8]), 16)
        except ValueError:
            raise KVChainCorrupt(
                "PT-SRV-007: malformed header length") from None
        if hlen <= 0 or m + 8 + hlen > len(mv):
            raise KVChainCorrupt("PT-SRV-007: header length out of range")
        try:
            hdr = json.loads(bytes(mv[m + 8:m + 8 + hlen]).decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError):
            raise KVChainCorrupt(
                "PT-SRV-007: undecodable artifact header") from None
        payload = mv[m + 8 + hlen:]
        try:
            itemsize = np.dtype(hdr["dtype"]).itemsize
            expect = (hdr["layers"] * 2 * hdr["n_written"] * hdr["kvh"]
                      * hdr["page_size"] * hdr["hd"] * itemsize)
            if not hdr["delivered"] or hdr["n_written"] < 1:
                raise KVChainCorrupt(
                    "PT-SRV-007: artifact carries no finished prefill")
        except (KeyError, TypeError, ValueError):
            raise KVChainCorrupt(
                "PT-SRV-007: artifact header missing chain fields") from None
        if len(payload) != expect:
            raise KVChainCorrupt(
                f"PT-SRV-007: payload is {len(payload)} bytes, header "
                f"promises {expect}")
        return hdr, payload

    def _verify(self, hdr: dict, payload: bytes) -> None:
        """Per-page crc32 + chain digest — names the damaged page."""
        itemsize = np.dtype(hdr["dtype"]).itemsize
        page_bytes = hdr["kvh"] * hdr["page_size"] * hdr["hd"] * itemsize
        side_bytes = hdr["n_written"] * page_bytes
        crcs = list(hdr.get("page_crc") or ())
        if len(crcs) != hdr["n_written"]:
            raise KVChainCorrupt(
                "PT-SRV-007: per-page crc table does not cover the chain")
        for j in range(hdr["n_written"]):
            crc = 0
            for layer in range(hdr["layers"]):
                base = layer * 2 * side_bytes
                for side in range(2):
                    off = base + side * side_bytes + j * page_bytes
                    crc = zlib.crc32(payload[off:off + page_bytes], crc)
            if (crc & 0xFFFFFFFF) != crcs[j]:
                raise KVChainCorrupt(
                    f"PT-SRV-007: chain page {j} failed its crc32 — "
                    f"rid={hdr.get('rid')} artifact corrupted in transit")
        if self._digest(hdr, (payload,)) != hdr.get("digest"):
            raise KVChainCorrupt(
                f"PT-SRV-007: chain digest mismatch — rid={hdr.get('rid')} "
                "header (prompt/delivered/sampling state) and pages must "
                "arrive exactly as exported")

    def _unpack(self, hdr: dict, payload: bytes):
        dt = np.dtype(hdr["dtype"])
        shape = (hdr["n_written"], hdr["kvh"], hdr["page_size"], hdr["hd"])
        n = int(np.prod(shape))
        nb = n * dt.itemsize
        out, off = [], 0
        for _ in range(hdr["layers"]):
            k = np.frombuffer(payload, dt, n, off).reshape(shape)
            off += nb
            v = np.frombuffer(payload, dt, n, off).reshape(shape)
            off += nb
            out.append((k, v))
        return out

    # -- import ------------------------------------------------------------
    def import_chain(self, engine: ContinuousBatchingEngine,
                     artifact: bytes,
                     req: Optional[Request] = None) -> Request:
        """Splice a chain into ``engine``: verify (unless the drill's
        control arm disabled it), allocate ``n_blocks`` fresh pages
        (LRU-evicting idle cached blocks on shortfall), scatter the
        written page bytes, and resume the request at the recorded
        position via ``admit_migrated`` (radix-inserted, refcounts
        correct). Raises ``EngineSaturated`` on slot/pool shortfall with
        the engine untouched, :class:`KVChainCorrupt` on damage."""
        hdr, payload = self._parse(artifact)
        if self.verify_crc:
            self._verify(hdr, payload)
        if engine.prefix_cache is None:
            raise ValueError("KV-chain splice needs a prefix-cache engine")
        kv = engine.caches["kv"]
        pool_shape = tuple(int(d) for d in kv[0][0].shape[1:])
        want = (hdr["kvh"], hdr["page_size"], hdr["hd"])
        if (engine.page_size != hdr["page_size"] or len(kv) != hdr["layers"]
                or pool_shape != want
                or str(kv[0][0].dtype) != hdr["dtype"]):
            raise ValueError(
                f"destination pool geometry {len(kv)}x{pool_shape} "
                f"({kv[0][0].dtype}) cannot hold chain "
                f"{hdr['layers']}x{want} ({hdr['dtype']}) — tiers must "
                "share the serving config")
        if engine._maxp < hdr["n_blocks"]:
            raise ValueError(
                f"chain spans {hdr['n_blocks']} pages but the destination "
                f"table holds {engine._maxp} per slot")
        if not engine._free_slots:
            raise EngineSaturated(
                f"no free slot on splice target for rid={hdr['rid']}")
        scales = None
        if hdr["dtype"] == "int8":
            # validated BEFORE any allocator state moves: a structurally
            # damaged scale table refuses the splice with the engine
            # untouched, like every other PT-SRV-007 path
            raw = hdr.get("kv_scales")
            if (not isinstance(raw, list) or len(raw) != hdr["layers"]
                    or any(len(pair) != 2 for pair in raw)):
                raise KVChainCorrupt(
                    "PT-SRV-007: int8 chain without a per-layer "
                    "kv_scales table — the block format needs its dequant "
                    "scales to travel with the page bytes")
            scales = [tuple(np.asarray(s, np.float32) for s in pair)
                      for pair in raw]
        blocks = engine._alloc.alloc(hdr["n_blocks"],
                                     evict=engine._radix.evict_lru)
        if blocks is None:
            raise EngineSaturated(
                f"splice pool shortfall for rid={hdr['rid']}: chain needs "
                f"{hdr['n_blocks']} blocks, {engine._alloc.free_blocks} "
                "free after LRU eviction — retry another decode replica")
        try:
            engine.caches = {
                "kv": scatter_chain_pages(kv, blocks[:hdr["n_written"]],
                                          self._unpack(hdr, payload),
                                          scales=scales),
                "tables": engine.caches["tables"]}
            if req is None:
                req = _request_from(hdr)
                req.output = [int(t) for t in hdr["delivered"]]
                req._n_out = len(req.output)
            engine.admit_migrated(req, blocks, hdr["pos"],
                                  last_tok=int(hdr["delivered"][-1]))
        except Exception:
            engine._alloc.decref(blocks)
            raise
        return req


class TieredRouter(FleetRouter):
    """Disaggregated prefill/decode tiers over the fleet substrate.

    >>> tiered = TieredRouter(build_prefill, build_decode, fleet_dir,
    ...                       num_prefill=1, num_decode=2)
    >>> tiered.submit(Request(prompt, max_new_tokens=64))
    >>> done = tiered.run_until_done()

    Replicas ``0..num_prefill-1`` form the prefill tier (new submissions
    route only here — pack prompts at full batch width by building the
    prefill engine fused with a generous ``pack_rows``), the rest the
    decode tier. After every fleet tick the router scans the prefill tier
    for finished prefills and migrates each chain to the least-loaded
    decode replica through :class:`KVChainCodec` (module docstring for
    the failure edges). All FleetRouter machinery — journal-backed
    failover, progress heartbeats, drain/rolling restart, brownout
    shedding, the fleet collector — runs unchanged over both tiers.
    """

    def __init__(self, build_prefill: Callable[[], ContinuousBatchingEngine],
                 build_decode: Callable[[], ContinuousBatchingEngine],
                 fleet_dir: str, num_prefill: int = 1, num_decode: int = 1,
                 codec: Optional[KVChainCodec] = None, **kw):
        if num_prefill < 1 or num_decode < 1:
            raise ValueError("each tier needs at least one replica")
        self._build_prefill = build_prefill
        self._build_decode = build_decode
        self._num_prefill = int(num_prefill)
        self.codec = codec if codec is not None else KVChainCodec()
        super().__init__(build_prefill, fleet_dir,
                         num_replicas=int(num_prefill) + int(num_decode),
                         **kw)
        # fail at construction, not on the first finished prefill: both
        # sides of the handoff need dynamic block tables over the
        # refcounted pool (export reads a slot's chain, import splices one)
        for rep in self.replicas:
            if rep.sup.engine.prefix_cache is None:
                raise ValueError(
                    f"{rep.tier}-tier replica {rep.idx} was built without "
                    "a prefix cache — KV-block migration needs "
                    "prefix_cache engines on both tiers")
        # migration_deferred counts STEPS a ready candidate waited for
        # decode capacity/compatibility (pre-check, per step);
        # migration_refused counts actual splice refusals at import (per
        # target tried) — conflating them would read a busy-wait as a
        # refusal storm and mask real splice failures
        self.stats.update(migrations=0, migration_s=0.0, migration_pages=0,
                          migration_bytes=0, migration_corrupt=0,
                          migration_deferred=0, migration_refused=0,
                          migration_reprefill=0, migration_hedges=0)
        #: per-migration wall-clock seconds, newest-last, capped — the
        #: ``serving_migration_under_loss`` bench reads p99 from here
        #: (hedges never fire in-process: no wire, no timeouts — the key
        #: exists so collectors read both pumps uniformly)
        self.migration_samples: List[float] = []
        self._corrupt_hook = None

    # -- tier membership (fleet.py hooks) ----------------------------------
    def _builder(self, idx: int):
        return (self._build_prefill if idx < self._num_prefill
                else self._build_decode)

    def tier_of(self, idx: int) -> str:
        return "prefill" if idx < self._num_prefill else "decode"

    def _routable(self, req: Request) -> List[_Replica]:
        """New submissions take the prefill tier; with no prefill replica
        alive the decode tier absorbs them (tiers are an optimization,
        not a capability split — every engine runs the full path)."""
        alive = super()._routable(req)
        pre = [r for r in alive if r.tier == "prefill"]
        return pre or alive

    def _pick_survivor(self, req: Request,
                       exclude: Set[int] = frozenset()) -> Optional[_Replica]:
        """Failover re-runs prefill, so prefill-tier survivors are
        preferred; once (re)finished it migrates again as usual."""
        alive = [r for r in self.replicas
                 if r.state == ReplicaState.ALIVE and r.idx not in exclude]
        pool = [r for r in alive if r.tier == "prefill"] or alive
        if not pool:
            return None
        n = len(pool)
        return min(pool, key=lambda r: (r.sup.load(),
                                        (r.idx - req.rid) % n))

    # -- the migration pump ------------------------------------------------
    # LOCKSTEP NOTE: procfleet/router.py's ProcTieredRouter mirrors this
    # pump over the wire (export_migration/import_migration replace the
    # direct engine access) — a behavioral fix to either pump must land
    # in BOTH.
    def step(self) -> None:
        super().step()
        self._migrate_ready()

    def _decode_targets(self, rid: int) -> List[_Replica]:
        alive = [r for r in self.replicas
                 if r.state == ReplicaState.ALIVE and r.tier == "decode"]
        n = max(1, len(alive))
        return sorted(alive, key=lambda r: (r.sup.load(),
                                            (r.idx - rid) % n))

    def _migrate_ready(self) -> None:
        """Migrate every finished prefill off the prefill tier. Runs on
        the driver thread after the fleet tick (never inside
        ``parallel_step`` replica threads), so engine state is quiescent."""
        if self._corrupt_hook is None:
            from ..distributed.resilience.faults import corrupt

            self._corrupt_hook = corrupt
        for rep in self.replicas:
            if rep.state != ReplicaState.ALIVE or rep.tier != "prefill":
                continue
            for rid in rep.sup.engine.migration_ready():
                user = self.requests.get(rid)
                if (user is None or user.done
                        or rep.sup._live.get(rid) is None):
                    continue
                if rid in rep.sup._verify:
                    # recovery catch-up twin: let it reach and verify the
                    # delivered mark locally before its chain travels
                    continue
                self._migrate_one(rep, rid, user)

    def _compatible(self, src_engine, dst_engine, user: Request,
                    need: int) -> bool:
        """Pool-geometry + capacity gate, checked BEFORE ownership moves:
        a chain must never be retired from its source toward a destination
        that cannot hold it (mismatched tier configs would otherwise
        strand the request after the ``migr-kv`` handoff)."""
        if (dst_engine.prefix_cache is None
                or dst_engine.page_size != src_engine.page_size
                or dst_engine._maxp < need
                or len(user.prompt) + user.max_new_tokens
                > dst_engine.max_len):
            return False
        src_kv, dst_kv = src_engine.caches["kv"], dst_engine.caches["kv"]
        if (len(dst_kv) != len(src_kv)
                or dst_kv[0][0].shape[1:] != src_kv[0][0].shape[1:]
                or dst_kv[0][0].dtype != src_kv[0][0].dtype):
            return False
        # capacity: free + radix-registered is an optimistic pool estimate
        # (registered blocks may be pinned by live tables), so the
        # import's EngineSaturated fallback stays load-bearing
        return bool(dst_engine._free_slots) and (
            dst_engine._alloc.free_blocks
            + len(dst_engine._radix)) >= need

    def _migrate_one(self, src: _Replica, rid: int, user: Request) -> bool:
        # compatibility/capacity pre-check BEFORE ownership moves: a tier
        # that is merely full (or misconfigured) is not a failure — the
        # candidate keeps decoding on the prefill tier and retries next
        # step.
        need = src.sup.engine._pages_needed(len(user.prompt),
                                            user.max_new_tokens)
        targets = [r for r in self._decode_targets(rid)
                   if self._compatible(src.sup.engine, r.sup.engine, user,
                                       need)]
        if not targets:
            self.stats["migration_deferred"] += 1
            return False            # no capacity / no decode tier alive:
        #                             decode in place, retry next step
        t0 = time.monotonic()
        t0_tr = None if self.tracer is None else self.tracer.now()
        # flush-before-surface: everything delivered so far is journaled
        # and spliced into the caller's object before the chain travels
        src.sup._sync_progress()
        twin = src.sup._live.get(rid)
        if twin is None or twin.done:
            return False            # finished inside that sync
        art = self.codec.export_chain(src.sup.engine, rid)
        hdr = self.codec.peek(art)
        # in-transit hook: the kv_migration_corruption drill flips page
        # bytes here (FaultPlan site ``serving.kv_transfer``)
        art = self._corrupt_hook("serving.kv_transfer", f"rid:{rid}", art)
        # ownership leaves the prefill journal BEFORE the splice lands
        # (``migr-kv`` + slot release): an ENGINE/replica fault on either
        # side now re-runs prefill from the decode admit or this router's
        # resume fallback — the rid is never served twice. This is
        # deliberately at-most-once: a whole-PROCESS crash inside the
        # journal-to-journal window would drop the rid on restart (neither
        # journal replays it), which streams-wise beats the admit-first
        # ordering's double-serve window.
        src.sup.retire_migrated(rid, hdr["digest"])
        placed = None
        corrupt_art = False
        for rep in targets:
            try:
                rep.sup.submit_migrated(user, art, self.codec)
                placed = rep
                break
            except KVChainCorrupt as e:
                # PT-SRV-007 takes the same retry-elsewhere arm as a
                # refusal (UNIFIED policy, mirrored in the proc pump where
                # wire-transit damage really is per-hop); in-process the
                # bytes are shared so later targets will refuse them too,
                # ending in the reprefill fallback below either way
                corrupt_art = True
                self.stats["migration_corrupt"] += 1
                self.events.append(("PT-SRV-007", str(e)))
                if self.tracer is not None:
                    self.tracer.migration_failure(
                        rid, "corrupt", tags={"replica": rep.idx})
                continue
            except (EngineSaturated, ValueError):
                # saturated at import (the pre-check's pool estimate was
                # optimistic) — or a geometry refusal the pre-check
                # somehow missed: either way this target is out, the
                # bytes are fine, try the next one
                self.stats["migration_refused"] += 1
                if self.tracer is not None:
                    self.tracer.migration_failure(
                        rid, "refused", tags={"replica": rep.idx})
                continue
            except Exception as e:  # noqa: BLE001 — replica death boundary
                # an unexpected splice failure (device OOM, journal IO)
                # leaves that replica's engine untrusted — same posture as
                # _step_all: mark it dead and fail its work over. Must not
                # escape: the rid is already retired from the source, so
                # an unhandled raise here would strand it forever.
                self._mark_dead(rep, f"splice of rid={rid} raised "
                               f"{type(e).__name__}: {e}")
                self._handle_death(rep)
                if self._assigned.get(rid, src.idx) != src.idx:
                    # the replica had journaled the admit before dying —
                    # its failover already re-placed the rid
                    return True
                continue
        if placed is None:
            # every decode replica refused (or the artifact is corrupt):
            # re-run prefill under resume semantics on the least-loaded
            # surviving replica (decode tier first) — journaled work is
            # never refused, and the delivered prefix is regenerated +
            # verified byte-for-byte (PT-SRV-005) before anything new
            # streams
            alive = self._decode_targets(rid)     # re-query: a target may
            target = (alive[0] if alive           # have died in the loop
                      else self._pick_survivor(user, exclude=set()))
            if target is None:
                user.done = user.failed = True
                user.error = (f"PT-TIER-001: no surviving replica to "
                              f"place migrated rid={rid} on")
                self._trace_lost(rid, user, src.idx)
                return True
            self.stats["migration_reprefill"] += 1
            target.sup.submit(user, resume=True)
            self._assigned[rid] = target.idx
            self.events.append(
                ("PT-TIER-001",
                 f"rid={rid} chain not spliced "
                 f"({'corrupt' if corrupt_art else 'refused'}) — prefill "
                 f"re-run on replica {target.idx}"))
            return True
        self._assigned[rid] = placed.idx
        dt = time.monotonic() - t0
        self.stats["migrations"] += 1
        self.stats["migration_s"] += dt
        self.migration_samples.append(dt)
        del self.migration_samples[:-512]
        self.stats["migration_pages"] += int(hdr["n_written"])
        self.stats["migration_bytes"] += len(art)
        self.events.append(
            ("PT-TIER-001",
             f"rid={rid} chain ({hdr['n_written']} page(s), {len(art)} "
             f"bytes) migrated replica {src.idx} -> {placed.idx} in "
             f"{dt * 1e3:.1f}ms"))
        if self.tracer is not None:
            self.tracer.migrate(rid, src.idx, placed.idx,
                                pages=int(hdr["n_written"]),
                                nbytes=len(art), t0=t0_tr,
                                tags={"replica": placed.idx})
        return True
