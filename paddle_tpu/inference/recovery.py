"""Crash-recoverable serving: request journal + supervisor (docs/SERVING.md).

The continuous-batching engine is the most stateful component in the repo —
a paged KV pool, a radix prefix cache, chunked-prefill slots, device-side
token carries. None of that state is durable, and none of it needs to be:
every admitted request is fully described by its prompt ids, sampling
params, seed and deadline, and the engine's sample keys are stateless
(``fold_in(key(seed), position)`` — models/generation_utils.py). So the
recovery unit is the REQUEST, not the engine: journal what was admitted and
how far each stream got, and an engine crash costs a rebuild + replay that
is **bit-identical** to the uninterrupted run (greedy and seeded sampling,
including requests past a copy-on-write divergence point — warm==cold
bit-identity means a fresh pool and an empty radix cache cannot change a
single token).

Components:

- :class:`RequestJournal` — append-only, per-record crc32-checked journal
  (the same torn-write posture as distributed/checkpoint/integrity.py: a
  crash mid-append leaves a torn TAIL, which loading tolerates; corruption
  in the middle of the journal raises :class:`JournalCorrupt` naming the
  record). Records: ``admit`` (full request parameters), ``prog`` (the
  emitted-token high-water mark plus the token ids themselves, so replay
  can verify bit-identity even across a process restart), ``fin``,
  ``shed``, ``crash``/``recovered`` markers.
- :class:`ServingSupervisor` — owns the engine via a ``build_engine``
  factory. ``submit`` journals then admits; ``step`` arms a
  :class:`~paddle_tpu.distributed.resilience.watchdog.StepWatchdog` around
  the engine step and, on a crash (any exception out of ``step`` — e.g. the
  ``serving.step`` ``kill`` fault) or a watchdog overrun (``serving.stall``),
  rebuilds: fresh engine, fresh block pool, empty radix cache, every
  unfinished journaled request re-admitted and replayed. Tokens already
  delivered (journaled high-water mark) are NOT re-delivered: the replay
  catches up to the mark, verifies the regenerated prefix matches the
  delivered one byte-for-byte (PT-SRV-005 on divergence), and streams on
  from there.

Deadline semantics across recovery: a re-admitted request's deadline clock
RESTARTS at re-admission (the journal stores the deadline *duration*) — an
engine fault is the operator's problem, not the request's.

PT-SRV diagnostic codes (docs/RESILIENCE.md):

========== ==============================================================
PT-SRV-001 engine crash absorbed — rebuilt from journal, requests replayed
PT-SRV-002 step watchdog overrun (stall) — flagged mid-hang, then rebuilt
PT-SRV-003 request shed at submit (``RequestShed`` — serving.py)
PT-SRV-004 journal corruption (:class:`JournalCorrupt` names the record)
PT-SRV-005 replay divergence: recovered prefix != delivered prefix
PT-SRV-006 brownout entered/exited (engine stats — serving.py)
========== ==============================================================
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Callable, Dict, List, Optional

from .serving import ContinuousBatchingEngine, Request, RequestShed

__all__ = ["JournalCorrupt", "RequestJournal", "ServingSupervisor"]


class JournalCorrupt(RuntimeError):
    """PT-SRV-004: a journal record failed its crc (or decode) somewhere
    other than the torn tail — the file was damaged after it was written."""


class RequestJournal:
    """Append-only, crc-checked request journal.

    One record per line: ``<crc32 of payload, 8 hex chars> <json payload>``.
    Appends flush to the OS on every record (``fsync=True`` additionally
    forces them to disk — crash-safe across power loss at a syscall per
    record; the default survives process death, which is the serving
    failure mode the supervisor drills).

    Loading tolerates a torn final record (a crash mid-append) by
    truncating to the last good record; a bad crc anywhere EARLIER raises
    :class:`JournalCorrupt` naming the line — silent damage never replays.
    """

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = bool(fsync)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        if os.path.exists(path):
            self.records, good = self._load_bytes(path)
            # drop a torn tail NOW: appending after partial bytes would
            # weld the next record onto them — mid-file corruption on the
            # following load instead of a tolerated torn append
            if good < os.path.getsize(path):
                with open(path, "r+b") as f:
                    f.truncate(good)
        else:
            self.records = []
        self._fh = open(path, "ab")

    @staticmethod
    def load(path: str) -> List[dict]:
        return RequestJournal._load_bytes(path)[0]

    @staticmethod
    def _load_bytes(path: str):
        """Parse the journal; returns ``(records, good_byte_length)`` where
        the length covers every intact record (a torn tail is excluded)."""
        out: List[dict] = []
        good = 0
        with open(path, "rb") as f:
            lines = f.read().split(b"\n")
        for i, line in enumerate(lines):
            if not line:
                # the split's final element (after the last newline) is
                # always empty; a blank line with records AFTER it is
                # damage — skipping it would make ``good`` undercount the
                # file offset, and the constructor's truncate(good) would
                # then chop bytes off a committed record
                if any(lines[j] for j in range(i + 1, len(lines))):
                    raise JournalCorrupt(
                        f"PT-SRV-004: journal {path} record {i + 1}: blank "
                        "line — records after it exist, so this is damage, "
                        "not a torn append")
                break
            bad = None
            if len(line) < 10 or line[8:9] != b" ":
                bad = "malformed record"
            else:
                payload = line[9:]
                try:
                    want = int(line[:8], 16)
                except ValueError:
                    want, bad = -1, "malformed crc"
                if bad is None and (zlib.crc32(payload) & 0xFFFFFFFF) != want:
                    bad = "crc mismatch"
                if bad is None:
                    try:
                        out.append(json.loads(payload.decode("utf-8")))
                        good += len(line) + 1
                        continue
                    except (UnicodeDecodeError, json.JSONDecodeError):
                        bad = "undecodable payload"
            # damage in the tail record = torn append -> tolerated (the
            # record never committed); damage earlier = corruption
            if any(lines[j] for j in range(i + 1, len(lines))):
                raise JournalCorrupt(
                    f"PT-SRV-004: journal {path} record {i + 1}: {bad} — "
                    "records after it exist, so this is damage, not a torn "
                    "append")
            break
        return out, good

    def append(self, kind: str, **fields) -> None:
        rec = {"k": kind}
        rec.update(fields)
        payload = json.dumps(rec, separators=(",", ":")).encode("utf-8")
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        self._fh.write(b"%08x " % crc + payload + b"\n")
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())
        self.records.append(rec)

    def unfinished(self) -> List[dict]:
        """Admit records with no matching ``fin`` — the replay set."""
        done = {r["rid"] for r in self.records if r["k"] == "fin"}
        return [r for r in self.records
                if r["k"] == "admit" and r["rid"] not in done]

    def delivered(self, rid: int) -> List[int]:
        """Token ids journaled as delivered for ``rid`` (concatenated
        ``prog`` deltas) — the prefix replay must reproduce exactly."""
        toks: List[int] = []
        for r in self.records:
            if r["k"] == "prog" and r["rid"] == rid:
                toks.extend(r["toks"])
        return toks

    def close(self) -> None:
        self._fh.close()


def _admit_record(req: Request) -> dict:
    return {"rid": req.rid, "prompt": [int(t) for t in req.prompt],
            "max_new": req.max_new_tokens, "eos": req.eos_token_id,
            "temp": req.temperature, "top_p": req.top_p, "top_k": req.top_k,
            "seed": req.seed, "deadline_s": req.deadline_s,
            "priority": req.priority}


def _request_from(rec: dict) -> Request:
    return Request(rec["prompt"], max_new_tokens=rec["max_new"],
                   eos_token_id=rec["eos"], temperature=rec["temp"],
                   top_p=rec["top_p"], top_k=rec["top_k"], seed=rec["seed"],
                   deadline_s=rec["deadline_s"], priority=rec["priority"])


class ServingSupervisor:
    """Crash-recoverable driver over a :class:`ContinuousBatchingEngine`.

    >>> sup = ServingSupervisor(lambda: ContinuousBatchingEngine(model, ...),
    ...                         journal_path, step_budget_s=2.0)
    >>> sup.submit(Request(prompt, max_new_tokens=64))
    >>> done = sup.run_until_done()

    The caller keeps its ``Request`` objects; across a crash their token
    streams continue bit-identically (the supervisor replays on a rebuilt
    engine, verifies the regenerated prefix against the journaled
    high-water mark, and appends only the new tokens). A supervisor
    constructed over an EXISTING journal (process restart) re-admits every
    unfinished request automatically; their reconstructed ``Request``
    objects live in :attr:`requests`.

    ``max_recoveries`` bounds the rebuild budget (a crash loop must
    eventually surface, not mask); ``max_recoveries=0`` disables recovery —
    the fault-drill's control arm.

    ``step_budget_s`` must comfortably exceed a WARM step (compile-heavy
    first steps otherwise read as stalls, and every rebuild recompiles —
    a false-positive cascade that burns the whole recovery budget). Warm
    the engine first, then arm via :meth:`set_step_budget`.
    """

    #: exceptions that are caller errors, never engine-state damage
    _SUBMIT_ERRORS = (ValueError,)

    def __init__(self, build_engine: Callable[[], ContinuousBatchingEngine],
                 journal_path: str, step_budget_s: Optional[float] = None,
                 max_recoveries: int = 2, watchdog_grace_steps: int = 4,
                 fsync: bool = False):
        from ..distributed.resilience.watchdog import StepWatchdog

        self._build = build_engine
        # a rebuilt engine recompiles its programs, and a compile-heavy
        # step is indistinguishable from a stall — without grace, one real
        # stall cascades into false positives that burn the whole recovery
        # budget. The first N steps after every rebuild run unarmed.
        self.watchdog_grace_steps = int(watchdog_grace_steps)
        self._grace = 0
        self.journal = RequestJournal(journal_path, fsync=fsync)
        self.requests: Dict[int, Request] = {}   # rid -> caller-facing req
        self._live: Dict[int, Request] = {}      # rid -> object in engine
        self._meta: Dict[int, dict] = {}         # rid -> admit record
        self._hwm: Dict[int, int] = {}           # rid -> delivered tokens
        self._done: set = set()
        self._finished: Dict[int, Request] = {}
        self.events: List[tuple] = []            # (code, message)
        self.recoveries = 0
        self.max_recoveries = int(max_recoveries)
        self.watchdog = (StepWatchdog(step_budget_s)
                         if step_budget_s is not None else None)
        self.stats = {"shed": 0, "recoveries": 0, "recovery_s": 0.0,
                      "replayed_requests": 0}
        self.engine = build_engine()
        # rids are assigned by a PER-PROCESS counter; a restart over an
        # existing journal resets it, so a fresh submit could collide with
        # a journaled rid (a stale "fin" would then mask the new request
        # from replay, and delivered() would merge two requests' tokens).
        # Bump the counter past every journaled rid before any submit.
        if self.journal.records:
            Request._counter[0] = max(
                Request._counter[0],
                max(r["rid"] for r in self.journal.records if "rid" in r))
        pending = self.journal.unfinished()
        if pending:
            # process restart over a live journal: replay now. The caller's
            # original Request objects are gone with the old process; the
            # reconstructed ones (exposed via .requests) carry the streams.
            for rec in pending:
                self._meta[rec["rid"]] = rec
                self._hwm[rec["rid"]] = len(self.journal.delivered(rec["rid"]))
                self.requests[rec["rid"]] = None   # filled by _readmit
            self._recover("PT-SRV-001",
                          f"journal restart: {len(pending)} unfinished "
                          "request(s) found", rebuild=False)

    # -- public API --------------------------------------------------------
    def submit(self, req: Request) -> int:
        """Journal + admit. ``RequestShed`` / ``EngineSaturated`` propagate
        (the journal records sheds; a saturated queue records nothing — the
        request never entered the system)."""
        try:
            self.engine.add_request(req)
        except RequestShed:
            self.stats["shed"] += 1
            self.journal.append("shed", rid=req.rid)
            raise
        self.journal.append("admit", **_admit_record(req))
        self.requests[req.rid] = req
        self._live[req.rid] = req
        self._meta[req.rid] = _admit_record(req)
        self._hwm[req.rid] = 0
        return req.rid

    def step(self) -> None:
        armed = self.watchdog is not None and self._grace <= 0
        if self._grace > 0:
            self._grace -= 1
        if armed:
            self.watchdog.arm(f"step:{getattr(self.engine, '_step_idx', 0)}")
        try:
            self.engine.step()
        except self._SUBMIT_ERRORS:
            if armed:
                self.watchdog.disarm()
            raise
        except Exception as e:  # engine state is untrusted from here on
            if armed:
                self.watchdog.disarm()
            if self.recoveries >= self.max_recoveries:
                raise
            self._recover(
                "PT-SRV-001",
                f"engine step raised {type(e).__name__}: {e}")
            return
        overran = self.watchdog.disarm() if armed else False
        if overran:
            tag, elapsed = self.watchdog.overruns[-1]
            if self.recoveries >= self.max_recoveries:
                raise RuntimeError(
                    f"PT-SRV-002: step {tag} stalled {elapsed:.3f}s past the "
                    f"{self.watchdog.budget_s:.3f}s budget and the recovery "
                    "budget is exhausted")
            self._recover(
                "PT-SRV-002",
                f"step {tag} overran its {self.watchdog.budget_s:.3f}s "
                f"budget ({elapsed:.3f}s) — engine presumed stuck")
            return
        self._sync_progress()

    def has_work(self) -> bool:
        return self.engine.has_work() or any(
            rid not in self._done for rid in self.requests)

    def run_until_done(self, max_steps: int = 100000) -> Dict[int, Request]:
        steps = 0
        while self.has_work() and steps < max_steps:
            self.step()
            steps += 1
        return self.finished()

    def finished(self) -> Dict[int, Request]:
        self._sync_progress()
        out, self._finished = self._finished, {}
        return out

    def set_step_budget(self, budget_s: Optional[float]) -> None:
        """(Re)arm the step watchdog — typically after a warmup wave has
        compiled the engine's programs, so the budget can be set from the
        measured warm step time rather than the compile time."""
        from ..distributed.resilience.watchdog import StepWatchdog

        if self.watchdog is not None:
            self.watchdog.close()
        self.watchdog = (StepWatchdog(budget_s)
                         if budget_s is not None else None)

    def close(self) -> None:
        if self.watchdog is not None:
            self.watchdog.close()
        self.journal.close()

    # -- progress / recovery ----------------------------------------------
    def _sync_progress(self) -> None:
        """Materialize pending tokens, move the per-request high-water
        marks forward in the journal, and surface completions. The journal
        mark advances only over MATERIALIZED tokens — those are the ones a
        streaming caller could have seen, so they are the ones recovery
        must never re-deliver (and must reproduce exactly)."""
        # drains pending readbacks AND the engine-side finished dict (kept
        # bounded); completion itself is tracked via the supervisor's maps
        self.engine.finished()
        for rid, user in self.requests.items():
            if rid in self._done or user is None:
                continue
            live = self._live.get(rid)
            if live is None:
                continue
            if live is not user and len(live.output) > len(user.output):
                user.output.extend(live.output[len(user.output):])
                user._n_out = len(user.output)
            n = len(user.output)
            if n > self._hwm[rid]:
                self.journal.append("prog", rid=rid, hwm=n,
                                    toks=user.output[self._hwm[rid]:])
                self._hwm[rid] = n
            if live.done:
                if live is not user:
                    user.done, user.failed = live.done, live.failed
                    user.error = live.error
                self.journal.append("fin", rid=rid, failed=bool(user.failed))
                self._done.add(rid)
                self._finished[rid] = user
                self._live.pop(rid, None)

    def _recover(self, code: str, msg: str, rebuild: bool = True) -> None:
        """Rebuild the engine and replay every unfinished journaled request
        on it: fresh block pool, empty radix cache, deadline clocks reset.
        Blocks until each replay has caught up to its delivered high-water
        mark (verified bit-for-bit), then returns — the service is back to
        its pre-crash state and normal stepping resumes."""
        t0 = time.monotonic()
        self.recoveries += 1
        self.stats["recoveries"] += 1
        self._grace = self.watchdog_grace_steps
        self.events.append((code, msg))
        if rebuild:
            self.journal.append("crash", code=code, msg=msg)
            self.engine = self._build()
        replaying: List[int] = []
        # backpressure was already charged at the original submit — a
        # max_queue smaller than the in-flight count must not refuse the
        # engine's own journaled work on replay
        saved_max_queue = self.engine.max_queue
        self.engine.max_queue = None
        for rec in self.journal.unfinished():
            rid = rec["rid"]
            if rid in self._done or rid not in self._meta:
                continue
            twin = _request_from(self._meta[rid])
            user = self.requests.get(rid)
            if user is None:
                # restart path: the twin IS the caller-facing object
                user = self.requests[rid] = twin
            else:
                # keep only the delivered prefix; the replay regenerates
                # (and must match) everything past it
                hwm = self._hwm.get(rid, 0)
                del user.output[hwm:]
                user._n_out = len(user.output)
                user.done = user.failed = False
                user.error = None
                user._engine = None
            self._live[rid] = twin
            self.engine.add_request(twin)
            replaying.append(rid)
        self.engine.max_queue = saved_max_queue
        self.stats["replayed_requests"] += len(replaying)
        # catch up to the delivered marks before declaring recovery done
        guard = 0
        while any(self._live[rid]._n_out < self._hwm.get(rid, 0)
                  and not self._live[rid].done for rid in replaying):
            try:
                self.engine.step()
            except Exception as e:
                # a crash DURING the replay itself still draws on the same
                # recovery budget — a back-to-back double fault must be
                # absorbed, not escape half-replayed
                if self.recoveries >= self.max_recoveries:
                    raise
                self._recover(
                    code, f"engine crashed again during replay "
                    f"({type(e).__name__}: {e})")
                return
            guard += 1
            if guard > 100000:
                raise RuntimeError(
                    "recovery replay did not reach the journaled high-water "
                    "marks — engine is not making progress")
        self.engine._drain_pending()
        for rid in replaying:
            twin, user = self._live[rid], self.requests[rid]
            hwm = self._hwm.get(rid, 0)
            delivered = list(user.output[:hwm] if user is not twin
                             else self.journal.delivered(rid))
            # a twin that failed short of the mark (e.g. its deadline
            # expired AGAIN during the compile-heavy catch-up) is an
            # ordinary request failure, not a data-integrity alarm — so
            # only the prefix it actually regenerated is held to the
            # bit-identity contract; ending early WITHOUT failing, or
            # emitting different tokens, is real divergence
            n = min(len(twin.output), hwm)
            if (twin.output[:n] != delivered[:n]
                    or (twin.done and not twin.failed
                        and len(twin.output) < hwm)):
                user.done = user.failed = True
                user.error = (
                    f"PT-SRV-005: replay diverged from the delivered stream "
                    f"at rid={rid} — {twin.output[:hwm][:8]}... vs "
                    f"{delivered[:8]}...")
                self.events.append(("PT-SRV-005", user.error))
                self.journal.append("fin", rid=rid, failed=True)
                self._done.add(rid)
                self._finished[rid] = user
                self._live.pop(rid, None)
            elif twin.failed:
                if user is not twin:
                    user.done, user.failed = True, True
                    user.error = twin.error
                self.journal.append("fin", rid=rid, failed=True)
                self._done.add(rid)
                self._finished[rid] = user
                self._live.pop(rid, None)
            elif user is twin and hwm:
                # restart path: the twin regenerated the delivered prefix
                # itself; nothing to splice
                pass
        dt = time.monotonic() - t0
        self.stats["recovery_s"] += dt
        self.journal.append("recovered", code=code, n=len(replaying),
                            seconds=round(dt, 6))
