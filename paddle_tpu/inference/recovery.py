"""Crash-recoverable serving: request journal + supervisor (docs/SERVING.md).

The continuous-batching engine is the most stateful component in the repo —
a paged KV pool, a radix prefix cache, chunked-prefill slots, device-side
token carries. None of that state is durable, and none of it needs to be:
every admitted request is fully described by its prompt ids, sampling
params, seed and deadline, and the engine's sample keys are stateless
(``fold_in(key(seed), position)`` — models/generation_utils.py). So the
recovery unit is the REQUEST, not the engine: journal what was admitted and
how far each stream got, and an engine crash costs a rebuild + replay that
is **bit-identical** to the uninterrupted run (greedy and seeded sampling,
including requests past a copy-on-write divergence point — warm==cold
bit-identity means a fresh pool and an empty radix cache cannot change a
single token).

Components:

- :class:`RequestJournal` — append-only, per-record crc32-checked journal
  (the same torn-write posture as distributed/checkpoint/integrity.py: a
  crash mid-append leaves a torn TAIL, which loading tolerates; corruption
  in the middle of the journal raises :class:`JournalCorrupt` naming the
  record). Records: ``admit`` (full request parameters), ``prog`` (the
  emitted-token high-water mark plus the token ids themselves, so replay
  can verify bit-identity even across a process restart), ``fin``,
  ``migr`` (migrated to another replica — fleet drain), ``migr-kv``
  (finished-prefill KV chain migrated to a decode-tier replica, with the
  chain digest — inference/disagg.py), ``shed``, ``crash``/``recovered``
  markers. Appends can be BATCHED off the hot
  path: ``defer`` buffers encoded records in memory and ``flush`` writes
  them in one syscall — the supervisor defers its per-step ``prog``
  records and flushes once per step, BEFORE any token is surfaced to a
  caller's stream, so the on-disk journal always covers everything a
  streaming client could have seen (the recovery guarantee is unchanged;
  only the write count per step collapsed).
- :class:`ServingSupervisor` — owns the engine via a ``build_engine``
  factory. The engine works on private TWIN request objects; the caller's
  ``Request`` receives tokens only at the post-flush splice, which is what
  makes the flush barrier real. ``submit`` journals then admits; ``step``
  arms a :class:`~paddle_tpu.distributed.resilience.watchdog.StepWatchdog`
  around the engine step and, on a crash (any exception out of ``step`` —
  e.g. the ``serving.step`` ``kill`` fault) or a watchdog overrun
  (``serving.stall``), rebuilds: fresh engine, fresh block pool, empty
  radix cache, every unfinished journaled request re-admitted and
  replayed. Tokens already delivered (journaled high-water mark) are NOT
  re-delivered: the replay catches up to the mark, verifies the
  regenerated prefix matches the delivered one byte-for-byte (PT-SRV-005
  on divergence), and streams on from there. ``submit(req, resume=True)``
  exposes the same dedup for requests arriving with an already-delivered
  prefix from ANOTHER replica's journal — the fleet failover path
  (inference/fleet.py).

Deadline semantics across recovery: a re-admitted request's deadline clock
RESTARTS at re-admission (the journal stores the deadline *duration*) — an
engine fault is the operator's problem, not the request's.

PT-SRV diagnostic codes (docs/RESILIENCE.md):

========== ==============================================================
PT-SRV-001 engine crash absorbed — rebuilt from journal, requests replayed
PT-SRV-002 step watchdog overrun (stall) — flagged mid-hang, then rebuilt
PT-SRV-003 request shed at submit (``RequestShed`` — serving.py)
PT-SRV-004 journal corruption (:class:`JournalCorrupt` names the record)
PT-SRV-005 replay divergence: recovered prefix != delivered prefix
PT-SRV-006 brownout entered/exited (engine stats — serving.py)
PT-SRV-008 mesh degraded (:class:`MeshDegraded` — device-group loss):
           engine resharded to the widest surviving tp width, requests
           replayed bit-identically (docs/RESILIENCE.md "Elastic
           serving mesh")
========== ==============================================================
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Callable, Dict, List, Optional, Set

from .serving import (ContinuousBatchingEngine, MeshDegraded, Request,
                      RequestShed)

__all__ = ["JournalCorrupt", "RequestJournal", "ServingSupervisor"]


class JournalCorrupt(RuntimeError):
    """PT-SRV-004: a journal record failed its crc (or decode) somewhere
    other than the torn tail — the file was damaged after it was written."""


class RequestJournal:
    """Append-only, crc-checked request journal.

    One record per line: ``<crc32 of payload, 8 hex chars> <json payload>``.
    ``append`` flushes to the OS per record; ``defer`` + ``flush`` batch
    many records into one write+flush — the hot-path mode (``fsync=True``
    additionally forces flushes to disk — crash-safe across power loss; the
    default survives process death, which is the serving failure mode the
    supervisor drills).

    Loading tolerates a torn final record (a crash mid-append) by
    truncating to the last good record; a bad crc anywhere EARLIER raises
    :class:`JournalCorrupt` naming the line — silent damage never replays.
    """

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = bool(fsync)
        self._buf: List[bytes] = []
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        if os.path.exists(path):
            self.records, good = self._load_bytes(path)
            # drop a torn tail NOW: appending after partial bytes would
            # weld the next record onto them — mid-file corruption on the
            # following load instead of a tolerated torn append
            if good < os.path.getsize(path):
                with open(path, "r+b") as f:
                    f.truncate(good)
        else:
            self.records = []
        self._fh = open(path, "ab")

    @staticmethod
    def load(path: str) -> List[dict]:
        return RequestJournal._load_bytes(path)[0]

    @staticmethod
    def _load_bytes(path: str):
        """Parse the journal; returns ``(records, good_byte_length)`` where
        the length covers every intact record (a torn tail is excluded)."""
        out: List[dict] = []
        good = 0
        with open(path, "rb") as f:
            lines = f.read().split(b"\n")
        for i, line in enumerate(lines):
            if not line:
                # the split's final element (after the last newline) is
                # always empty; a blank line with records AFTER it is
                # damage — skipping it would make ``good`` undercount the
                # file offset, and the constructor's truncate(good) would
                # then chop bytes off a committed record
                if any(lines[j] for j in range(i + 1, len(lines))):
                    raise JournalCorrupt(
                        f"PT-SRV-004: journal {path} record {i + 1}: blank "
                        "line — records after it exist, so this is damage, "
                        "not a torn append")
                break
            bad = None
            if len(line) < 10 or line[8:9] != b" ":
                bad = "malformed record"
            else:
                payload = line[9:]
                try:
                    want = int(line[:8], 16)
                except ValueError:
                    want, bad = -1, "malformed crc"
                if bad is None and (zlib.crc32(payload) & 0xFFFFFFFF) != want:
                    bad = "crc mismatch"
                if bad is None:
                    try:
                        out.append(json.loads(payload.decode("utf-8")))
                        good += len(line) + 1
                        continue
                    except (UnicodeDecodeError, json.JSONDecodeError):
                        bad = "undecodable payload"
            # damage in the tail record = torn append -> tolerated (the
            # record never committed); damage earlier = corruption
            if any(lines[j] for j in range(i + 1, len(lines))):
                raise JournalCorrupt(
                    f"PT-SRV-004: journal {path} record {i + 1}: {bad} — "
                    "records after it exist, so this is damage, not a torn "
                    "append")
            break
        return out, good

    def defer(self, kind: str, **fields) -> None:
        """Buffer one record in memory (visible immediately via
        :attr:`records` — in-process recovery always sees it). Nothing
        reaches the file until :meth:`flush`; callers own the barrier:
        flush BEFORE acting on anything a crash must be able to replay."""
        rec = {"k": kind}
        rec.update(fields)
        payload = json.dumps(rec, separators=(",", ":")).encode("utf-8")
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        self._buf.append(b"%08x " % crc + payload + b"\n")
        self.records.append(rec)

    def flush(self) -> None:
        """Write every deferred record in ONE syscall and flush to the OS
        (+fsync when configured) — the durability barrier."""
        if not self._buf:
            return
        self._fh.write(b"".join(self._buf))
        self._buf.clear()
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())

    def append(self, kind: str, **fields) -> None:
        self.defer(kind, **fields)
        self.flush()

    @staticmethod
    def pending(records: List[dict]) -> List[dict]:
        """Admit records with no matching terminal
        (``fin``/``migr``/``migr-kv``) record — the ONE definition of the
        replay set, shared by :meth:`unfinished` and the fleet's
        journal-backed failover. A ``migr-kv`` chain handoff ends this
        journal's responsibility exactly like a drain ``migr``: replaying
        it here while the decode tier serves it would double-serve."""
        done = {r["rid"] for r in records
                if r["k"] in ("fin", "migr", "migr-kv")}
        return [r for r in records
                if r["k"] == "admit" and r["rid"] not in done]

    def unfinished(self) -> List[dict]:
        """The replay set (a migrated request is another replica's
        responsibility)."""
        return self.pending(self.records)

    def delivered(self, rid: int) -> List[int]:
        """Token ids journaled as delivered for ``rid`` (concatenated
        ``prog`` deltas) — the prefix replay must reproduce exactly."""
        toks: List[int] = []
        for r in self.records:
            if r["k"] == "prog" and r["rid"] == rid:
                toks.extend(r["toks"])
        return toks

    def close(self) -> None:
        self.flush()
        self._fh.close()

    def abandon(self) -> None:
        """Close WITHOUT flushing — process-death simulation (fleet drills):
        deferred-but-unflushed records die with the process, exactly like a
        kill between defer and flush would lose them. The flush barrier
        guarantees no surfaced token is among them."""
        self._buf.clear()
        self._fh.close()


def _admit_record(req: Request) -> dict:
    return {"rid": req.rid, "prompt": [int(t) for t in req.prompt],
            "max_new": req.max_new_tokens, "eos": req.eos_token_id,
            "temp": req.temperature, "top_p": req.top_p, "top_k": req.top_k,
            "seed": req.seed, "deadline_s": req.deadline_s,
            "priority": req.priority, "tenant": req.tenant}


def _request_from(rec: dict) -> Request:
    r = Request(rec["prompt"], max_new_tokens=rec["max_new"],
                eos_token_id=rec["eos"], temperature=rec["temp"],
                top_p=rec["top_p"], top_k=rec["top_k"], seed=rec["seed"],
                deadline_s=rec["deadline_s"], priority=rec["priority"],
                # .get(): pre-observatory journals carry no tenant field
                tenant=rec.get("tenant"))
    # twins and restart-reconstructions carry the ORIGINAL rid: the journal,
    # the engine bookkeeping and the fleet's routing table all key on it
    r.rid = rec["rid"]
    return r


class ServingSupervisor:
    """Crash-recoverable driver over a :class:`ContinuousBatchingEngine`.

    >>> sup = ServingSupervisor(lambda: ContinuousBatchingEngine(model, ...),
    ...                         journal_path, step_budget_s=2.0)
    >>> sup.submit(Request(prompt, max_new_tokens=64))
    >>> done = sup.run_until_done()

    The engine decodes into private TWIN objects; the caller's ``Request``
    receives tokens only after the step's journal records are flushed (the
    barrier that makes the on-disk high-water mark always cover everything
    a streaming client saw). Across a crash the streams continue
    bit-identically (the supervisor replays on a rebuilt engine, verifies
    the regenerated prefix against the delivered one, and appends only the
    new tokens). A supervisor constructed over an EXISTING journal (process
    restart) re-admits every unfinished request automatically; their
    reconstructed ``Request`` objects live in :attr:`requests`.

    ``max_recoveries`` bounds the rebuild budget (a crash loop must
    eventually surface, not mask); ``max_recoveries=0`` disables recovery —
    the fault-drill's control arm.

    ``step_budget_s`` must comfortably exceed a WARM step (compile-heavy
    first steps otherwise read as stalls, and every rebuild recompiles —
    a false-positive cascade that burns the whole recovery budget). Warm
    the engine first, then arm via :meth:`set_step_budget`.
    """

    #: exceptions that are caller errors, never engine-state damage
    _SUBMIT_ERRORS = (ValueError,)

    def __init__(self, build_engine: Callable[[], ContinuousBatchingEngine],
                 journal_path: str, step_budget_s: Optional[float] = None,
                 max_recoveries: int = 2, watchdog_grace_steps: int = 4,
                 fsync: bool = False, tracer=None,
                 trace_tags: Optional[dict] = None, elastic: bool = True):
        from ..distributed.resilience.watchdog import StepWatchdog

        self._build = build_engine
        # observability (docs/OBSERVABILITY.md): the supervisor owns the
        # tracer attachment because the engine is factory-built (and
        # REBUILT on recovery) — every new engine gets the same recorder,
        # so one request's spans stay in one timeline across crashes
        self.tracer = tracer
        self.trace_tags = dict(trace_tags or {})
        # a rebuilt engine recompiles its programs, and a compile-heavy
        # step is indistinguishable from a stall — without grace, one real
        # stall cascades into false positives that burn the whole recovery
        # budget. The first N steps after every rebuild run unarmed.
        self.watchdog_grace_steps = int(watchdog_grace_steps)
        self._grace = 0
        self.journal = RequestJournal(journal_path, fsync=fsync)
        self.requests: Dict[int, Request] = {}   # rid -> caller-facing req
        self._live: Dict[int, Request] = {}      # rid -> twin in the engine
        self._meta: Dict[int, dict] = {}         # rid -> admit record
        # rids whose twin started BEHIND the delivered mark (recovery or a
        # resume submission): the regenerated prefix must byte-match the
        # delivered one before anything new is surfaced (PT-SRV-005)
        self._verify: Set[int] = set()
        self._done: set = set()
        self._finished: Dict[int, Request] = {}
        self.events: List[tuple] = []            # (code, message)
        self.recoveries = 0
        self.max_recoveries = int(max_recoveries)
        self.watchdog = (StepWatchdog(step_budget_s)
                         if step_budget_s is not None else None)
        # elastic=False is the mesh-degrade CONTROL arm: a MeshDegraded
        # out of the engine escapes instead of resharding, and every
        # in-flight request is lost with the device group
        self.elastic = bool(elastic)
        self._build_mesh_aware: Optional[bool] = None
        self.stats = {"shed": 0, "recoveries": 0, "recovery_s": 0.0,
                      "replayed_requests": 0, "mesh_reshards": 0,
                      "mesh_degraded": 0}
        self.engine = build_engine()
        self._attach_tracer()
        # rids are assigned by a PER-PROCESS counter; a restart over an
        # existing journal resets it, so a fresh submit could collide with
        # a journaled rid (a stale "fin" would then mask the new request
        # from replay, and delivered() would merge two requests' tokens).
        # Bump the counter past every journaled rid before any submit.
        if self.journal.records:
            Request._counter[0] = max(
                Request._counter[0],
                max(r["rid"] for r in self.journal.records if "rid" in r))
        pending = self.journal.unfinished()
        if pending:
            # process restart over a live journal: replay now. The caller's
            # original Request objects are gone with the old process; the
            # reconstructed ones (exposed via .requests) carry the streams.
            for rec in pending:
                self._meta[rec["rid"]] = rec
            self._recover("PT-SRV-001",
                          f"journal restart: {len(pending)} unfinished "
                          "request(s) found", rebuild=False)

    def _attach_tracer(self) -> None:
        if self.tracer is not None:
            self.engine.tracer = self.tracer
            self.engine.trace_tags = dict(self.trace_tags)

    # -- public API --------------------------------------------------------
    def submit(self, req: Request, resume: bool = False) -> int:
        """Journal + admit (a private twin carrying the same rid enters the
        engine). ``RequestShed`` / ``EngineSaturated`` propagate (the
        journal records sheds; a saturated queue records nothing — the
        request never entered the system).

        ``resume=True``: ``req.output`` already holds tokens delivered by a
        previous engine/replica (fleet failover). They are journaled as
        this supervisor's high-water mark, the twin regenerates them from
        scratch, and nothing new surfaces until the regenerated prefix
        byte-matches the delivered one (PT-SRV-005 on divergence) — the
        caller's stream continues exactly where it left off."""
        meta = _admit_record(req)
        twin = _request_from(meta)
        if resume and self.tracer is not None:
            # raise the streamed-token dedup floor BEFORE the twin admits:
            # catch-up regeneration below the delivered mark re-streams
            # nothing the caller doesn't already have, and every span from
            # here on carries recovered=true
            self.tracer.mark_recovered(req.rid, len(req.output),
                                       self.trace_tags)
        if resume:
            # journaled work is never refused: backpressure AND feasibility
            # shedding were already charged at the ORIGINAL submit — a
            # busy survivor must absorb another replica's rescued request,
            # not shed it (the deadline clock restarts at re-admission)
            saved_q = self.engine.max_queue
            saved_shed = self.engine.shed_infeasible
            self.engine.max_queue = None
            self.engine.shed_infeasible = False
            try:
                self.engine.add_request(twin)
            finally:
                self.engine.max_queue = saved_q
                self.engine.shed_infeasible = saved_shed
        else:
            try:
                self.engine.add_request(twin)
            except RequestShed:
                self.stats["shed"] += 1
                self.journal.append("shed", rid=req.rid)
                raise
        self.journal.defer("admit", **meta)
        if resume and req.output:
            self.journal.defer("prog", rid=req.rid, hwm=len(req.output),
                               toks=[int(t) for t in req.output])
            self._verify.add(req.rid)
        self.journal.flush()
        req._n_out = len(req.output)
        self.requests[req.rid] = req
        self._live[req.rid] = twin
        self._meta[req.rid] = meta
        return req.rid

    def step(self) -> None:
        armed = self.watchdog is not None and self._grace <= 0
        if self._grace > 0:
            self._grace -= 1
        if armed:
            self.watchdog.arm(f"step:{getattr(self.engine, '_step_idx', 0)}")
        try:
            self.engine.step()
        except self._SUBMIT_ERRORS:
            if armed:
                self.watchdog.disarm()
            raise
        except MeshDegraded as e:
            # device-group loss is DISTINCT from an engine crash: the
            # journal is intact and the surviving devices can still serve
            # — reshard to the widest surviving width and replay, instead
            # of rebuilding at a width that no longer exists. elastic=False
            # (or an exhausted budget, or a factory that cannot build
            # narrower) lets it escape: the control arm, requests lost.
            if armed:
                self.watchdog.disarm()
            if not self.elastic or self.recoveries >= self.max_recoveries:
                raise
            self._degrade(e)
            return
        except Exception as e:  # engine state is untrusted from here on
            if armed:
                self.watchdog.disarm()
            if self.recoveries >= self.max_recoveries:
                raise
            self._recover(
                "PT-SRV-001",
                f"engine step raised {type(e).__name__}: {e}")
            return
        overran = self.watchdog.disarm() if armed else False
        if overran:
            tag, elapsed = self.watchdog.overruns[-1]
            if self.recoveries >= self.max_recoveries:
                raise RuntimeError(
                    f"PT-SRV-002: step {tag} stalled {elapsed:.3f}s past the "
                    f"{self.watchdog.budget_s:.3f}s budget and the recovery "
                    "budget is exhausted")
            self._recover(
                "PT-SRV-002",
                f"step {tag} overran its {self.watchdog.budget_s:.3f}s "
                f"budget ({elapsed:.3f}s) — engine presumed stuck")
            return
        self._sync_progress()

    def has_work(self) -> bool:
        return self.engine.has_work() or any(
            rid not in self._done for rid in self.requests)

    def run_until_done(self, max_steps: int = 100000) -> Dict[int, Request]:
        steps = 0
        while self.has_work() and steps < max_steps:
            self.step()
            steps += 1
        return self.finished()

    def finished(self) -> Dict[int, Request]:
        # control-plane refresh: engine.finished() also snapshots the retry
        # registry into engine.stats — here (per collection), not per step
        self.engine.finished()
        self._sync_progress()
        out, self._finished = self._finished, {}
        return out

    def load(self) -> int:
        """Requests currently in this supervisor's engine (queued + slotted
        + mid-prefill) — the fleet router's balancing signal."""
        eng = self.engine
        # O(1): the engine's occupied-slot counter, not a max_batch scan —
        # the router calls this per submit, and a 256-slot fleet would
        # otherwise pay replicas * max_batch python work per request
        return len(eng._queue) + eng.active_slots()

    def progress(self) -> tuple:
        """Progress marker for the fleet heartbeat. Changes whenever any
        stream advances, a request completes, the engine is rebuilt, or
        the load changes (so an idle-to-busy transition resets the
        staleness clock — idleness must not count against the wedge ttl);
        a supervisor with work whose marker sits still is wedged in a way
        step completion cannot show (e.g. every slot deferring forever on
        a stuck admission)."""
        return (id(self.engine), self.engine._sched_tokens,
                len(self._done), self.load())

    def behind(self, rid: int) -> bool:
        """True while ``rid``'s engine twin has regenerated fewer tokens
        than the caller's delivered mark and is still running — the fleet
        failover's catch-up condition (``_failover`` steps the survivor
        until no resumed rid is behind). Part of the replica surface a
        process-replica proxy (inference/procfleet) mirrors over the
        wire."""
        twin = self._live.get(rid)
        user = self.requests.get(rid)
        if twin is None or user is None:
            return False
        return twin._n_out < len(user.output) and not twin.done

    def withdraw(self, rid: int) -> Optional[dict]:
        """Pull a still-QUEUED request out of the engine (fleet drain
        migration): journals ``migr`` — this journal's responsibility for
        the request ends — and returns its admit record so the caller can
        resubmit it elsewhere. None when the request is already active
        (in-flight work finishes on this replica) or done."""
        twin = self._live.get(rid)
        if rid in self._done or twin is None:
            return None
        if not self.engine.withdraw_queued(rid):
            return None
        self.journal.append("migr", rid=rid)
        self._live.pop(rid, None)
        self._verify.discard(rid)
        self.requests.pop(rid, None)
        return self._meta.pop(rid, None)

    # -- disaggregated-tier KV migration (inference/disagg.py) -------------
    def submit_migrated(self, req: Request, artifact: bytes, codec) -> int:
        """Accept a migrated finished-prefill chain: splice its KV pages
        into this supervisor's engine and resume decode at the recorded
        position. Journals the admit + the delivered high-water mark
        AFTER the splice lands (same ordering as :meth:`submit`): a
        refusal — ``EngineSaturated`` on slot/pool shortfall, typed
        ``KVChainCorrupt`` (PT-SRV-007) on a crc/digest mismatch —
        propagates with no journal trace, so the caller can retry
        elsewhere or fall back to re-running prefill.

        The twin CONTINUES the stream in place (its output is pre-seeded
        with the delivered tokens) — nothing regenerates, so there is no
        PT-SRV-005 verification window. A crash AFTER this lands replays
        from the journaled admit through the ordinary recovery path: the
        rebuilt engine re-runs prefill and verifies the delivered prefix
        byte-for-byte — "re-run prefill", never double-serve."""
        meta = _admit_record(req)
        twin = _request_from(meta)
        twin.output = [int(t) for t in req.output]
        twin._n_out = len(twin.output)
        codec.import_chain(self.engine, artifact, req=twin)
        self.journal.defer("admit", **meta)
        if req.output:
            self.journal.defer("prog", rid=req.rid, hwm=len(req.output),
                               toks=[int(t) for t in req.output])
        self.journal.flush()
        req._n_out = len(req.output)
        self.requests[req.rid] = req
        self._live[req.rid] = twin
        self._meta[req.rid] = meta
        return req.rid

    def retire_migrated(self, rid: int, digest: str) -> Optional[dict]:
        """The KV-migration handoff's source side: journal ``migr-kv``
        (this journal's responsibility for ``rid`` ends — failover over
        this journal must not re-serve it) and release the ACTIVE slot
        (pages decref'd; the chain bytes were exported first). Returns the
        admit record, mirroring :meth:`withdraw`."""
        self.journal.append("migr-kv", rid=rid, digest=str(digest))
        self.engine.withdraw_active(rid)
        self._live.pop(rid, None)
        self._verify.discard(rid)
        self.requests.pop(rid, None)
        return self._meta.pop(rid, None)

    def set_step_budget(self, budget_s: Optional[float]) -> None:
        """(Re)arm the step watchdog — typically after a warmup wave has
        compiled the engine's programs, so the budget can be set from the
        measured warm step time rather than the compile time."""
        from ..distributed.resilience.watchdog import StepWatchdog

        if self.watchdog is not None:
            self.watchdog.close()
        self.watchdog = (StepWatchdog(budget_s)
                         if budget_s is not None else None)

    def close(self) -> None:
        if self.watchdog is not None:
            self.watchdog.close()
        self.journal.close()

    def abandon(self) -> None:
        """Process-death simulation (fleet replica kill): release the fd
        and watchdog WITHOUT flushing deferred records — recovery must work
        from what the flush barrier guaranteed is on disk."""
        if self.watchdog is not None:
            self.watchdog.close()
        self.journal.abandon()

    # -- progress / recovery ----------------------------------------------
    def _sync_progress(self) -> None:
        """Advance the caller-visible streams: drain the engine into the
        twins, journal the per-request deltas (ONE buffered write), flush,
        and only then splice tokens / completion into the caller's
        objects. The flush-before-surface ordering is the recovery
        contract: every token a streaming caller could have seen is on
        disk, so recovery never re-delivers and must reproduce exactly."""
        # drains pending readbacks AND the engine-side finished dict (kept
        # bounded); completion itself is tracked via the supervisor's maps.
        # Deliberately NOT engine.finished(): that also snapshots the retry
        # registry — control-plane work this per-step path must not pay
        self.engine._drain_pending()
        self.engine._finished.clear()
        updates: List[tuple] = []
        # iterate the LIVE twins, not every request ever submitted: this
        # runs per step, and a long-lived supervisor accumulates finished
        # rids in self.requests without bound (O(live) beats O(lifetime))
        for rid, twin in list(self._live.items()):
            if rid in self._done:
                continue
            user = self.requests.get(rid)
            if user is None:
                continue
            n_user = len(user.output)
            n_twin = len(twin.output)
            if rid in self._verify:
                if n_twin < n_user and not twin.done:
                    continue            # still catching up: surface nothing
                k = min(n_twin, n_user)
                # a twin that failed short of the mark (e.g. its deadline
                # expired AGAIN during the compile-heavy catch-up) is an
                # ordinary request failure, not a data-integrity alarm — so
                # only the prefix it actually regenerated is held to the
                # bit-identity contract; ending early WITHOUT failing, or
                # emitting different tokens, is real divergence
                if (twin.output[:k] != user.output[:k]
                        or (twin.done and not twin.failed
                            and n_twin < n_user)):
                    err = (f"PT-SRV-005: replay diverged from the delivered "
                           f"stream at rid={rid} — {twin.output[:k][:8]}... "
                           f"vs {user.output[:8]}...")
                    self.events.append(("PT-SRV-005", err))
                    self.journal.defer("fin", rid=rid, failed=True)
                    if self.tracer is not None:
                        # a twin that never completed through the engine's
                        # _mark_done needs its terminal stamped here or the
                        # lane never closes; a twin that DID finish (done
                        # but diverged, or ended early clean) already has
                        # one — record the divergence without stamping a
                        # second terminal
                        if self.tracer.is_open(rid):
                            self.tracer.finish(rid, len(user.output),
                                               failed=True, error=err,
                                               kind="fail",
                                               tags=self.trace_tags)
                        else:
                            self.tracer.instant("replay_divergence", rid,
                                                self.trace_tags,
                                                error=err[:200])
                    updates.append((rid, user, [], True, True, err))
                    continue
                if n_twin >= n_user:
                    self._verify.discard(rid)
            new = twin.output[n_user:] if n_twin > n_user else []
            if new:
                self.journal.defer("prog", rid=rid, hwm=n_twin,
                                   toks=[int(t) for t in new])
            if twin.done:
                self.journal.defer("fin", rid=rid, failed=bool(twin.failed))
                updates.append((rid, user, new, True, twin.failed,
                                twin.error))
            elif new:
                updates.append((rid, user, new, False, False, None))
        # FLUSH BARRIER: nothing below becomes caller-visible until its
        # journal record is past the OS write
        self.journal.flush()
        for rid, user, new, done, failed, error in updates:
            if new:
                user.output.extend(new)
                user._n_out = len(user.output)
            if done:
                user.done = True
                user.failed = bool(failed)
                user.error = error
                self._done.add(rid)
                self._finished[rid] = user
                self._live.pop(rid, None)
                self._verify.discard(rid)

    def _degrade(self, e: MeshDegraded) -> None:
        """PT-SRV-008 reshard-and-resume (docs/RESILIENCE.md "Elastic
        serving mesh"): pick the widest surviving tp width that still
        divides BOTH head counts (falling to unsharded when none does),
        harvest the degraded engine's column shards host-side ONCE,
        rebuild through the width-aware factory, re-split the same bytes
        along the same output dims, and replay every unfinished journaled
        request — streams stay bit-equal to an uninterrupted run because
        the reshard moves bytes, never values."""
        from ..distributed.auto_parallel.serving_sharding import (
            adopt_resharded_params, harvest_param_shards)

        if self._build_mesh_aware is None:
            import inspect

            try:
                params = inspect.signature(self._build).parameters
                self._build_mesh_aware = (
                    "mesh_tp" in params
                    or any(p.kind is inspect.Parameter.VAR_KEYWORD
                           for p in params.values()))
            except (TypeError, ValueError):
                self._build_mesh_aware = False
        if not self._build_mesh_aware:
            # the factory cannot build at a different width — the degrade
            # is unservable; let the typed signal escape to the operator
            raise e
        eng = self.engine
        old_tp = (int(eng.mesh.tp)
                  if getattr(eng, "mesh", None) is not None else 1)
        cfg = eng.model.config
        heads = [int(getattr(cfg, f)) for f in
                 ("num_attention_heads", "num_key_value_heads")
                 if getattr(cfg, f, None) is not None]
        new_tp: Optional[int] = None
        for w in range(max(0, int(e.survivors)), 1, -1):
            if all(h % w == 0 for h in heads):
                new_tp = w
                break
        # the old shards are an exact partition of the full weights —
        # gather them host-side once, BEFORE the degraded engine goes away
        host = harvest_param_shards(eng)
        builder = (lambda: adopt_resharded_params(
            self._build(mesh_tp=new_tp), host))
        self.stats["mesh_reshards"] += 1
        self.stats["mesh_degraded"] = 1
        t0_tr = None if self.tracer is None else self.tracer.now()
        self._recover(
            "PT-SRV-008",
            f"mesh degraded: lost {e.lost} device(s) from tp={old_tp} — "
            + (f"resharding to tp={new_tp}" if new_tp is not None else
               f"{e.survivors} survivor(s) divide no head count — "
               "falling back to unsharded"),
            builder=builder)
        if self.tracer is not None:
            # ok=False on fall-to-unsharded: the service survived but the
            # replica lost its sharding entirely — dashboards must see it
            self.tracer.span("mesh_degrade", None, t0_tr,
                             tags=self.trace_tags,
                             ok=new_tp is not None, old_tp=old_tp,
                             new_tp=int(new_tp or 1), lost=int(e.lost))

    def _recover(self, code: str, msg: str, rebuild: bool = True,
                 builder: Optional[Callable[
                     [], ContinuousBatchingEngine]] = None) -> None:
        """Rebuild the engine and replay every unfinished journaled request
        on it: fresh block pool, empty radix cache, deadline clocks reset.
        Blocks until each replay has caught up to its delivered high-water
        mark (verified bit-for-bit), then returns — the service is back to
        its pre-crash state and normal stepping resumes."""
        t0 = time.monotonic()
        t0_tr = None if self.tracer is None else self.tracer.now()
        self.recoveries += 1
        self.stats["recoveries"] += 1
        self._grace = self.watchdog_grace_steps
        self.events.append((code, msg))
        if rebuild:
            self.journal.append("crash", code=code, msg=msg)
            self.engine = (builder or self._build)()
            self._attach_tracer()
        replaying: List[int] = []
        # backpressure and feasibility shedding were already charged at the
        # original submit — neither a max_queue smaller than the in-flight
        # count nor a cold post-rebuild decode-rate estimate may refuse the
        # engine's own journaled work on replay
        saved_max_queue = self.engine.max_queue
        saved_shed = self.engine.shed_infeasible
        self.engine.max_queue = None
        self.engine.shed_infeasible = False
        try:
            for rec in self.journal.unfinished():
                rid = rec["rid"]
                if rid in self._done or rid not in self._meta:
                    continue
                user = self.requests.get(rid)
                if user is None:
                    # restart path: reconstruct the caller-facing object;
                    # its delivered prefix comes straight from the journal
                    user = self.requests[rid] = _request_from(
                        self._meta[rid])
                    user.output.extend(self.journal.delivered(rid))
                    user._n_out = len(user.output)
                user.done = user.failed = False
                user.error = None
                twin = _request_from(self._meta[rid])
                self._live[rid] = twin
                if user.output:
                    self._verify.add(rid)
                if self.tracer is not None:
                    self.tracer.mark_recovered(rid, len(user.output),
                                               self.trace_tags)
                self.engine.add_request(twin)
                replaying.append(rid)
        finally:
            self.engine.max_queue = saved_max_queue
            self.engine.shed_infeasible = saved_shed
        self.stats["replayed_requests"] += len(replaying)
        # catch up to the delivered marks before declaring recovery done
        guard = 0
        while any(self._live[rid]._n_out < len(self.requests[rid].output)
                  and not self._live[rid].done for rid in replaying
                  if rid in self._live):
            try:
                self.engine.step()
            except Exception as e:
                # a crash DURING the replay itself still draws on the same
                # recovery budget — a back-to-back double fault must be
                # absorbed, not escape half-replayed
                if self.recoveries >= self.max_recoveries:
                    raise
                self._recover(
                    code, f"engine crashed again during replay "
                    f"({type(e).__name__}: {e})", builder=builder)
                return
            guard += 1
            if guard > 100000:
                raise RuntimeError(
                    "recovery replay did not reach the journaled high-water "
                    "marks — engine is not making progress")
        # verification + splicing run through the one sync path
        self._sync_progress()
        dt = time.monotonic() - t0
        self.stats["recovery_s"] += dt
        if self.tracer is not None:
            self.tracer.recovery(t0_tr, code, len(replaying),
                                 tags=self.trace_tags)
        self.journal.append("recovered", code=code, n=len(replaying),
                            seconds=round(dt, 6))
