"""Fleet-level serving: a replica router over journaled engine supervisors.

One :class:`~paddle_tpu.inference.recovery.ServingSupervisor` makes one
engine survive crashes, stalls and overload (docs/SERVING.md) — but a
single replica is still a single point of failure and a ceiling on
traffic. :class:`FleetRouter` manages N supervisor-wrapped replicas and
makes them behave like one reliable engine (ROADMAP open item 1; the
reference's predictor-pool/multi-stream inference layer is the shape, the
journal/watchdog/shedding machinery of PRs 2-5 is the substrate):

- **Routing** — radix-cache affinity: the router remembers which replica
  holds each prompt's page-aligned prefix chain and routes same-prefix
  sessions there (warm KV blocks, no recompute), UNLESS that replica's
  queue is ``queue_slack`` deeper than the best candidate — affinity never
  beats balance by more than a bounded margin. Everything else spreads to
  the least-loaded replica (deterministic rid-based tie-break). A replica
  refusing admission (``EngineSaturated``/``RequestShed``) falls through
  to the next candidate before the refusal reaches the caller.
- **Failover** (PT-FLT-001) — a replica death (an exception escaping its
  supervisor, a ``fleet.replica_kill`` fault, or heartbeat staleness) is
  absorbed by re-admitting the dead replica's unfinished requests on
  survivors, read from its ON-DISK journal (journal-backed: the router's
  memory is not trusted). Dedup rides the delivered high-water marks: the
  survivor regenerates each delivered prefix, verifies it byte-for-byte
  (PT-SRV-005 on divergence) and streams on — the caller's token stream
  is byte-identical to an uninterrupted run (warm==cold bit-identity is
  what makes a different replica's fresh cache emit the same tokens).
- **Rolling drain/restart** (PT-FLT-002) — ``drain(i)`` stops routing to
  a replica, migrates its still-QUEUED requests to survivors (journaled
  ``migr`` — they would otherwise wait out the whole drain), lets
  in-flight slots finish in place, then rebuilds the replica with a fresh
  journal and rejoins it. ``rolling_restart()`` walks the fleet one
  replica at a time — zero-downtime updates, zero failed or duplicated
  tokens.
- **Fleet brownout/shedding** (PT-FLT-003/004) — per-replica pressure is
  aggregated: ONE hot replica is simply avoided by routing (and degrades
  itself via its engine-level brownout, docs/SERVING.md) — the fleet only
  enters brownout when EVERY alive replica sits at depth, and then sheds
  sheddable-priority requests at submit with a typed ``RequestShed``
  (hysteretic exit, same discipline as the engine brownout).

Fault sites (docs/RESILIENCE.md): ``fleet.replica_kill`` (kill = replica
process death mid-step), ``fleet.drain`` (kill = operator drain signal).
``tools/fault_drill.py`` drills all three fleet classes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Set

from .recovery import RequestJournal, ServingSupervisor, _request_from
from .serving import (ContinuousBatchingEngine, EngineSaturated, Request,
                      RequestShed)

__all__ = ["FleetConfig", "FleetRouter", "ReplicaState"]


class ReplicaState:
    ALIVE = "alive"
    DRAINING = "draining"
    DEAD = "dead"
    #: drained and REMOVED from service (autoscaler scale-in): unlike DEAD
    #: there is no work to rescue and no respawn — the slot is simply gone
    RETIRED = "retired"


#: states excluded from routing, stepping, load and completion accounting
_GONE = (ReplicaState.DEAD, ReplicaState.RETIRED)


@dataclasses.dataclass
class FleetConfig:
    """Router knobs (:class:`FleetRouter` — docs/SERVING.md fleet section).

    - ``affinity``: route same-prefix sessions to the replica whose radix
      cache holds the blocks (off = pure load spread).
    - ``queue_slack``: affinity yields to balance once the warm replica is
      this many requests deeper than the least-loaded one.
    - ``heartbeat_ttl_s``: a replica that still has work but whose
      PROGRESS marker (scheduled tokens + completions) has not advanced
      for this long is declared dead. The supervisor's step watchdog
      catches a step that HANGS; this heartbeat catches the wedge it
      cannot — steps that keep returning without moving any stream
      forward (e.g. a pool wedged behind a stuck admission, every slot
      deferring forever).
    - ``brownout_depth``: per-replica load (queued+slotted) that counts as
      pressure; default = the engine's ``max_queue`` (or ``2*max_batch``
      when unbounded).
    - ``brownout_enter_after`` / ``brownout_exit_after``: hysteresis, in
      consecutive pressure(-free) events.
    - ``shed_priority``: minimum ``Request.priority`` value shed during
      fleet brownout (default: LOW traffic sheds, interactive survives).
    - ``prefix_map_cap``: bound on remembered prefix chains (oldest drop).
    - ``parallel_step``: step replicas in threads — jax dispatches are
      async so replica programs overlap; keep False for deterministic
      drills/tests. Enable only once every replica is WARM (its programs
      compiled by a first wave): replicas share one model object, and
      concurrent first-compile TRACING over shared state is unsafe
      (jax ``UnexpectedTracerError``); replaying compiled programs from
      threads is fine.
    """

    affinity: bool = True
    queue_slack: int = 2
    heartbeat_ttl_s: float = 60.0
    brownout_depth: Optional[int] = None
    brownout_enter_after: int = 2
    brownout_exit_after: int = 4
    shed_priority: int = Request.PRIORITY_LOW
    prefix_map_cap: int = 4096
    parallel_step: bool = False


class _Replica:
    def __init__(self, idx: int, sup: ServingSupervisor, journal_path: str,
                 gen: int = 0, tier: str = "serving"):
        self.idx = idx
        self.sup = sup
        self.journal_path = journal_path
        self.state = ReplicaState.ALIVE
        self.gen = gen
        self.tier = tier                # "serving" | "prefill" | "decode"
        self.retiring = False           # drain completes into RETIRED
        self.progress = None            # supervisor progress marker
        self.last_progress_t = time.monotonic()


class FleetRouter:
    """N supervisor-wrapped engine replicas behaving like one reliable
    engine (module docstring; docs/SERVING.md fleet state machine).

    >>> fleet = FleetRouter(build_engine, fleet_dir, num_replicas=3)
    >>> fleet.submit(Request(prompt, max_new_tokens=64))
    >>> done = fleet.run_until_done()

    ``failover=False`` is the drill's control arm: a replica death marks
    its in-flight requests failed instead of re-admitting them.
    ``graceful_drain=False`` models a deployment that restarts replicas
    WITHOUT draining: the drain signal becomes a hard kill (state
    discarded, no migration) followed by a cold respawn.
    """

    def __init__(self, build_engine: Callable[[], ContinuousBatchingEngine],
                 fleet_dir: str, num_replicas: int = 2,
                 step_budget_s: Optional[float] = None,
                 max_recoveries: int = 2, failover: bool = True,
                 graceful_drain: bool = True,
                 config: Optional[FleetConfig] = None, fsync: bool = False,
                 tracer=None):
        if num_replicas < 1:
            raise ValueError("num_replicas must be >= 1")
        self._build = build_engine
        self.fleet_dir = fleet_dir
        os.makedirs(fleet_dir, exist_ok=True)
        self.config = config or FleetConfig()
        self.failover = bool(failover)
        self.graceful_drain = bool(graceful_drain)
        # ONE TraceRecorder across the fleet: every supervisor/engine stamps
        # with a replica tag (pid = replica in the chrome trace), and a
        # failed-over request's spans continue in the same lane
        self.tracer = tracer
        self._sup_kw = dict(step_budget_s=step_budget_s,
                            max_recoveries=max_recoveries, fsync=fsync)
        # stats exist BEFORE the first _make_sup: subclasses that spawn
        # real worker processes (inference/procfleet) count spawns there
        self.stats = {"submitted": 0, "fleet_shed": 0, "replica_deaths": 0,
                      "failovers": 0, "failover_s": 0.0,
                      "failover_requests": 0, "drains": 0, "migrated": 0,
                      "restarts": 0, "brownouts": 0, "affinity_hits": 0,
                      "replicas_added": 0, "replicas_retired": 0}
        self.events: List[tuple] = []                # (code, message)
        self.replicas: List[_Replica] = []
        try:
            for i in range(num_replicas):
                # restart over an existing fleet_dir: resume each
                # replica's LATEST generation — rolling restarts leave
                # g1/g2/... journals and replaying a superseded g0 would
                # lose the newer work
                gen = self._latest_gen(i)
                path = os.path.join(fleet_dir, f"replica{i}.g{gen}.jrnl")
                self.replicas.append(_Replica(
                    i, self._make_sup(i, path), path, gen=gen,
                    tier=self.tier_of(i)))
        except Exception:
            # a replica that failed to build must not strand the ones
            # already built (a process-replica fleet would otherwise leak
            # live worker processes until interpreter exit)
            for rep in self.replicas:
                try:
                    rep.sup.abandon()
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass
            raise
        self.requests: Dict[int, Request] = {}
        self._assigned: Dict[int, int] = {}          # rid -> replica idx
        self._returned: Set[int] = set()
        self._prefix_map: Dict[bytes, int] = {}      # chain digest -> idx
        self._step_idx = 0
        self._brownout_active = False
        self._pressure_events = 0
        self._clear_events = 0
        self._brownout_forced = False
        self._fault_hook = None
        self._fault_cls = None

    def _trace_lost(self, rid: int, user: Request, replica: int) -> None:
        """Terminal stamp for a lost request — guarded like recovery.py's
        divergence path: the engine may have terminal'd the rid in the
        very step the replica died (twin finished, result never spliced);
        a second terminal would break the one-terminal invariant, so that
        case records a non-terminal ``request_lost`` event instead."""
        if self.tracer is None:
            return
        if self.tracer.is_open(rid):
            self.tracer.finish(rid, len(user.output), failed=True,
                               error=user.error, kind="fail",
                               tags={"replica": replica})
        else:
            self.tracer.instant("request_lost", rid,
                                tags={"replica": replica},
                                error=(user.error or "")[:200])

    def _make_sup(self, idx: int, path: str):
        """Build the replica-``idx`` supervisor over journal ``path`` — the
        ONE construction point (initial fleet, ``_respawn``,
        ``add_replica``). The process-per-replica fleet
        (inference/procfleet) overrides this to spawn a worker process and
        return a :class:`~paddle_tpu.inference.procfleet.ProcReplica`
        proxy; everything else in the router consumes the same replica
        surface (submit/step/finished/load/progress/withdraw/behind/
        close/abandon + ``.engine`` geometry)."""
        return ServingSupervisor(self._builder(idx), path,
                                 **self._rep_kw(idx))

    def _builder(self, idx: int) -> Callable[[], ContinuousBatchingEngine]:
        """Engine factory for replica ``idx`` — one homogeneous fleet by
        default; the :class:`~paddle_tpu.inference.disagg.TieredRouter`
        overrides this with per-tier factories (tier membership)."""
        return self._build

    def tier_of(self, idx: int) -> str:
        """Tier label for replica ``idx`` (``"serving"`` in a flat fleet;
        the TieredRouter partitions into ``"prefill"``/``"decode"``)."""
        return "serving"

    def _rep_kw(self, idx: int) -> dict:
        kw = dict(self._sup_kw)
        if self.tracer is not None:
            kw.update(tracer=self.tracer, trace_tags={"replica": idx})
        return kw

    def _latest_gen(self, idx: int) -> int:
        best = 0
        pat = re.compile(rf"replica{idx}\.g(\d+)\.jrnl$")
        for name in os.listdir(self.fleet_dir):
            mm = pat.fullmatch(name)
            if mm:
                best = max(best, int(mm.group(1)))
        return best

    def _retire_journal(self, path: str, migrated: List[int],
                        failed: List[int]) -> None:
        """Mark rescued/lost rids in a dead replica's ON-DISK journal so a
        router restarted over this fleet_dir does not replay work that is
        now owned by survivors (``migr``) or was deliberately lost
        (``fin`` failed) — double service, not recovery."""
        if not (migrated or failed):
            return
        j = RequestJournal(path)
        try:
            for rid in migrated:
                j.defer("migr", rid=rid)
            for rid in failed:
                j.defer("fin", rid=rid, failed=True)
            j.flush()
        finally:
            j.close()

    # -- submission / routing ----------------------------------------------
    def submit(self, req: Request) -> int:
        """Route + admit. ``RequestShed``/``EngineSaturated`` reach the
        caller only once EVERY routable replica refused (or the fleet is
        in brownout and the request's class is sheddable)."""
        self._fleet_shed_check(req)
        candidates = self._route_order(req)
        if not candidates:
            raise EngineSaturated("fleet has no alive replica")
        last: Optional[Exception] = None
        for rep, warm in candidates:
            try:
                rep.sup.submit(req)
            except (EngineSaturated, RequestShed) as e:
                last = e
                continue
            self.stats["submitted"] += 1
            if warm:
                self.stats["affinity_hits"] += 1
            self.requests[req.rid] = req
            self._assigned[req.rid] = rep.idx
            self._register_prefix(req.prompt, rep.idx)
            # sustained all-replicas-full submission pressure counts toward
            # fleet brownout even between steps
            self._pressure_event(self._fleet_pressured())
            return req.rid
        self._pressure_event(True)
        raise last

    def _fleet_shed_check(self, req: Request) -> None:
        if (self._brownout_active
                and req.priority >= self.config.shed_priority):
            self.stats["fleet_shed"] += 1
            if self.tracer is not None:
                # shed before any replica saw it — the tracer books the
                # implicit submit so the lifecycle still closes (tenant
                # tag included: fleet sheds count against that tenant's
                # attainment in the SLO monitor)
                self.tracer.shed(
                    req.rid,
                    tags=({"tenant": req.tenant} if req.tenant is not None
                          else None),
                    reason="fleet brownout")
            raise RequestShed(
                f"PT-FLT-003: fleet brownout — priority {req.priority} "
                f"request rid={req.rid} shed at submit (every replica at "
                "depth); retry later or raise the priority")

    def _routable(self, req: Request) -> List[_Replica]:
        """Replicas eligible to admit a NEW submission — the whole alive
        fleet here; the TieredRouter narrows this to the prefill tier."""
        return [r for r in self.replicas if r.state == ReplicaState.ALIVE]

    def _route_order(self, req: Request):
        """Candidate replicas, best first, as ``(replica, is_warm)``:
        affinity target (bounded by ``queue_slack``), then least-loaded
        with a deterministic rid-based tie-break so equal-load replicas
        share the traffic."""
        alive = self._routable(req)
        if not alive:
            return []
        # capacity-weighted load: a process replica whose mesh shrank
        # under an elastic degrade (ProcReplica.capacity_weight < 1)
        # reads proportionally busier, so new work drifts toward
        # full-width survivors — no failover, no churn, just weighting
        loads = {
            r.idx: r.sup.load()
            / max(getattr(r.sup, "capacity_weight", lambda: 1.0)(), 1e-6)
            for r in alive}
        n = len(alive)
        order = sorted(alive, key=lambda r: (loads[r.idx],
                                             (r.idx - req.rid) % n))
        warm_idx = None
        if self.config.affinity and not self._brownout_active:
            warm_idx = self._affinity_lookup(req.prompt)
        if warm_idx is not None:
            warm = next((r for r in alive if r.idx == warm_idx), None)
            if (warm is not None and loads[warm.idx]
                    <= loads[order[0].idx] + self.config.queue_slack):
                order = [warm] + [r for r in order if r is not warm]
                return [(r, r is warm) for r in order]
        return [(r, False) for r in order]

    def _chain_keys(self, prompt) -> List[bytes]:
        """One digest per full prompt page, each covering the whole prefix
        up to and including that page — computed with a single incremental
        hasher (O(pages), not O(pages^2))."""
        page = self.replicas[0].sup.engine.page_size
        n_full = len(prompt) // page
        if not n_full:
            return []
        raw = bytes(memoryview(prompt[: n_full * page]).cast("B"))
        bpp = page * prompt.itemsize
        h = hashlib.blake2b(digest_size=8)
        keys = []
        for k in range(n_full):
            h.update(raw[k * bpp:(k + 1) * bpp])
            keys.append(h.copy().digest())
        return keys

    def _register_prefix(self, prompt, idx: int) -> None:
        for key in self._chain_keys(prompt):
            self._prefix_map.pop(key, None)      # re-insert: newest-last
            self._prefix_map[key] = idx
        while len(self._prefix_map) > self.config.prefix_map_cap:
            self._prefix_map.pop(next(iter(self._prefix_map)))

    def _affinity_lookup(self, prompt) -> Optional[int]:
        best = None
        for key in self._chain_keys(prompt):
            idx = self._prefix_map.get(key)
            if idx is None:
                break
            best = idx
        return best

    def _drop_prefixes(self, idx: int) -> None:
        self._prefix_map = {k: v for k, v in self._prefix_map.items()
                            if v != idx}

    # -- stepping / health -------------------------------------------------
    def step(self) -> None:
        """One fleet tick: drain signals, one supervisor step per live
        replica, staleness checks, failover for the newly dead, drain
        completion, brownout hysteresis."""
        if self._fault_hook is None:
            from ..distributed.resilience.faults import (FaultInjected,
                                                         maybe_inject)

            self._fault_hook = maybe_inject
            self._fault_cls = FaultInjected
        self._step_idx += 1
        for rep in self.replicas:
            if rep.state in _GONE:
                continue
            try:
                self._fault_hook("fleet.drain",
                                 f"replica:{rep.idx}:step:{self._step_idx}")
            except self._fault_cls:
                self.drain(rep.idx)
        live = [r for r in self.replicas
                if r.state in (ReplicaState.ALIVE, ReplicaState.DRAINING)]
        died = self._step_all(live)
        now = time.monotonic()
        for rep in live:
            if rep.state == ReplicaState.DEAD or rep in died:
                continue
            try:
                sig = rep.sup.progress()
            except Exception as e:  # noqa: BLE001 — replica death boundary
                # a process replica can die BETWEEN its step and this
                # probe (inference/procfleet): the probe failing is the
                # death signal, same boundary as _step_all
                self._mark_dead(rep, f"progress probe failed: "
                                f"{type(e).__name__}: {e}")
                died.append(rep)
                continue
            if sig != rep.progress:
                rep.progress = sig
                rep.last_progress_t = now
            elif (rep.sup.has_work() and now - rep.last_progress_t
                    > self.config.heartbeat_ttl_s):
                self._mark_dead(
                    rep, "heartbeat stale: steps complete but no stream has "
                    f"advanced for {now - rep.last_progress_t:.1f}s "
                    f"(> ttl {self.config.heartbeat_ttl_s:.1f}s)")
                died.append(rep)
        for rep in died:
            self._handle_death(rep)
        for rep in self.replicas:
            if rep.state == ReplicaState.DRAINING and not rep.sup.has_work():
                self._finish_drain(rep)
        self._pressure_event(self._fleet_pressured())

    def _step_all(self, live: List[_Replica]) -> List[_Replica]:
        """Step every live replica; returns the ones that died doing it.
        ``parallel_step`` overlaps replicas in threads (jax dispatch is
        async; programs from different replicas interleave on the device),
        death handling stays sequential after the join."""
        errs: Dict[int, Exception] = {}

        def one(rep: _Replica):
            try:
                self._fault_hook(
                    "fleet.replica_kill",
                    f"replica:{rep.idx}:step:{self._step_idx}")
                rep.sup.step()
            except Exception as e:  # noqa: BLE001 — replica death boundary
                errs[rep.idx] = e

        if self.config.parallel_step and len(live) > 1:
            threads = [threading.Thread(target=one, args=(rep,), daemon=True)
                       for rep in live]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        else:
            for rep in live:
                one(rep)
        died = []
        for rep in live:
            if rep.idx in errs:
                e = errs[rep.idx]
                self._mark_dead(rep, f"{type(e).__name__}: {e}")
                died.append(rep)
        return died

    def _mark_dead(self, rep: _Replica, why: str) -> None:
        rep.state = ReplicaState.DEAD
        rep.sup.abandon()       # fd + watchdog released, NO flush: the
        #                         on-disk journal is what failover trusts
        self._drop_prefixes(rep.idx)    # its cache died with it — stale
        #                                 affinity would route cold misses
        self.stats["replica_deaths"] += 1
        self.events.append(
            ("PT-FLT-001", f"replica {rep.idx} died: {why}"))

    def _handle_death(self, rep: _Replica) -> None:
        if self.failover:
            self._failover(rep)
            return
        # control arm (drill): the dead replica's in-flight requests are
        # simply lost — surfaced as failures so callers don't hang
        lost = []
        for rid, idx in list(self._assigned.items()):
            user = self.requests.get(rid)
            if idx != rep.idx or user is None or user.done:
                continue
            user.done = user.failed = True
            user.error = (f"PT-FLT-001: replica {rep.idx} died and failover "
                          "is disabled — request lost")
            self._trace_lost(rid, user, rep.idx)
            lost.append(rid)
        self._retire_journal(rep.journal_path, [], lost)

    # -- failover ----------------------------------------------------------
    def _failover(self, dead: _Replica) -> None:
        """Re-admit the dead replica's unfinished requests on survivors,
        from its ON-DISK journal. Streamed-token dedup rides the journaled
        high-water marks (``submit(resume=True)``): each survivor
        regenerates the delivered prefix, verifies it byte-for-byte and
        streams on — byte-identical to an uninterrupted run."""
        t0 = time.monotonic()
        recs = RequestJournal.load(dead.journal_path)
        pending = RequestJournal.pending(recs)
        resumed: List[tuple] = []
        for rec in pending:
            rid = rec["rid"]
            user = self.requests.get(rid)
            if user is None:
                # router restarted over existing journals: reconstruct the
                # caller-facing object from the admit record
                user = self.requests[rid] = _request_from(rec)
            if user.done:
                continue
            # the on-disk delivered prefix is authoritative (the flush
            # barrier ran before anything was surfaced, so normally these
            # are equal — reconcile in its favor regardless)
            delivered = [t for r in recs
                         if r["k"] == "prog" and r["rid"] == rid
                         for t in r["toks"]]
            if [int(t) for t in user.output] != delivered:
                user.output[:] = delivered
            user._n_out = len(user.output)
            user.done = user.failed = False
            user.error = None
            target = self._pick_survivor(req=user, exclude={dead.idx})
            if target is None:
                user.done = user.failed = True
                user.error = ("PT-FLT-001: no surviving replica to fail "
                              f"over rid={rid} to")
                self._trace_lost(rid, user, dead.idx)
                continue
            # resume=True: journaled work is never refused — the supervisor
            # disables backpressure AND feasibility shedding for it (both
            # were charged at the original submit)
            if self.tracer is not None:
                # the failover EDGE: which journal the request came from
                # and which survivor continues its stream
                self.tracer.failover(rid, dead.idx, target.idx)
            target.sup.submit(user, resume=True)
            self._assigned[rid] = target.idx
            self._register_prefix(user.prompt, target.idx)
            resumed.append((target, rid))
        # mark ownership movement in the dead journal: a router restarted
        # over this fleet_dir must not replay rescued (or lost) work
        self._retire_journal(
            dead.journal_path, [rid for _, rid in resumed],
            [r["rid"] for r in pending
             if self.requests.get(r["rid"]) is not None
             and self.requests[r["rid"]].failed])
        # catch each survivor up to the delivered marks before the fleet
        # resumes normal ticking — recovery ends with the streams whole
        for target in {t for t, _ in resumed}:
            rids = [rid for t, rid in resumed if t is target]
            guard = 0
            while any(target.sup.behind(rid) for rid in rids):
                target.sup.step()
                guard += 1
                if guard > 100000:
                    raise RuntimeError(
                        "failover replay did not reach the journaled "
                        "high-water marks on replica "
                        f"{target.idx}")
        dt = time.monotonic() - t0
        self.stats["failovers"] += 1
        self.stats["failover_s"] += dt
        self.stats["failover_requests"] += len(resumed)
        self.events.append(
            ("PT-FLT-001",
             f"failover: {len(resumed)} request(s) from replica "
             f"{dead.idx}'s journal re-admitted on survivors in {dt:.2f}s"))

    def _pick_survivor(self, req: Request,
                       exclude: Set[int] = frozenset()) -> Optional[_Replica]:
        alive = [r for r in self.replicas
                 if r.state == ReplicaState.ALIVE and r.idx not in exclude]
        if not alive:
            return None
        n = len(alive)
        return min(alive, key=lambda r: (r.sup.load(),
                                         (r.idx - req.rid) % n))

    # -- drain / rolling restart ------------------------------------------
    def drain(self, idx: int) -> None:
        """Stop routing to replica ``idx``, migrate its still-queued
        requests to survivors, let in-flight slots finish in place. The
        replica rebuilds and rejoins automatically once idle (observed by
        ``step``). ``graceful_drain=False`` deployments hard-kill instead —
        the control arm showing what drains exist to prevent."""
        rep = self.replicas[idx]
        if rep.state != ReplicaState.ALIVE:
            return
        self.stats["drains"] += 1
        if not self.graceful_drain:
            self._mark_dead(rep, "hard restart without drain "
                            "(graceful_drain=False)")
            # no failover on a hard restart: the operator replaced the
            # process without migrating — exactly the lost-work mode the
            # graceful path exists to prevent
            lost = []
            for rid, aidx in list(self._assigned.items()):
                user = self.requests.get(rid)
                if aidx != idx or user is None or user.done:
                    continue
                user.done = user.failed = True
                user.error = ("PT-FLT-002: replica hard-restarted without "
                              "drain — request lost")
                self._trace_lost(rid, user, idx)
                lost.append(rid)
            self._retire_journal(rep.journal_path, [], lost)
            self._respawn(rep)
            return
        rep.state = ReplicaState.DRAINING
        self._drop_prefixes(idx)        # its cache dies with the restart
        migrated = 0
        for rid, aidx in list(self._assigned.items()):
            if aidx != idx:
                continue
            user = self.requests.get(rid)
            if user is None or user.done:
                continue
            rec = rep.sup.withdraw(rid)
            if rec is None:
                continue                # active in a slot: finishes here
            target = self._pick_survivor(user, exclude={idx})
            if target is None:
                # single-replica fleet: nothing to migrate to — hand it
                # back to the draining replica (finishes before restart)
                target = rep
            # resume=True: migrated work is never refused (supervisor
            # disables backpressure + shedding for it)
            target.sup.submit(user, resume=True)
            self._assigned[rid] = target.idx
            migrated += 1
        self.stats["migrated"] += migrated
        self.events.append(
            ("PT-FLT-002", f"replica {idx} draining: {migrated} queued "
             "request(s) migrated, in-flight slots finishing in place"))

    def _finish_drain(self, rep: _Replica) -> None:
        rep.sup.close()
        if rep.retiring:
            # scale-in (autoscale.py): the drain migrated/finished every
            # request — remove the replica instead of respawning it
            rep.retiring = False
            rep.state = ReplicaState.RETIRED
            self.stats["replicas_retired"] += 1
            self.events.append(
                ("PT-FLT-005", f"replica {rep.idx} retired after drain "
                 "(scale-in)"))
            return
        self._respawn(rep)
        self.events.append(
            ("PT-FLT-002", f"replica {rep.idx} rebuilt and rejoined "
             f"(generation {rep.gen})"))

    def _respawn(self, rep: _Replica) -> None:
        rep.gen += 1
        rep.journal_path = os.path.join(
            self.fleet_dir, f"replica{rep.idx}.g{rep.gen}.jrnl")
        rep.sup = self._make_sup(rep.idx, rep.journal_path)
        rep.state = ReplicaState.ALIVE
        rep.retiring = False
        rep.progress = None
        rep.last_progress_t = time.monotonic()
        self.stats["restarts"] += 1

    def restart(self, idx: int) -> None:
        """Cold-respawn a DEAD replica (failover already rescued its work;
        a fresh journal avoids replaying requests survivors now own)."""
        rep = self.replicas[idx]
        if rep.state != ReplicaState.DEAD:
            raise ValueError(f"replica {idx} is {rep.state}, not dead — "
                             "use drain() for live replicas")
        self._respawn(rep)
        self.events.append(
            ("PT-FLT-002", f"replica {idx} restarted after death "
             f"(generation {rep.gen})"))

    # -- autoscaling hooks (inference/autoscale.py — PT-FLT-005) ----------
    def add_replica(self) -> int:
        """Grow the fleet by one supervisor-wrapped replica, built through
        the SAME factory/journal path as the originals (a scaled-up
        replica is failover-, drain- and restart-capable from birth). The
        new replica starts cold (empty cache, uncompiled programs) and is
        immediately routable. Returns its index."""
        idx = len(self.replicas)
        gen = self._latest_gen(idx)
        path = os.path.join(self.fleet_dir, f"replica{idx}.g{gen}.jrnl")
        self.replicas.append(_Replica(
            idx, self._make_sup(idx, path), path, gen=gen,
            tier=self.tier_of(idx)))
        self.stats["replicas_added"] += 1
        self.events.append(
            ("PT-FLT-005", f"replica {idx} added (scale-out: fleet now "
             f"{sum(1 for r in self.replicas if r.state not in _GONE)} "
             "serving replica(s))"))
        return idx

    def retire_replica(self, idx: int) -> bool:
        """Scale-in: drain replica ``idx`` (still-queued work migrates to
        survivors, in-flight slots finish in place) and REMOVE it once
        idle instead of respawning it. Refused (returns False) for the
        last serving replica or a replica that is not ALIVE; requires
        ``graceful_drain`` (a hard-restart deployment has no lossless
        scale-in path — use drain semantics or accept the loss
        explicitly)."""
        rep = self.replicas[idx]
        if rep.state != ReplicaState.ALIVE or not self.graceful_drain:
            return False
        alive = [r for r in self.replicas
                 if r.state == ReplicaState.ALIVE]
        if len(alive) <= 1:
            return False            # never retire the last replica
        rep.retiring = True
        self.drain(idx)
        return True

    def force_brownout(self, active: bool) -> None:
        """Controller override of the fleet brownout (autoscale.py at max
        replicas): while forced, the hysteretic pressure state machine is
        suspended — the controller owns the exit as well as the entry, so
        one pressure-free tick cannot undo a deliberate degradation."""
        if active and not self._brownout_active:
            self.stats["brownouts"] += 1
            self.events.append(
                ("PT-FLT-003", "fleet brownout FORCED (autoscaler at max "
                 "replicas): shedding priority >= "
                 f"{self.config.shed_priority} at submit"))
        elif not active and self._brownout_forced:
            self.events.append(
                ("PT-FLT-004", "forced fleet brownout released"))
        self._brownout_forced = bool(active)
        self._brownout_active = bool(active)
        self._pressure_events = self._clear_events = 0

    def rolling_restart(self, max_steps: int = 100000) -> None:
        """Drain + rebuild every replica, one at a time, under traffic —
        the zero-downtime update path (PT-FLT-002)."""
        for rep in list(self.replicas):
            if rep.state in _GONE:
                continue
            self.drain(rep.idx)
            guard = 0
            while rep.state == ReplicaState.DRAINING and guard < max_steps:
                self.step()
                guard += 1
            if rep.state == ReplicaState.DRAINING:
                raise RuntimeError(
                    f"replica {rep.idx} did not finish draining in "
                    f"{max_steps} fleet steps")

    # -- brownout ----------------------------------------------------------
    def _fleet_pressured(self) -> bool:
        alive = [r for r in self.replicas if r.state == ReplicaState.ALIVE]
        if not alive:
            return True
        depth = self.config.brownout_depth
        if depth is None:
            # load() counts queued AND slotted, so the threshold must too:
            # full slots + full queue (or an equal backlog when unbounded)
            # — plain slot utilization with an empty queue is healthy, not
            # pressure
            eng = alive[0].sup.engine
            depth = eng.max_batch + (eng.max_queue
                                     if eng.max_queue is not None
                                     else eng.max_batch)
        return min(r.sup.load() for r in alive) >= max(1, depth)

    def _pressure_event(self, pressured: bool) -> None:
        cfg = self.config
        if self._brownout_forced:
            return          # controller-owned: force_brownout(False) exits
        if self._brownout_active:
            if pressured:
                self._clear_events = 0
            else:
                self._clear_events += 1
                if self._clear_events >= cfg.brownout_exit_after:
                    self._brownout_active = False
                    self._pressure_events = self._clear_events = 0
                    self.events.append(
                        ("PT-FLT-004", "fleet brownout exited"))
            return
        if pressured:
            self._pressure_events += 1
            if self._pressure_events >= cfg.brownout_enter_after:
                self._brownout_active = True
                self._clear_events = 0
                self.stats["brownouts"] += 1
                self.events.append(
                    ("PT-FLT-004",
                     "fleet brownout entered: every alive replica at "
                     "depth — shedding priority >= "
                     f"{cfg.shed_priority} at submit"))
        else:
            self._pressure_events = 0

    # -- completion --------------------------------------------------------
    def has_work(self) -> bool:
        # no exception guard here: every replica surface answers
        # has_work() from local state (the process proxy serves it from
        # reply-piggybacked caches, never the wire) — a raise is a real
        # bug that must surface, not feed a silent busy-loop
        if any(rep.sup.has_work() for rep in self.replicas
               if rep.state not in _GONE):
            return True
        return any(not r.done for r in self.requests.values())

    def run_until_done(self, max_steps: int = 100000) -> Dict[int, Request]:
        steps = 0
        while self.has_work() and steps < max_steps:
            self.step()
            steps += 1
        return self.finished()

    def finished(self) -> Dict[int, Request]:
        for rep in self.replicas:
            if rep.state not in _GONE:
                rep.sup.finished()
        out = {rid: r for rid, r in self.requests.items()
               if r.done and rid not in self._returned}
        self._returned.update(out)
        return out

    def load(self) -> Dict[int, int]:
        """Per-replica load snapshot (queued + slotted), DEAD/RETIRED
        replicas excluded — the observability surface the balancer itself
        uses."""
        return {rep.idx: rep.sup.load() for rep in self.replicas
                if rep.state not in _GONE}

    def close(self) -> None:
        for rep in self.replicas:
            if rep.state not in _GONE:
                rep.sup.close()
