"""paddle_tpu.hub — hubconf-based model discovery and loading.

Parity anchor: python/paddle/hapi/hub.py (list at :185, help at :235,
load at :283) — a repo exposes entrypoints via a ``hubconf.py`` whose public
callables are the models; ``dependencies`` lists required import names.

This environment has no network egress, so ``source='local'`` (a directory
containing ``hubconf.py``) is fully supported; the github/gitee download path
raises a clear error instead of silently hanging.
"""

from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

MODULE_HUBCONF = "hubconf.py"
VAR_DEPENDENCY = "dependencies"


def _load_hubconf(repo_dir):
    path = os.path.join(os.path.expanduser(repo_dir), MODULE_HUBCONF)
    if not os.path.isfile(path):
        raise ValueError(f"no {MODULE_HUBCONF} found in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, os.path.dirname(path))
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(os.path.dirname(path))
    deps = getattr(mod, VAR_DEPENDENCY, [])
    missing = [d for d in deps if importlib.util.find_spec(d) is None]
    if missing:
        raise RuntimeError(f"hub repo requires missing packages: {missing}")
    return mod


def _check_source(source):
    if source not in ("github", "gitee", "local"):
        raise ValueError(
            f"source must be 'github', 'gitee' or 'local', got {source!r}")
    if source != "local":
        raise RuntimeError(
            "paddle_tpu.hub: remote sources need network egress, which this "
            "runtime does not have — clone the repo and use source='local'")


def list(repo_dir, source: str = "github", force_reload: bool = False,
         **kwargs):
    """All entrypoint names a hub repo exposes (hapi/hub.py:185)."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir, model: str, source: str = "github",
         force_reload: bool = False, **kwargs):
    """Docstring of one entrypoint (hapi/hub.py:235)."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"hub entrypoint {model!r} not found")
    return fn.__doc__


def load(repo_dir, model: str, source: str = "github",
         force_reload: bool = False, **kwargs):
    """Call an entrypoint and return its model (hapi/hub.py:283)."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"hub entrypoint {model!r} not found")
    return fn(**kwargs)
