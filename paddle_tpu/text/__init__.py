"""paddle_tpu.text (reference: python/paddle/text — viterbi_decode.py +
datasets/). Datasets are synthesized deterministically (zero-egress), keeping
the documented field shapes."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.op_registry import apply_fn
from ..core.tensor import Tensor, unwrap
from ..io.dataset import Dataset

__all__ = ["viterbi_decode", "ViterbiDecoder", "Imdb", "Imikolov",
           "UCIHousing", "Conll05st", "Movielens", "WMT14", "WMT16"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """CRF Viterbi decoding (reference: text/viterbi_decode.py:31).

    potentials: [B, T, N] unary emissions; transition_params: [N, N];
    lengths: [B]. Returns (scores [B], paths [B, T]).
    """

    def fn(emit, trans, lens):
        B, T, N = emit.shape
        if include_bos_eos_tag:
            # last two tags are BOS/EOS (reference convention): start from BOS
            alpha0 = emit[:, 0] + trans[N - 2][None]
        else:
            alpha0 = emit[:, 0]

        def step(carry, t):
            alpha = carry  # [B, N]
            scores = alpha[:, :, None] + trans[None]  # [B, from, to]
            best_prev = jnp.argmax(scores, axis=1)  # [B, N]
            alpha_new = jnp.max(scores, axis=1) + emit[:, t]
            keep = (t < lens)[:, None]
            alpha_new = jnp.where(keep, alpha_new, alpha)
            return alpha_new, best_prev

        alpha, back = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        if include_bos_eos_tag:
            alpha = alpha + trans[:, N - 1][None]
        last_tag = jnp.argmax(alpha, -1)  # [B]
        score = jnp.max(alpha, -1)

        # backtrack (reverse scan over the backpointers)
        def bt(carry, t):
            tag = carry
            prev = back[t]  # [B, N] pointers for transition t -> t+1
            new = jnp.take_along_axis(prev, tag[:, None], 1)[:, 0]
            new = jnp.where(t + 1 < lens, new, tag)
            return new, tag

        tag_final, tags_rev = jax.lax.scan(bt, last_tag,
                                           jnp.arange(T - 2, -1, -1))
        path = jnp.concatenate([tag_final[None], tags_rev[::-1]], 0).T
        # positions beyond each length keep the terminal tag; mask to 0
        mask = jnp.arange(T)[None] < lens[:, None]
        path = jnp.where(mask, path, 0)
        return score, path.astype(jnp.int64)

    return apply_fn("viterbi_decode", fn, potentials, transition_params,
                    lengths)


class ViterbiDecoder:
    """Layer-style wrapper (reference: text/viterbi_decode.py ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


# ---------------------------------------------------------------------------
# datasets (synthetic, deterministic)
# ---------------------------------------------------------------------------

class _SyntheticText(Dataset):
    vocab_size = 1000

    def __init__(self, mode: str = "train", size: int = 500, **kwargs):
        self.mode = mode
        self.size = size
        self._rng_seed = 0 if mode == "train" else 1

    def __len__(self):
        return self.size


class Imdb(_SyntheticText):
    """Sentiment classification: (word_ids [T], label 0/1)."""

    def __getitem__(self, idx):
        rng = np.random.default_rng((self._rng_seed, idx))
        label = int(rng.integers(0, 2))
        length = int(rng.integers(20, 100))
        # class-conditional token distribution so models can actually learn
        lo, hi = (0, self.vocab_size // 2) if label == 0 else (
            self.vocab_size // 2, self.vocab_size)
        doc = rng.integers(lo, hi, length).astype(np.int64)
        return doc, label


class Imikolov(_SyntheticText):
    """N-gram LM dataset: (context [N-1], next_word)."""

    def __init__(self, mode="train", data_type="NGRAM", window_size=5, **kw):
        super().__init__(mode, **kw)
        self.window_size = window_size

    def __getitem__(self, idx):
        rng = np.random.default_rng((self._rng_seed, idx))
        seq = rng.integers(0, self.vocab_size, self.window_size).astype(np.int64)
        return tuple(seq[:-1]) + (seq[-1],)


class UCIHousing(_SyntheticText):
    """Regression: (features [13], price [1]) with a learnable linear map."""

    _w = np.linspace(-1, 1, 13).astype(np.float32)

    def __getitem__(self, idx):
        rng = np.random.default_rng((self._rng_seed, idx))
        x = rng.standard_normal(13).astype(np.float32)
        y = np.array([x @ self._w + 0.1 * rng.standard_normal()], np.float32)
        return x, y


class Conll05st(_SyntheticText):
    """SRL simplified to (words [T], labels [T])."""

    n_labels = 20

    def __getitem__(self, idx):
        rng = np.random.default_rng((self._rng_seed, idx))
        length = int(rng.integers(5, 30))
        words = rng.integers(0, self.vocab_size, length).astype(np.int64)
        labels = rng.integers(0, self.n_labels, length).astype(np.int64)
        return words, labels


class Movielens(_SyntheticText):
    """Rating prediction: (user_id, movie_id, rating)."""

    def __getitem__(self, idx):
        rng = np.random.default_rng((self._rng_seed, idx))
        return (int(rng.integers(0, 6000)), int(rng.integers(0, 4000)),
                float(rng.integers(1, 6)))


class WMT14(_SyntheticText):
    """Translation: (src_ids [S], trg_ids [T], trg_next [T])."""

    def __getitem__(self, idx):
        rng = np.random.default_rng((self._rng_seed, idx))
        s, t = int(rng.integers(5, 30)), int(rng.integers(5, 30))
        src = rng.integers(0, self.vocab_size, s).astype(np.int64)
        trg = rng.integers(0, self.vocab_size, t).astype(np.int64)
        return src, trg, np.roll(trg, -1)


class WMT16(WMT14):
    pass
