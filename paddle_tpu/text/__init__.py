"""paddle_tpu.text — text datasets (reference: python/paddle/text). Round-1 stub."""
