"""paddle_tpu.jit — to_static / save / load (reference: python/paddle/jit/api.py:195).

TPU-native redesign: the reference needs two frontends (AST transpile + SOT bytecode
tracing, jit/dy2static + jit/sot) because its graph IR must be built from Python
control flow. Here "static mode" IS jax tracing: ``to_static(fn)`` functionalizes the
layer (parameters become inputs), traces once per input signature, and caches the XLA
executable. Training works through the tape: the whole compiled function is recorded
as ONE GradNode whose backward is a second cached XLA executable that rematerializes
the forward (jit-of-vjp) — fwd and bwd are each a single fused TPU program.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core import autograd_engine
from ..core.tensor import Tensor, unwrap
from ..nn.layer.layers import Layer


# global to_static switch (paddle.jit.enable_to_static): False -> every
# StaticFunction runs its original eager body
_to_static_enabled = [True]


def _collect_state(layer: Layer):
    """Ordered (names, tensors) for params + buffers."""
    names, tensors = [], []
    for n, p in layer.named_parameters():
        names.append("P:" + n)
        tensors.append(p)
    for n, b in layer.named_buffers():
        names.append("B:" + n)
        tensors.append(b)
    return names, tensors


class _Swap:
    """Temporarily substitute arrays into layer state (functionalization)."""

    def __init__(self, tensors: List[Tensor], arrays):
        self.tensors = tensors
        self.arrays = arrays
        self.saved = None

    def __enter__(self):
        self.saved = [t._data for t in self.tensors]
        for t, a in zip(self.tensors, self.arrays):
            t._data = a
        return self

    def __exit__(self, *exc):
        for t, s in zip(self.tensors, self.saved):
            t._data = s
        return False


def functional_call(layer: Layer, fn: Callable, state_arrays, *args, **kwargs):
    """Run ``fn`` with layer state replaced by ``state_arrays`` (a flat list)."""
    _, tensors = _collect_state(layer)
    with _Swap(tensors, state_arrays):
        return fn(*args, **kwargs)


def _tree_unwrap(x):
    return jax.tree_util.tree_map(
        lambda v: v._data if isinstance(v, Tensor) else v, x,
        is_leaf=lambda v: isinstance(v, Tensor),
    )


def _tree_wrap(x):
    return jax.tree_util.tree_map(
        lambda v: Tensor(v) if isinstance(v, (jax.Array,)) else v, x)


class StaticFunction:
    """A traced+compiled callable with Paddle's ``to_static`` UX."""

    def __init__(self, function, input_spec=None, build_strategy=None, backend=None, full_graph=True, property=False):
        self._orig_fn = function
        self._layer: Optional[Layer] = None
        if hasattr(function, "__self__") and isinstance(function.__self__, Layer):
            self._layer = function.__self__
        elif isinstance(function, Layer):
            self._layer = function
            self._orig_fn = function.forward
        self._input_spec = input_spec
        self._fwd_cache: Dict[Any, Callable] = {}
        self._bwd_cache: Dict[Any, Callable] = {}
        self._last_concrete = None
        # graph-break state (SOT parity, jit/sot translate.py fallback): when
        # full_graph=False and tracing fails on value-dependent Python control
        # flow, the function permanently falls back to eager execution
        self._full_graph = full_graph
        self._fallback_eager = False
        self._split_plan = None  # SOT-style partial graphs (partial_graph.py)
        self._bound_sig = None   # lazy inspect.signature for plan calls
        functools.update_wrapper(self, self._orig_fn)

    @property
    def _has_defaults(self):
        f = getattr(self._orig_fn, "__func__", self._orig_fn)
        return bool(getattr(f, "__defaults__", None))

    @property
    def forward(self):
        return self

    def _pure(self, static_kwargs):
        layer = self._layer
        fn = self._orig_fn

        if layer is None:
            def pure(state_arrays, in_arrays):
                with autograd_engine.no_grad():
                    out = fn(*_tree_wrap(in_arrays), **static_kwargs)
                return _tree_unwrap(out)
        else:
            _, tensors = _collect_state(layer)

            def pure(state_arrays, in_arrays):
                with autograd_engine.no_grad(), _Swap(tensors, state_arrays):
                    out = fn(*_tree_wrap(in_arrays), **static_kwargs)
                return _tree_unwrap(out)

        return pure

    def _positional(self, args, kwargs):
        """Normalize a call to positional order (the split plan's calling
        convention), applying signature defaults. Raises TypeError on
        signatures the splitter rejected anyway (*args/**kwargs)."""
        import inspect

        if self._bound_sig is None:
            self._bound_sig = inspect.signature(self._orig_fn)
        ba = self._bound_sig.bind(*args, **kwargs)
        ba.apply_defaults()
        return tuple(ba.arguments[p] for p in self._bound_sig.parameters)

    def _run_plan(self, args, kwargs):
        """Run the split plan; a NameError/UnboundLocalError from a
        synthesized piece (a prefix-stored name that this input path never
        defined, or a loop-carried var with no pre-loop binding) permanently
        reverts to whole-function eager (ADVICE r4). The failed partial
        execution is then re-run eagerly from the top — Python-level side
        effects it performed before failing repeat (side effects inside
        to_static functions are unsupported, as in the reference's SOT)."""
        if kwargs or self._has_defaults:
            # a TypeError here is a genuinely malformed call — same error
            # the eager function would raise; let it propagate
            args = self._positional(args, kwargs)
            kwargs = {}
        try:
            return self._split_plan(*args)
        except (NameError, UnboundLocalError) as e:
            import warnings

            warnings.warn(
                f"to_static: partial-graph plan for "
                f"{getattr(self._orig_fn, '__name__', '?')} failed at run "
                f"time ({type(e).__name__}: {e}) — reverting to eager.")
            self._split_plan = None
            self._fallback_eager = True
            return self._orig_fn(*args, **kwargs)

    def __call__(self, *args, **kwargs):
        if self._fallback_eager or not _to_static_enabled[0]:
            return self._orig_fn(*args, **kwargs)
        if self._split_plan is not None:
            return self._run_plan(args, kwargs)
        try:
            return self._compiled_call(*args, **kwargs)
        except (jax.errors.TracerBoolConversionError,
                jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError,
                jax.errors.TracerIntegerConversionError) as e:
            # graph break: value-dependent Python control flow inside the
            # traced region. The reference's SOT splits the bytecode at the
            # break and resumes compiled execution (sot/translate.py:31);
            # the jax-native equivalent splits the AST at the breaking top-
            # level statement: if -> eager condition bridge + per-branch
            # suffix graphs; while/for -> lax.while_loop lowering or an
            # eager loop bridge driving a compiled body subgraph
            # (jit/partial_graph.py). Breaks the splitter cannot express
            # fall back to whole-function eager execution.
            if self._full_graph:
                raise
            import warnings

            from .partial_graph import break_lineno_of, try_split

            fn = self._orig_fn
            plan = try_split(fn, break_lineno_of(e, fn), layer=self._layer)
            if plan is not None:
                warnings.warn(
                    f"to_static: graph break in "
                    f"{getattr(self._orig_fn, '__name__', '?')} "
                    f"({type(e).__name__}) — split into compiled subgraphs "
                    "with an eager bridge at the breaking statement "
                    "(SOT-style partial graphs).")
                self._split_plan = plan
                return self._run_plan(args, kwargs)
            warnings.warn(
                f"to_static: graph break in {getattr(self._orig_fn, '__name__', '?')} "
                f"({type(e).__name__}) — falling back to eager execution. "
                f"Use paddle.where / lax-style control flow to stay compiled.")
            self._fallback_eager = True
            return self._orig_fn(*args, **kwargs)

    def _compiled_call(self, *args, **kwargs):
        layer = self._layer
        state_tensors: List[Tensor] = []
        if layer is not None:
            _, state_tensors = _collect_state(layer)
        state_arrays = [t._data for t in state_tensors]

        in_tensors = [a for a in jax.tree_util.tree_leaves(
            args, is_leaf=lambda v: isinstance(v, Tensor)) if isinstance(a, Tensor)]
        in_arrays = _tree_unwrap(args)

        static_kwargs = {k: v for k, v in kwargs.items() if not isinstance(v, Tensor)}
        key = (len(state_arrays), tuple(sorted(static_kwargs.items())))

        if key not in self._fwd_cache:
            pure = self._pure(static_kwargs)
            self._fwd_cache[key] = jax.jit(pure)
            self._bwd_cache[key] = jax.jit(
                lambda state, ins, cots: jax.vjp(pure, state, ins)[1](cots)
            )
        f_fwd = self._fwd_cache[key]
        f_bwd = self._bwd_cache[key]

        record = autograd_engine.grad_enabled() and any(
            not t.stop_gradient for t in state_tensors + in_tensors
        ) and not any(isinstance(a, jax.core.Tracer) for a in state_arrays)

        out_arrays = f_fwd(state_arrays, in_arrays)
        out_leaves, out_tree = jax.tree_util.tree_flatten(out_arrays)
        out_tensors = [Tensor(o) for o in out_leaves]

        if record:
            diff_tensors = [
                t for t in state_tensors + in_tensors
                if jnp.issubdtype(t.dtype, jnp.floating)
            ]

            def vjp_fn(cots, _state=state_arrays, _ins=in_arrays, _tree=out_tree):
                cot_list = list(cots) if isinstance(cots, tuple) else [cots]
                cot_tree = jax.tree_util.tree_unflatten(_tree, cot_list)
                g_state, g_ins = f_bwd(_state, _ins, cot_tree)
                grads = []
                gs_flat = g_state
                gi_flat = jax.tree_util.tree_leaves(g_ins)
                all_tensors = state_tensors + in_tensors
                all_grads = list(gs_flat) + list(gi_flat)
                gmap = {id(t): g for t, g in zip(all_tensors, all_grads)}
                for t in diff_tensors:
                    grads.append(gmap.get(id(t)))
                return tuple(grads)

            node = autograd_engine.GradNode(
                "to_static", vjp_fn, diff_tensors,
                [(o.shape, o.dtype) for o in out_leaves],
            )
            for i, t in enumerate(out_tensors):
                t.stop_gradient = False
                t._node = node
                t._out_idx = i

        return jax.tree_util.tree_unflatten(out_tree, out_tensors)

    def cache_keys(self):
        """Introspection for the trace-hazard linter: one
        ``(n_state, static_kwargs)`` key per compiled variant. Many variants
        differing only in Python-scalar kwarg values mean the scalar is being
        captured by value and forcing a recompile per call (PT-TRACE-002)."""
        return list(self._fwd_cache.keys())

    def concrete_program(self):
        return self._last_concrete

    @property
    def code(self):
        import inspect

        try:
            return inspect.getsource(self._orig_fn)
        except Exception:
            return "<source unavailable>"


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, full_graph=True, **kwargs):
    """Decorator / wrapper turning a dygraph callable into a compiled one."""

    def decorate(fn):
        if isinstance(fn, Layer):
            sf = StaticFunction(fn, input_spec, build_strategy, backend, full_graph)
            fn.forward = sf
            return fn
        return StaticFunction(fn, input_spec, build_strategy, backend, full_graph)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


class TranslatedLayer(Layer):
    """Result of jit.load: a Layer driving an exported XLA computation."""

    def __init__(self, exported, state_arrays, in_tree, out_tree):
        super().__init__()
        self._exported = exported
        self._state_arrays = state_arrays
        self._in_tree = in_tree
        self._out_tree = out_tree

    def forward(self, *args):
        in_arrays = _tree_unwrap(args)
        out = self._exported.call(self._state_arrays, in_arrays)
        return _tree_wrap(out)


def save(layer, path, input_spec=None, **configs):
    """jit.save (reference: jit/api.py). Serializes:
    - ``path + '.pdiparams'``: pickled state dict (numpy)
    - ``path + '.pdmodel'``: StableHLO artifact via jax.export (serving path)
    """
    import pickle

    import numpy as np

    from ..framework import io as fio

    if isinstance(layer, StaticFunction):
        sf = layer
        target = sf._layer
    elif isinstance(layer, Layer):
        target = layer
        sf = layer.forward if isinstance(layer.forward, StaticFunction) else StaticFunction(layer)
    else:
        raise TypeError("jit.save expects a Layer or @to_static function")

    # save EXACTLY the state list the export closes over (_collect_state:
    # params + all buffers, incl. non-persistable ones) — state_dict() skips
    # non-persistable buffers and would desync the Predictor's state/input
    # split when loading the artifact
    if target is not None:
        names, tensors = _collect_state(target)
        state = dict(zip(names, tensors))
    else:
        state = {}
    fio.save(state, path + ".pdiparams")

    if input_spec:
        from jax import export as jexport

        names, tensors = _collect_state(target)
        state_arrays = [t._data for t in tensors]
        args_struct = tuple(
            jax.ShapeDtypeStruct(tuple(s.shape), jnp.dtype(
                s.dtype if isinstance(s.dtype, str) else s.dtype))
            for s in input_spec
        )
        pure = sf._pure({})
        exp = jexport.export(jax.jit(pure))(
            [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in state_arrays],
            args_struct,
        )
        with open(path + ".pdmodel", "wb") as f:
            f.write(exp.serialize())
        # portable StableHLO TEXT module alongside the serialized artifact:
        # the non-Python consumption surface (native/src/stablehlo_runner.cc
        # executes it from C++; any PJRT host language can compile it) —
        # the analogue of the reference's jit::Layer C++ artifact
        # (/root/reference/paddle/fluid/jit/layer.h:1, r/ and goapi clients)
        with open(path + ".mlir", "w") as f:
            f.write(str(exp.mlir_module()))


def load(path, **configs):
    """jit.load — rebuild a TranslatedLayer from saved artifacts."""
    import pickle

    from jax import export as jexport

    from ..framework import io as fio

    state = fio.load(path + ".pdiparams")
    try:
        with open(path + ".pdmodel", "rb") as f:
            exp = jexport.deserialize(f.read())
    except FileNotFoundError:
        raise FileNotFoundError(
            f"{path}.pdmodel not found — jit.save with input_spec produces the serving artifact"
        )
    arrays = [unwrap(v) for v in state.values()]

    class _Loaded(Layer):
        def __init__(self):
            super().__init__()
            self._arrays = arrays

        def forward(self, *args):
            ins = _tree_unwrap(args)
            out = exp.call(self._arrays, ins)
            return _tree_wrap(out)

    return _Loaded()
