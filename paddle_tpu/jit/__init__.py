"""paddle_tpu.jit (reference: python/paddle/jit)."""

from .api import StaticFunction, functional_call, ignore_module, load, not_to_static, save, to_static  # noqa: F401
