"""paddle_tpu.jit (reference: python/paddle/jit)."""

from .api import (  # noqa: F401
    StaticFunction,
    TranslatedLayer,
    functional_call,
    ignore_module,
    load,
    not_to_static,
    save,
    to_static,
)


def set_code_level(level=100, also_to_stdout=False):
    """Dy2static debug logging (reference: jit/set_code_level) — traces are
    jax-level here; retained for API parity."""


def set_verbosity(level=0, also_to_stdout=False):
    pass


def enable_to_static(enable_to_static_bool=True):
    from . import api

    api._to_static_enabled[0] = bool(enable_to_static_bool)
