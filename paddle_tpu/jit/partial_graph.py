"""Partial-graph compilation for ``to_static(full_graph=False)``.

Parity anchor: the reference's SOT resumes COMPILED execution after a graph
break instead of abandoning compilation (jit/sot/translate.py:31 — the
opcode translator splits the bytecode at the break and stitches compiled
subgraphs with an eager bridge; loops resume via FOR_ITER handling,
jit/sot/opcode_translator/executor/opcode_executor.py:1694).

TPU-native redesign: instead of bytecode surgery, the function's AST is
split at the breaking statement:

``if`` break::

    prefix  = statements before the if           -> one jitted graph
    bridge  = the if CONDITION, evaluated eagerly on the prefix's concrete
              outputs (the data-dependent bool the trace could not take)
    suffix  = branch body + remaining statements -> one jitted graph per
              taken branch (compiled lazily, only for branches that run)

``while`` break (tensor condition, or a deeper break inside the body)::

    prefix -> whole-loop ``lax.while_loop`` lowering when the body traces
    with a stable carry (ONE compiled graph for the entire loop); otherwise
    an eager bridge drives the loop — condition evaluated eagerly per
    iteration, body a compiled subgraph reused across iterations -> suffix.

``for`` break (break inside the body)::

    prefix -> iterable evaluated eagerly -> compiled body subgraph per
    iteration (loop-carried vars threaded as a live tuple) -> suffix.

Each synthesized piece is itself a ``full_graph=False`` StaticFunction, so a
second break inside it splits again (elif chains, an ``if`` inside a loop
body, and nested loops all recurse naturally). Layer methods are supported:
``self`` is bound into the synthesized functions' namespace and parameters
are functionalized through the sub-StaticFunctions (grads flow like any
to_static Layer call). Keyword calls and defaults are normalized to
positional by the caller (jit/api.py) before entering the plan.

Bounds (documented, not silent):
  - the function signature may not use *args/**kwargs/keyword-only args;
  - the breaking statement must sit at the TOP LEVEL of the function body
    (a break buried in a nested statement splits at the enclosing top-level
    statement when that is an if/for/while, else falls back);
  - loop bodies containing ``break``/``continue``/``return`` (or loop
    ``else:`` clauses) fall back to whole-function eager;
  - loop-carried variables must be defined before the loop (Python allows a
    body-defined name to escape; the synthesized prefix raises NameError and
    api.py falls back to eager permanently);
  - when the function has closure nonlocals (or is a Layer method, whose
    ``self`` is injected), the synthesized functions see a SNAPSHOT of those
    bindings taken at split time; plain module-global functions read their
    module globals LIVE (rebinding a global after the split is visible).
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from typing import Optional

__all__ = ["try_split", "SplitPlan", "break_lineno_of"]


def break_lineno_of(exc, fn) -> Optional[int]:
    """Line (in fn's file) where tracing broke, from the exception traceback."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return None
    tb = exc.__traceback__
    lineno = None
    while tb is not None:
        if tb.tb_frame.f_code is code:
            lineno = tb.tb_lineno
        tb = tb.tb_next
    return lineno


class _Names(ast.NodeVisitor):
    def __init__(self):
        self.loads = set()
        self.stores = set()

    def visit_Name(self, node):
        (self.loads if isinstance(node.ctx, ast.Load)
         else self.stores).add(node.id)

    def visit_AugAssign(self, node):
        # `s += x` both reads and writes s (ast marks the target Store only)
        if isinstance(node.target, ast.Name):
            self.loads.add(node.target.id)
        self.generic_visit(node)


def _names(nodes):
    v = _Names()
    for n in nodes:
        v.visit(n)
    return v


def _has_flow_escape(stmts):
    """break/continue/return anywhere inside (incl. nested) — the loop
    splitters can't express these; fall back."""
    for stmt in stmts:
        for n in ast.walk(stmt):
            if isinstance(n, (ast.Break, ast.Continue, ast.Return)):
                return True
    return False


_SYNTH_COUNT = [0]


def _make_fn(name, arg_names, body_stmts, globs):
    """exec a synthesized def and return the function object. Its source is
    registered in linecache so a SECOND graph break inside it can be split
    again (try_split needs inspect.getsource)."""
    import linecache

    fdef = ast.FunctionDef(
        name=name,
        args=ast.arguments(posonlyargs=[], args=[ast.arg(a) for a in arg_names],
                           kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=body_stmts or [ast.Pass()],
        decorator_list=[], returns=None, type_params=[])
    mod = ast.Module(body=[fdef], type_ignores=[])
    ast.fix_missing_locations(mod)
    src = ast.unparse(mod)
    _SYNTH_COUNT[0] += 1
    fname = f"<partial_graph:{name}:{_SYNTH_COUNT[0]}>"
    linecache.cache[fname] = (len(src), None, src.splitlines(True), fname)
    ns = {}
    exec(compile(src, fname, "exec"), globs, ns)  # noqa: S102
    return ns[name]


class SplitPlan:
    """Callable stitching compiled subgraphs with eager bridges.

    stages: a list of callables run in sequence over the live tuple —
      - ``("jit", sf)``: live = sf(*live) (sf returns the next live tuple)
      - ``("if", cond_fn, true_sf, false_sf)``: eager bool bridge, then the
        taken branch CONSUMES the rest of the function (it is the final
        stage; its return value is the function's return value)
      - ``("while", cond_sf, body_sf, whole_sf)``: loop bridge (see
        _WhileStage)
      - ``("for", iter_fn, body_sf, n_target)``: eager iteration bridge
    The final stage returns the function's result; non-final stages return
    live tuples."""

    def __init__(self, prefix_sf, stage, live):
        self._prefix = prefix_sf
        self._stage = stage
        self._live = live

    def _live_tuple(self, vals):
        return vals if isinstance(vals, tuple) else (vals,)

    def __call__(self, *args):
        live = self._live_tuple(self._prefix(*args))
        return self._stage(live)


class _IfStage:
    def __init__(self, cond_fn, true_sf, false_sf):
        self._cond = cond_fn
        self._true = true_sf
        self._false = false_sf

    def __call__(self, live):
        cond = bool(self._cond(*live))
        return (self._true if cond else self._false)(*live)


class _WhileStage:
    """Tensor-condition (or breaking-body) while: try ONE fully-compiled
    ``lax.while_loop`` over the carry first; if that traces, the whole loop
    is a single graph. Otherwise drive eagerly: condition bridge per
    iteration, compiled body subgraph (reused executable) per iteration."""

    def __init__(self, cond_sf, body_sf, suffix_sf):
        self._cond = cond_sf
        self._body = body_sf
        self._suffix = suffix_sf
        self._lax_ok: Optional[bool] = None
        self._lax_fn = None
        self._probe_out = None  # first lax run's result (don't run twice)
        # carry signatures the whole-loop lowering FAILED for: `_lax_fn` is
        # cached from the first (grad-free) call, but a later call with new
        # carry shapes retraces it — and a body that was carry-stable at the
        # probe's shapes may not be at these (ADVICE medium). Such calls fall
        # back to the eager cond/body bridge, memoized per signature so the
        # failed retrace isn't re-attempted every call.
        self._lax_bad = set()

    def _try_lax(self, live):
        import jax

        from ..core.tensor import Tensor

        cond_fn, body_fn = self._cond._orig_fn, self._body._orig_fn

        def wrap(c):
            return tuple(Tensor(x) if not isinstance(x, Tensor) else x
                         for x in c)

        def whole(*carry):
            def c(state):
                out = cond_fn(*wrap(state))
                return out._data if isinstance(out, Tensor) else out

            def b(state):
                out = body_fn(*wrap(state))
                out = out if isinstance(out, tuple) else (out,)
                return tuple(o._data if isinstance(o, Tensor) else o
                             for o in out)

            init = tuple(o._data if isinstance(o, Tensor) else o
                         for o in carry)
            return jax.lax.while_loop(c, b, init)

        from .api import StaticFunction

        fn = StaticFunction(whole, full_graph=True)
        # probe: trace errors (unstable carry etc.) raise here; the result is
        # kept so the first successful call doesn't execute the loop twice
        self._probe_out = fn(*live)
        return fn

    def __call__(self, live):
        # Grad-requiring inputs must take the eager bridge EVERY call —
        # lax.while_loop has no reverse-mode derivative (the bridge's
        # compiled body subgraphs record the tape normally). Decided per
        # call, not cached: a warmup pass without grads must not pin a
        # training pass onto the lax path. Layer methods always bridge (the
        # raw cond/body close over `self`, so the whole-loop jit would bake
        # parameters in as trace-time CONSTANTS).
        from ..core.tensor import Tensor

        needs_grad = any(isinstance(v, Tensor) and not v.stop_gradient
                         for v in live)
        use_lax = False
        sig = None
        if not needs_grad and self._cond._layer is None:
            sig = self._carry_sig(live)
            if sig not in self._lax_bad:
                if self._lax_ok is None:
                    try:
                        self._lax_fn = self._try_lax(live)
                        self._lax_ok = True
                    except Exception:
                        self._lax_ok = False
                        self._lax_bad.add(sig)
                use_lax = bool(self._lax_ok)
        if use_lax:
            if self._probe_out is not None:
                out, self._probe_out = self._probe_out, None
                live = out if isinstance(out, tuple) else (out,)
            else:
                try:
                    out = self._lax_fn(*live)
                except Exception:
                    # new carry signature broke the whole-loop retrace (the
                    # body is not shape-stable at THESE shapes): eager
                    # bridge for this signature, lax stays live for the ones
                    # that already lowered
                    self._lax_bad.add(sig)
                    use_lax = False
                else:
                    live = out if isinstance(out, tuple) else (out,)
        if not use_lax:
            while bool(self._cond(*live)):
                out = self._body(*live)
                live = out if isinstance(out, tuple) else (out,)
        return self._suffix(*live)

    @staticmethod
    def _carry_sig(live):
        """Abstract signature of a carry tuple: per-element (type, shape,
        dtype). Python scalars key by type alone — their values don't change
        what traces."""
        sig = []
        for v in live:
            d = getattr(v, "_data", v)
            shape = getattr(d, "shape", None)
            sig.append((type(v).__name__,
                        None if shape is None else tuple(shape),
                        str(getattr(d, "dtype", ""))))
        return tuple(sig)


class _ForStage:
    def __init__(self, iter_fn, body_sf, suffix_sf):
        self._iter = iter_fn
        self._body = body_sf
        self._suffix = suffix_sf

    def __call__(self, live):
        for item in self._iter(*live):
            out = self._body(*live, *(item if self._body._pg_targets > 1
                                      else (item,)))
            live = out if isinstance(out, tuple) else (out,)
        return self._suffix(*live)


def _sub_static(fn, layer):
    from .api import StaticFunction

    sf = StaticFunction(fn, full_graph=False)
    if layer is not None:
        sf._layer = layer  # functionalize params/buffers + grad recording
    return sf


def try_split(fn, lineno: Optional[int], layer=None) -> Optional[SplitPlan]:
    """Build a SplitPlan for a break at ``lineno`` (file line), or None.

    ``layer``: when ``fn`` is a Layer method, the owning Layer — ``self`` is
    bound into the synthesized namespace and every compiled piece
    functionalizes the layer's state (grads flow exactly like the unsplit
    to_static call)."""
    if lineno is None:
        return None
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return None
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, ast.FunctionDef):
        return None
    a = fdef.args
    if a.vararg or a.kwarg or a.kwonlyargs or a.posonlyargs:
        return None
    arg_names = [x.arg for x in a.args]
    self_name = None
    if layer is not None:
        if not arg_names:
            return None
        self_name = arg_names[0]  # bound through globals, not as an arg
        arg_names = arg_names[1:]
    # map the file lineno onto the dedented source's linenos: getsource
    # starts at co_firstlineno (the first decorator when decorated), which
    # is line 1 of the parsed source
    code = fn.__code__ if not inspect.ismethod(fn) else fn.__func__.__code__
    rel = lineno - code.co_firstlineno + 1
    idx = None
    for i, stmt in enumerate(fdef.body):
        if stmt.lineno <= rel <= (stmt.end_lineno or stmt.lineno):
            idx = i
            break
    if idx is None:
        return None
    brk = fdef.body[idx]
    if not isinstance(brk, (ast.If, ast.While, ast.For)):
        return None
    prefix_stmts = fdef.body[:idx]
    rest = fdef.body[idx + 1:]
    # an early `return` anywhere in the prefix (e.g. a static guard) would
    # be swallowed by the synthesized live-tuple return — don't split
    if any(isinstance(n, ast.Return)
           for stmt in prefix_stmts for n in ast.walk(stmt)):
        return None

    # ADVICE r4: plain module-level functions exec against fn.__globals__
    # ITSELF so later global rebinds stay visible; closures and Layer methods
    # need an overlay namespace -> documented snapshot (module Bounds)
    nonlocals = inspect.getclosurevars(fn).nonlocals
    if not nonlocals and layer is None:
        globs = fn.__globals__
    else:
        globs = dict(fn.__globals__)
        globs.update(nonlocals)
        if layer is not None:
            globs[self_name] = layer

    avail = _names(prefix_stmts).stores | set(arg_names)

    def ret_tuple(names):
        return ast.Return(ast.Tuple(
            [ast.Name(n, ast.Load()) for n in names], ast.Load()))

    if isinstance(brk, ast.If):
        needed = _names([brk] + rest).loads
        live = sorted(avail & needed)
        prefix_fn = _make_fn("__pg_prefix", arg_names,
                             prefix_stmts + [ret_tuple(live)], globs)
        cond_fn = _make_fn("__pg_cond", live,
                           [ast.Return(brk.test)], globs)
        true_fn = _make_fn("__pg_true", live, brk.body + rest, globs)
        false_fn = _make_fn("__pg_false", live, (brk.orelse or []) + rest,
                            globs)
        stage = _IfStage(cond_fn,
                         _sub_static(true_fn, layer),
                         _sub_static(false_fn, layer))
        return SplitPlan(_sub_static(prefix_fn, layer), stage, live)

    if isinstance(brk, ast.While):
        if brk.orelse or _has_flow_escape(brk.body):
            return None
        body_n = _names(brk.body)
        cond_loads = _names([ast.Expr(brk.test)]).loads
        rest_loads = _names(rest).loads
        # loop-carried live set: read by the condition/body/rest AND defined
        # before the loop (body-only names are per-iteration temps; a
        # body-defined name escaping into rest -> prefix NameError -> eager)
        live = sorted(avail & (cond_loads | body_n.loads | rest_loads))
        prefix_fn = _make_fn("__pg_prefix", arg_names,
                             prefix_stmts + [ret_tuple(live)], globs)
        cond_fn = _make_fn("__pg_wcond", live,
                           [ast.Return(brk.test)], globs)
        body_fn = _make_fn("__pg_wbody", live,
                           list(brk.body) + [ret_tuple(live)], globs)
        suffix_fn = _make_fn("__pg_suffix", live, rest or [ast.Pass()],
                             globs)
        stage = _WhileStage(_sub_static(cond_fn, layer),
                            _sub_static(body_fn, layer),
                            _sub_static(suffix_fn, layer))
        return SplitPlan(_sub_static(prefix_fn, layer), stage, live)

    # ast.For
    if brk.orelse or _has_flow_escape(brk.body):
        return None
    tgt = brk.target
    if isinstance(tgt, ast.Name):
        targets = [tgt.id]
    elif isinstance(tgt, ast.Tuple) and all(
            isinstance(e, ast.Name) for e in tgt.elts):
        targets = [e.id for e in tgt.elts]
    else:
        return None
    body_n = _names(brk.body)
    rest_loads = _names(rest).loads
    if set(targets) & rest_loads:
        # Python leaks the loop variable; the splitter doesn't — fall back
        return None
    iter_loads = _names([ast.Expr(brk.iter)]).loads
    live = sorted((avail - set(targets))
                  & (iter_loads | body_n.loads | rest_loads))
    prefix_fn = _make_fn("__pg_prefix", arg_names,
                         prefix_stmts + [ret_tuple(live)], globs)
    iter_fn = _make_fn("__pg_iter", live, [ast.Return(brk.iter)], globs)
    body_fn = _make_fn("__pg_fbody", live + targets,
                       list(brk.body) + [ret_tuple(live)], globs)
    suffix_fn = _make_fn("__pg_suffix", live, rest or [ast.Pass()], globs)
    body_sf = _sub_static(body_fn, layer)
    body_sf._pg_targets = len(targets)
    stage = _ForStage(iter_fn, body_sf, _sub_static(suffix_fn, layer))
    return SplitPlan(_sub_static(prefix_fn, layer), stage, live)
