"""Partial-graph compilation for ``to_static(full_graph=False)``.

Parity anchor: the reference's SOT resumes COMPILED execution after a graph
break instead of abandoning compilation (jit/sot/translate.py:31 — the
opcode translator splits the bytecode at the break and stitches compiled
subgraphs with an eager bridge).

TPU-native redesign: instead of bytecode surgery, the function's AST is
split at the breaking ``if`` statement:

    prefix  = statements before the if           -> one jitted graph
    bridge  = the if CONDITION, evaluated eagerly on the prefix's concrete
              outputs (the data-dependent bool the trace could not take)
    suffix  = branch body + remaining statements -> one jitted graph per
              taken branch (compiled lazily, only for branches that run)

Each suffix is itself a ``full_graph=False`` StaticFunction, so a second
break inside it splits again (elif chains are nested ifs and recurse
naturally). When the break is not an ``if`` at the top level of the function
body — while-on-tensor, tensor-int conversion in indexing, breaks inside
loops — :func:`try_split` returns None and the caller keeps the
whole-function eager fallback.

Bounds (documented, not silent): plain functions only (no *args/**kwargs,
no Layer state), source must be available, and the breaking statement must
be a top-level ``if``.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from typing import Optional

__all__ = ["try_split", "SplitPlan", "break_lineno_of"]


def break_lineno_of(exc, fn) -> Optional[int]:
    """Line (in fn's file) where tracing broke, from the exception traceback."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return None
    tb = exc.__traceback__
    lineno = None
    while tb is not None:
        if tb.tb_frame.f_code is code:
            lineno = tb.tb_lineno
        tb = tb.tb_next
    return lineno


class _Names(ast.NodeVisitor):
    def __init__(self):
        self.loads = set()
        self.stores = set()

    def visit_Name(self, node):
        (self.loads if isinstance(node.ctx, ast.Load)
         else self.stores).add(node.id)

    def visit_AugAssign(self, node):
        # `s += x` both reads and writes s (ast marks the target Store only)
        if isinstance(node.target, ast.Name):
            self.loads.add(node.target.id)
        self.generic_visit(node)


def _names(nodes):
    v = _Names()
    for n in nodes:
        v.visit(n)
    return v


_SYNTH_COUNT = [0]


def _make_fn(name, arg_names, body_stmts, globs):
    """exec a synthesized def and return the function object. Its source is
    registered in linecache so a SECOND graph break inside it can be split
    again (try_split needs inspect.getsource)."""
    import linecache

    fdef = ast.FunctionDef(
        name=name,
        args=ast.arguments(posonlyargs=[], args=[ast.arg(a) for a in arg_names],
                           kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=body_stmts or [ast.Pass()],
        decorator_list=[], returns=None, type_params=[])
    mod = ast.Module(body=[fdef], type_ignores=[])
    ast.fix_missing_locations(mod)
    src = ast.unparse(mod)
    _SYNTH_COUNT[0] += 1
    fname = f"<partial_graph:{name}:{_SYNTH_COUNT[0]}>"
    linecache.cache[fname] = (len(src), None, src.splitlines(True), fname)
    ns = {}
    exec(compile(src, fname, "exec"), globs, ns)  # noqa: S102
    return ns[name]


class SplitPlan:
    """Callable implementing prefix-jit -> eager condition -> suffix-jit.

    The prefix returns EVERY value the suffix reads (including reassigned
    parameters — `x = x * 2` before the break must reach the suffix as the
    doubled value, not the caller's argument), so the condition and branches
    take only the live tuple."""

    def __init__(self, prefix_sf, cond_fn, true_sf, false_sf, live):
        self._prefix = prefix_sf
        self._cond = cond_fn
        self._true = true_sf
        self._false = false_sf
        self._live = live

    def __call__(self, *args):
        live_vals = self._prefix(*args)
        if not isinstance(live_vals, tuple):
            live_vals = (live_vals,)
        cond = bool(self._cond(*live_vals))
        branch = self._true if cond else self._false
        return branch(*live_vals)


def try_split(fn, lineno: Optional[int]) -> Optional[SplitPlan]:
    """Build a SplitPlan for a break at ``lineno`` (file line), or None."""
    from .api import StaticFunction

    if lineno is None:
        return None
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return None
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, ast.FunctionDef):
        return None
    a = fdef.args
    if (a.vararg or a.kwarg or a.kwonlyargs or a.posonlyargs or a.defaults):
        return None
    arg_names = [x.arg for x in a.args]
    # map the file lineno onto the dedented source's linenos: getsource
    # starts at co_firstlineno (the first decorator when decorated), which
    # is line 1 of the parsed source
    rel = lineno - fn.__code__.co_firstlineno + 1
    idx = None
    for i, stmt in enumerate(fdef.body):
        if stmt.lineno <= rel <= (stmt.end_lineno or stmt.lineno):
            idx = i
            break
    if idx is None or not isinstance(fdef.body[idx], ast.If):
        return None
    prefix_stmts = fdef.body[:idx]
    if_stmt = fdef.body[idx]
    rest = fdef.body[idx + 1:]
    # an early `return` anywhere in the prefix (e.g. a static guard) would
    # be swallowed by the synthesized live-tuple return — don't split
    if any(isinstance(n, ast.Return)
           for stmt in prefix_stmts for n in ast.walk(stmt)):
        return None

    # live set: everything the suffix reads that exists at the break —
    # arguments INCLUDED (a reassigned parameter must flow through the
    # prefix's return, not the caller's original value)
    produced = _names(prefix_stmts).stores | set(arg_names)
    needed = _names([if_stmt] + rest).loads
    live = sorted(produced & needed)

    globs = dict(fn.__globals__)
    globs.update(inspect.getclosurevars(fn).nonlocals)

    ret_live = ast.Return(ast.Tuple(
        [ast.Name(n, ast.Load()) for n in live], ast.Load()))
    prefix_fn = _make_fn("__pg_prefix", arg_names,
                         prefix_stmts + [ret_live], globs)
    cond_fn = _make_fn("__pg_cond", live,
                       [ast.Return(if_stmt.test)], globs)
    true_fn = _make_fn("__pg_true", live,
                       if_stmt.body + rest, globs)
    false_fn = _make_fn("__pg_false", live,
                        (if_stmt.orelse or []) + rest, globs)

    # prefix: one jitted graph (a break before the if would have surfaced
    # earlier, but keep the eager safety net); suffixes: full_graph=False so
    # a second break splits again
    return SplitPlan(
        StaticFunction(prefix_fn, full_graph=False),
        cond_fn,
        StaticFunction(true_fn, full_graph=False),
        StaticFunction(false_fn, full_graph=False),
        live)
