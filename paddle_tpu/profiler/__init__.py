"""paddle_tpu.profiler (reference: python/paddle/profiler + fluid/platform/profiler).

TPU-native: the device-side tracer is XLA/XPlane via ``jax.profiler`` (TensorBoard-
compatible, replaces the reference's CUPTI CudaTracer); host-side op scopes use
``jax.profiler.TraceAnnotation`` (the RecordEvent analogue — reference
profiler/utils.py:47) plus a lightweight wall-clock event tree for the summary table.
The host tracer itself is native: a C++ per-thread event collector with
chrome://tracing export (paddle_tpu/native/src/trace.cc — the HostTracer +
ChromeTracingLogger equivalent, reference chrometracing_logger.cc), used
whenever the native library is available.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from enum import Enum
from typing import Optional

import jax

from .. import native as _native


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


def make_scheduler(*, closed=0, ready=0, record=1, repeat=0, skip_first=0):
    total = closed + ready + record

    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        if repeat and s >= repeat * total:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    def handler(prof):
        prof.export(dir_name)

    return handler


def export_host_chrome_trace(path: str, process_name: str = "paddle_tpu") -> bool:
    """Dump the native host-tracer events as a chrome://tracing JSON file."""
    lib = _native.load()
    if lib is None:
        return False
    return lib.pt_trace_dump(path.encode(), process_name.encode()) == 0


export_protobuf = export_chrome_tracing


class RecordEvent:
    """Named host scope (reference: profiler/utils.py:47). Shows up in XPlane traces
    and in the host-side statistics table."""

    _active_stack = []

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self.begin_ts = None

    def begin(self):
        self.begin_ts = time.perf_counter()
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        _host_events.start(self.name, self.begin_ts)
        lib = _native.peek()  # never builds; Profiler.start() does the load
        if lib is not None and lib.pt_trace_enabled():
            lib.pt_trace_begin(self.name.encode())
            self._native_gen = lib.pt_trace_generation()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            _host_events.stop(self.name, time.perf_counter())
            self._ann = None
            gen = getattr(self, "_native_gen", None)
            if gen is not None:
                lib = _native.peek()
                # Skip the pop if tracing restarted mid-scope — the begin-stack
                # was cleared and popping would close someone else's scope.
                if lib is not None and lib.pt_trace_enabled() and \
                        lib.pt_trace_generation() == gen:
                    lib.pt_trace_end()
                self._native_gen = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def _device_mem_stats():
    """bytes_in_use / peak_bytes_in_use of device 0, or None when the
    backend exposes no allocator stats (virtual CPU devices)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return (int(stats.get("bytes_in_use", 0)),
            int(stats.get("peak_bytes_in_use", 0)))


class _HostEvents:
    """Per-name host wall-clock stats + optional per-region device-memory
    brackets (reference: profiler_statistic.py:856 StatisticData — the
    EventSummary's per-op items track calls/total/avg/max/min; :630 memory
    items track allocation peaks per scope)."""

    def __init__(self):
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)
        self.maxs = defaultdict(float)
        self.mins = defaultdict(lambda: float("inf"))
        self._open = {}
        # memory brackets: name -> [increase_bytes_total, peak_bytes max];
        # enabled while any started Profiler(profile_memory=True) is live
        self.mem_enabled = False
        self.mem_refs = 0
        self.mem_delta = defaultdict(int)
        self.mem_peak = defaultdict(int)
        self._mem_open = {}

    def start(self, name, ts):
        self._open.setdefault(name, []).append(ts)
        # push UNCONDITIONALLY (None when memory brackets are off): a
        # profile_memory Profiler starting or stopping while RecordEvent
        # scopes are open must not desync the bracket stack — a scope that
        # began without a snapshot pops its own None, never a snapshot
        # pushed by a different (post-toggle) invocation
        self._mem_open.setdefault(name, []).append(
            _device_mem_stats() if self.mem_enabled else None)

    def stop(self, name, ts):
        if self._open.get(name):
            t0 = self._open[name].pop()
            dt = ts - t0
            self.totals[name] += dt
            self.counts[name] += 1
            self.maxs[name] = max(self.maxs[name], dt)
            self.mins[name] = min(self.mins[name], dt)
        if self._mem_open.get(name):
            before = self._mem_open[name].pop()
            # account only brackets whose scope RAN fully under memory
            # profiling: a None push (disabled at begin) contributes
            # nothing even if profiling turned on mid-scope
            if self.mem_enabled and before is not None:
                after = _device_mem_stats()
                if after is not None:
                    self.mem_delta[name] += after[0] - before[0]
                    self.mem_peak[name] = max(self.mem_peak[name], after[1])

    def reset(self):
        self.totals.clear()
        self.counts.clear()
        self.maxs.clear()
        self.mins.clear()
        self._open.clear()
        self.mem_delta.clear()
        self.mem_peak.clear()
        self._mem_open.clear()


_host_events = _HostEvents()


def _format_table(title, headers, rows):
    """Aligned ASCII table in the reference's _build_table style
    (profiler_statistic.py:874)."""
    widths = [max(len(str(h)), *(len(str(r[i])) for r in rows))
              if rows else len(str(h)) for i, h in enumerate(headers)]
    sep = "-" * (sum(widths) + 2 * len(widths))
    out = [sep, title, sep,
           "  ".join(str(h).ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    out.append(sep)
    return "\n".join(out)


class Profiler:
    """Reference: python/paddle/profiler/profiler.py:358."""

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False):
        # memory brackets are refcounted on start()/stop(): overlapping
        # profilers don't disable each other, and a constructed-but-never-
        # started profiler doesn't turn on device memory_stats() process-wide
        self._mem_owner = bool(profile_memory)
        self._mem_active = False
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(record=scheduler[1] - scheduler[0], closed=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else (lambda step: ProfilerState.RECORD)
        )
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self.step_num = 0
        self._state = ProfilerState.CLOSED
        self._trace_dir = None
        self._tracing = False
        self._step_times = []
        self._last_step_ts = None

    def start(self):
        if self._mem_owner and not self._mem_active:
            self._mem_active = True
            _host_events.mem_refs += 1
            _host_events.mem_enabled = True
        self._state = self._scheduler(self.step_num)
        self._maybe_toggle()
        self._last_step_ts = time.perf_counter()

    def stop(self):
        self._state = ProfilerState.CLOSED
        self._maybe_toggle()
        if self._mem_active:
            self._mem_active = False
            _host_events.mem_refs = max(0, _host_events.mem_refs - 1)
            _host_events.mem_enabled = _host_events.mem_refs > 0
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step_ts is not None:
            self._step_times.append((now - self._last_step_ts, num_samples))
        self._last_step_ts = now
        self.step_num += 1
        new_state = self._scheduler(self.step_num)
        if new_state != self._state:
            self._state = new_state
            self._maybe_toggle()

    def _maybe_toggle(self):
        should_trace = self._state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) and not self._timer_only
        if should_trace and not self._tracing:
            import tempfile

            self._trace_dir = self._trace_dir or tempfile.mkdtemp(prefix="paddle_tpu_prof_")
            jax.profiler.start_trace(self._trace_dir)
            lib = _native.load()
            if lib is not None:
                lib.pt_trace_start()
            self._tracing = True
        elif not should_trace and self._tracing:
            jax.profiler.stop_trace()
            lib = _native.load()
            if lib is not None:
                lib.pt_trace_stop()
            self._tracing = False

    def export(self, path=None, format="json"):
        if path and format == "json":
            import os

            os.makedirs(path, exist_ok=True)
            export_host_chrome_trace(os.path.join(path, "host_trace.json"))
        return self._trace_dir

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Sorted per-op statistic tables + memory summary (reference:
        profiler_statistic.py:856 StatisticData / :874 _build_table).

        Views emitted: OverView (step timing), OperatorView (host RecordEvent
        scopes: Calls/Total/Avg/Max/Min/Ratio, sorted by ``sorted_by`` —
        SortedKeys.CPUTotal/CPUAvg/CPUMax/CPUMin), and with
        ``profile_memory=True`` a MemoryView (per-scope device-HBM increase +
        peak bytes-in-use, from device memory_stats brackets)."""
        scale = {"ms": 1e3, "s": 1.0, "us": 1e6}[time_unit]
        he = _host_events
        key = {
            None: lambda n: -he.totals[n],
            SortedKeys.CPUTotal: lambda n: -he.totals[n],
            SortedKeys.CPUAvg: lambda n: -he.totals[n] / max(he.counts[n], 1),
            SortedKeys.CPUMax: lambda n: -he.maxs[n],
            SortedKeys.CPUMin: lambda n: he.mins[n],
        }.get(sorted_by, lambda n: -he.totals[n])
        grand = sum(he.totals.values()) or 1.0
        rows = []
        for name in sorted(he.totals, key=key):
            n = he.counts[name]
            tot = he.totals[name]
            rows.append((
                name, n,
                f"{tot * scale:.3f}",
                f"{tot / max(n, 1) * scale:.3f}",
                f"{he.maxs[name] * scale:.3f}",
                f"{(0.0 if he.mins[name] == float('inf') else he.mins[name]) * scale:.3f}",
                f"{tot / grand * 100:.1f}%",
            ))
        parts = []
        if self._step_times:
            ts = [t for t, _ in self._step_times]
            parts.append(_format_table(
                "OverView", ("Metric", "Value"),
                [("steps", len(ts)),
                 (f"avg_step ({time_unit})",
                  f"{sum(ts) / len(ts) * scale:.3f}"),
                 (f"max_step ({time_unit})", f"{max(ts) * scale:.3f}"),
                 (f"min_step ({time_unit})", f"{min(ts) * scale:.3f}")]))
        parts.append(_format_table(
            f"OperatorView (host, unit: {time_unit})",
            ("Name", "Calls", "Total", "Avg", "Max", "Min", "Ratio"),
            rows))
        if self._mem_owner or he.mem_enabled or he.mem_peak:
            mem_rows = [(name,
                         f"{he.mem_delta[name] / 2**20:.2f}",
                         f"{he.mem_peak[name] / 2**20:.2f}")
                        for name in sorted(set(he.mem_delta)
                                           | set(he.mem_peak),
                                           key=lambda n: -he.mem_peak[n])]
            cur = _device_mem_stats()
            if cur is not None:
                mem_rows.append(("[device now]", f"{cur[0] / 2**20:.2f}",
                                 f"{cur[1] / 2**20:.2f}"))
            parts.append(_format_table(
                "MemoryView (device HBM, MB)",
                ("Name", "Increase", "PeakInUse"),
                mem_rows))
        table = "\n".join(parts)
        print(table)
        return table

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        t, n = self._step_times[-1]
        ips = (n / t) if (n and t > 0) else (1.0 / t if t > 0 else 0.0)
        return f"step_time: {t * 1e3:.2f} ms, ips: {ips:.2f}"

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


@contextlib.contextmanager
def profile(log_dir="./profiler_log"):
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class ProfilerResult:
    """Aggregated view of an exported chrome trace (reference:
    profiler_statistic.py statistics over the event tree)."""

    def __init__(self, events):
        self.events = events
        agg = {}
        for e in events:
            if e.get("ph") != "X":
                continue
            name = e.get("name", "?")
            d = agg.setdefault(name, {"calls": 0, "total_us": 0.0})
            d["calls"] += 1
            d["total_us"] += float(e.get("dur", 0.0))
        self.summary = {
            n: {**d, "avg_us": d["total_us"] / max(d["calls"], 1)}
            for n, d in agg.items()}

    def sorted_by_total(self):
        return sorted(self.summary.items(), key=lambda kv: -kv[1]["total_us"])


def load_profiler_result(path):
    """Load an exported chrome-trace JSON (export_chrome_tracing /
    export_host_chrome_trace output, or a jax.profiler trace dir) into a
    ProfilerResult with per-name call counts and durations. Raw XPlane
    protobuf dumps remain TensorBoard-profile territory."""
    import gzip
    import json
    import os

    if os.path.isdir(path):
        cands = [os.path.join(r, f) for r, _, fs in os.walk(path)
                 for f in fs if f.endswith((".json", ".json.gz",
                                            ".trace.json.gz"))]
        if not cands:
            raise FileNotFoundError(
                f"no chrome-trace .json under {path} (XPlane-only dump? "
                "open it with TensorBoard's profile plugin)")
        path = max(cands, key=os.path.getmtime)  # newest capture
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", data if isinstance(data, list) else [])
    return ProfilerResult(events)


import enum as _enum


class SortedKeys(_enum.IntEnum):
    """Summary sort keys (reference: profiler/profiler_statistic.py SortedKeys)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(_enum.IntEnum):
    """Summary view selector (reference: profiler/profiler.py SummaryView)."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8
