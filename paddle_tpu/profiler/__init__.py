"""paddle_tpu.profiler (reference: python/paddle/profiler + fluid/platform/profiler).

TPU-native: the device-side tracer is XLA/XPlane via ``jax.profiler`` (TensorBoard-
compatible, replaces the reference's CUPTI CudaTracer); host-side op scopes use
``jax.profiler.TraceAnnotation`` (the RecordEvent analogue — reference
profiler/utils.py:47) plus a lightweight wall-clock event tree for the summary table.
The host tracer itself is native: a C++ per-thread event collector with
chrome://tracing export (paddle_tpu/native/src/trace.cc — the HostTracer +
ChromeTracingLogger equivalent, reference chrometracing_logger.cc), used
whenever the native library is available.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from enum import Enum
from typing import Optional

import jax

from .. import native as _native


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


def make_scheduler(*, closed=0, ready=0, record=1, repeat=0, skip_first=0):
    total = closed + ready + record

    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        if repeat and s >= repeat * total:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    def handler(prof):
        prof.export(dir_name)

    return handler


def export_host_chrome_trace(path: str, process_name: str = "paddle_tpu") -> bool:
    """Dump the native host-tracer events as a chrome://tracing JSON file."""
    lib = _native.load()
    if lib is None:
        return False
    return lib.pt_trace_dump(path.encode(), process_name.encode()) == 0


export_protobuf = export_chrome_tracing


class RecordEvent:
    """Named host scope (reference: profiler/utils.py:47). Shows up in XPlane traces
    and in the host-side statistics table."""

    _active_stack = []

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self.begin_ts = None

    def begin(self):
        self.begin_ts = time.perf_counter()
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        _host_events.start(self.name, self.begin_ts)
        lib = _native.peek()  # never builds; Profiler.start() does the load
        if lib is not None and lib.pt_trace_enabled():
            lib.pt_trace_begin(self.name.encode())
            self._native_gen = lib.pt_trace_generation()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            _host_events.stop(self.name, time.perf_counter())
            self._ann = None
            gen = getattr(self, "_native_gen", None)
            if gen is not None:
                lib = _native.peek()
                # Skip the pop if tracing restarted mid-scope — the begin-stack
                # was cleared and popping would close someone else's scope.
                if lib is not None and lib.pt_trace_enabled() and \
                        lib.pt_trace_generation() == gen:
                    lib.pt_trace_end()
                self._native_gen = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class _HostEvents:
    def __init__(self):
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)
        self._open = {}

    def start(self, name, ts):
        self._open.setdefault(name, []).append(ts)

    def stop(self, name, ts):
        if self._open.get(name):
            t0 = self._open[name].pop()
            self.totals[name] += ts - t0
            self.counts[name] += 1

    def reset(self):
        self.totals.clear()
        self.counts.clear()
        self._open.clear()


_host_events = _HostEvents()


class Profiler:
    """Reference: python/paddle/profiler/profiler.py:358."""

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(record=scheduler[1] - scheduler[0], closed=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else (lambda step: ProfilerState.RECORD)
        )
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self.step_num = 0
        self._state = ProfilerState.CLOSED
        self._trace_dir = None
        self._tracing = False
        self._step_times = []
        self._last_step_ts = None

    def start(self):
        self._state = self._scheduler(self.step_num)
        self._maybe_toggle()
        self._last_step_ts = time.perf_counter()

    def stop(self):
        self._state = ProfilerState.CLOSED
        self._maybe_toggle()
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step_ts is not None:
            self._step_times.append((now - self._last_step_ts, num_samples))
        self._last_step_ts = now
        self.step_num += 1
        new_state = self._scheduler(self.step_num)
        if new_state != self._state:
            self._state = new_state
            self._maybe_toggle()

    def _maybe_toggle(self):
        should_trace = self._state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) and not self._timer_only
        if should_trace and not self._tracing:
            import tempfile

            self._trace_dir = self._trace_dir or tempfile.mkdtemp(prefix="paddle_tpu_prof_")
            jax.profiler.start_trace(self._trace_dir)
            lib = _native.load()
            if lib is not None:
                lib.pt_trace_start()
            self._tracing = True
        elif not should_trace and self._tracing:
            jax.profiler.stop_trace()
            lib = _native.load()
            if lib is not None:
                lib.pt_trace_stop()
            self._tracing = False

    def export(self, path=None, format="json"):
        if path and format == "json":
            import os

            os.makedirs(path, exist_ok=True)
            export_host_chrome_trace(os.path.join(path, "host_trace.json"))
        return self._trace_dir

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        lines = ["---- host op summary (wall) ----"]
        scale = {"ms": 1e3, "s": 1.0, "us": 1e6}[time_unit]
        for name, total in sorted(_host_events.totals.items(), key=lambda kv: -kv[1]):
            n = _host_events.counts[name]
            lines.append(f"{name:<48} calls={n:<8} total={total * scale:.3f}{time_unit} avg={total / n * scale:.3f}{time_unit}")
        if self._step_times:
            ts = [t for t, _ in self._step_times]
            lines.append(f"steps={len(ts)} avg_step={sum(ts) / len(ts) * 1e3:.2f}ms")
        table = "\n".join(lines)
        print(table)
        return table

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        t, n = self._step_times[-1]
        ips = (n / t) if (n and t > 0) else (1.0 / t if t > 0 else 0.0)
        return f"step_time: {t * 1e3:.2f} ms, ips: {ips:.2f}"

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


@contextlib.contextmanager
def profile(log_dir="./profiler_log"):
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class ProfilerResult:
    """Aggregated view of an exported chrome trace (reference:
    profiler_statistic.py statistics over the event tree)."""

    def __init__(self, events):
        self.events = events
        agg = {}
        for e in events:
            if e.get("ph") != "X":
                continue
            name = e.get("name", "?")
            d = agg.setdefault(name, {"calls": 0, "total_us": 0.0})
            d["calls"] += 1
            d["total_us"] += float(e.get("dur", 0.0))
        self.summary = {
            n: {**d, "avg_us": d["total_us"] / max(d["calls"], 1)}
            for n, d in agg.items()}

    def sorted_by_total(self):
        return sorted(self.summary.items(), key=lambda kv: -kv[1]["total_us"])


def load_profiler_result(path):
    """Load an exported chrome-trace JSON (export_chrome_tracing /
    export_host_chrome_trace output, or a jax.profiler trace dir) into a
    ProfilerResult with per-name call counts and durations. Raw XPlane
    protobuf dumps remain TensorBoard-profile territory."""
    import gzip
    import json
    import os

    if os.path.isdir(path):
        cands = [os.path.join(r, f) for r, _, fs in os.walk(path)
                 for f in fs if f.endswith((".json", ".json.gz",
                                            ".trace.json.gz"))]
        if not cands:
            raise FileNotFoundError(
                f"no chrome-trace .json under {path} (XPlane-only dump? "
                "open it with TensorBoard's profile plugin)")
        path = max(cands, key=os.path.getmtime)  # newest capture
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", data if isinstance(data, list) else [])
    return ProfilerResult(events)


import enum as _enum


class SortedKeys(_enum.IntEnum):
    """Summary sort keys (reference: profiler/profiler_statistic.py SortedKeys)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(_enum.IntEnum):
    """Summary view selector (reference: profiler/profiler.py SummaryView)."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8
