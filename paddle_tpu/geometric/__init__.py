"""paddle_tpu.geometric — graph-learning ops.

Parity anchors: the reference's paddle.geometric package —
segment reductions (python/paddle/geometric/math.py:29 segment_sum et al.
over phi segment_pool kernels), message passing
(geometric/message_passing/send_recv.py:55 send_u_recv, :210 send_ue_recv,
:413 send_uv over graph_send_recv kernels), graph reindexing
(geometric/reindex.py:32 reindex_graph/reindex_heter_graph) and neighbor
sampling (geometric/sampling/neighbors.py:68 sample_neighbors,
weighted_sample_neighbors).

TPU-native design: the dense per-edge/per-node compute (gather → message →
segment-reduce) maps to ``jnp.take`` + ``jax.ops.segment_*`` — XLA lowers
them to fused gather/scatter that stay on-device and differentiate through
``jax.grad``. The structural ops (reindex, neighbor sampling) have
data-DEPENDENT output shapes, so — like the reference, whose sampling
pipeline runs on concrete tensors between training steps — they execute
eagerly on host numpy and return concrete Tensors.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.op_registry import apply_fn
from ..core.tensor import Tensor

__all__ = [
    "send_u_recv", "send_ue_recv", "send_uv",
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "reindex_graph", "reindex_heter_graph",
    "sample_neighbors", "weighted_sample_neighbors",
]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap(x):
    return Tensor(x) if not isinstance(x, jax.core.Tracer) else x


def _num_segments(ids, hint=None):
    if hint is not None:
        return int(hint)
    if isinstance(ids, jax.core.Tracer):
        raise ValueError(
            "segment/send ops under jit need a static out_size / "
            "num_segments (data-dependent output shapes cannot be traced)")
    return int(jnp.max(ids)) + 1 if ids.size else 0


# ---------------------------------------------------------------------------
# segment reductions (math.py)
# ---------------------------------------------------------------------------

def _reduce_to_dst(msg, dst, n, reduce_op):
    # segment counts in fp32: a low-precision data dtype (bf16) loses
    # integer exactness above 256, corrupting means for high-degree nodes
    def counts():
        return jax.ops.segment_sum(jnp.ones((msg.shape[0],), jnp.float32),
                                   dst, num_segments=n)

    if reduce_op == "sum":
        return jax.ops.segment_sum(msg, dst, num_segments=n)
    if reduce_op == "mean":
        tot = jax.ops.segment_sum(msg, dst, num_segments=n)
        cnt = jnp.maximum(counts(), 1.0).astype(msg.dtype)
        return tot / cnt.reshape((n,) + (1,) * (msg.ndim - 1))
    if reduce_op in ("min", "max"):
        fn = jax.ops.segment_min if reduce_op == "min" else jax.ops.segment_max
        out = fn(msg, dst, num_segments=n)
        # empty rows: the reference's kernels write 0, not +-inf
        mask = (counts() > 0).reshape((n,) + (1,) * (msg.ndim - 1))
        return jnp.where(mask, out, jnp.zeros_like(out))
    raise ValueError(f"reduce_op must be sum/mean/min/max, got {reduce_op!r}")


def _segment(data, segment_ids, op, num_segments=None):
    """Dispatched through apply_fn so eager tape autograd flows through the
    data input (the reference's segment kernels are dygraph-differentiable)."""
    ids = _arr(segment_ids).astype(jnp.int32)
    n = _num_segments(ids, num_segments)

    def impl(d):
        return _reduce_to_dst(d, ids, n, op)

    if isinstance(data, Tensor):      # eager: dispatched (tape autograd)
        return apply_fn(f"geometric.segment_{op}", impl, data)
    return _wrap(impl(_arr(data)))    # raw arrays -> Tensor; tracers pass


def segment_sum(data, segment_ids, name=None):
    """out[i] = sum of data rows with segment_ids == i (math.py:29)."""
    return _segment(data, segment_ids, "sum")


def segment_mean(data, segment_ids, name=None):
    """Mean per segment; empty segments give 0 (math.py:84)."""
    return _segment(data, segment_ids, "mean")


def segment_min(data, segment_ids, name=None):
    """Min per segment; empty segments give 0 (math.py:140)."""
    return _segment(data, segment_ids, "min")


def segment_max(data, segment_ids, name=None):
    """Max per segment; empty segments give 0 (math.py:196)."""
    return _segment(data, segment_ids, "max")


# ---------------------------------------------------------------------------
# message passing (send_recv.py)
# ---------------------------------------------------------------------------



def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src_index], reduce into rows dst_index
    (send_recv.py:55 graph_send_recv). out rows = out_size (static under
    jit) or max(dst_index)+1; untouched rows are 0."""
    xa = _arr(x)
    src = _arr(src_index).astype(jnp.int32)
    dst = _arr(dst_index).astype(jnp.int32)
    # reference default (out_size None/<=0): output dim0 == x.shape[0]
    n = (int(out_size) if out_size is not None and int(out_size) > 0
         else xa.shape[0])
    def impl(xd):
        return _reduce_to_dst(jnp.take(xd, src, axis=0), dst, n, reduce_op)

    if isinstance(x, Tensor):
        return apply_fn("geometric.send_u_recv", impl, x)
    return _wrap(impl(xa))


def _edge_message(xg, y, message_op):
    y = _arr(y)
    if y.ndim < xg.ndim:
        y = y.reshape(y.shape + (1,) * (xg.ndim - y.ndim))
    if message_op == "add":
        return xg + y
    if message_op == "sub":
        return xg - y
    if message_op == "mul":
        return xg * y
    if message_op == "div":
        return xg / y
    raise ValueError(f"message_op must be add/sub/mul/div, got {message_op!r}")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Gather x[src_index], combine with per-edge y via message_op, reduce
    into rows dst_index (send_recv.py:210 graph_send_ue_recv)."""
    xa = _arr(x)
    src = _arr(src_index).astype(jnp.int32)
    dst = _arr(dst_index).astype(jnp.int32)
    n = (int(out_size) if out_size is not None and int(out_size) > 0
         else xa.shape[0])
    def impl(xd, yd):
        return _reduce_to_dst(
            _edge_message(jnp.take(xd, src, axis=0), yd, message_op),
            dst, n, reduce_op)

    if isinstance(x, Tensor) or isinstance(y, Tensor):
        x_t = x if isinstance(x, Tensor) else Tensor(xa)
        y_t = y if isinstance(y, Tensor) else Tensor(_arr(y))
        return apply_fn("geometric.send_ue_recv", impl, x_t, y_t)
    return _wrap(impl(xa, _arr(y)))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-EDGE output op(x[src], y[dst]) with no reduction
    (send_recv.py:413 graph_send_uv)."""
    src = _arr(src_index).astype(jnp.int32)
    dst = _arr(dst_index).astype(jnp.int32)
    def impl(xd, yd):
        return _edge_message(jnp.take(xd, src, axis=0),
                             jnp.take(yd, dst, axis=0), message_op)

    if isinstance(x, Tensor) or isinstance(y, Tensor):
        x_t = x if isinstance(x, Tensor) else Tensor(_arr(x))
        y_t = y if isinstance(y, Tensor) else Tensor(_arr(y))
        return apply_fn("geometric.send_uv", impl, x_t, y_t)
    return _wrap(impl(_arr(x), _arr(y)))


# ---------------------------------------------------------------------------
# reindex (reindex.py) — host-side, data-dependent shapes
# ---------------------------------------------------------------------------

def _np(x):
    return np.asarray(x._data if isinstance(x, Tensor) else x)


def _reindex(x, neighbor_lists, count_lists):
    x = _np(x).reshape(-1)
    mapping = {}
    out_nodes = []
    for v in x.tolist():
        mapping[v] = len(out_nodes)
        out_nodes.append(v)
    srcs, dsts = [], []
    for neighbors, count in zip(neighbor_lists, count_lists):
        neighbors = _np(neighbors).reshape(-1)
        count = _np(count).reshape(-1)
        for v in neighbors.tolist():
            if v not in mapping:
                mapping[v] = len(out_nodes)
                out_nodes.append(v)
        srcs.append(np.asarray([mapping[v] for v in neighbors.tolist()],
                               np.int64))
        dsts.append(np.repeat(np.arange(len(count), dtype=np.int64), count))
    src = np.concatenate(srcs) if srcs else np.zeros((0,), np.int64)
    dst = np.concatenate(dsts) if dsts else np.zeros((0,), np.int64)
    nodes = np.asarray(out_nodes, x.dtype)
    return (Tensor(src.astype(x.dtype)), Tensor(dst.astype(x.dtype)),
            Tensor(nodes))


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Reindex sampled-subgraph node ids from 0 (reindex.py:32): returns
    (reindex_src, reindex_dst, out_nodes); out_nodes = x ++ first-seen
    neighbors not in x. Host-side (data-dependent shapes), like the
    reference's sampling pipeline."""
    return _reindex(x, [neighbors], [count])


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """reindex_graph over per-edge-type neighbor lists sharing one node set
    (reindex.py:157)."""
    return _reindex(x, list(neighbors), list(count))


# ---------------------------------------------------------------------------
# neighbor sampling (sampling/neighbors.py) — host-side
# ---------------------------------------------------------------------------

def _sample(row, colptr, input_nodes, sample_size, eids, return_eids,
            weights=None):
    row = _np(row).reshape(-1)
    colptr = _np(colptr).reshape(-1)
    nodes = _np(input_nodes).reshape(-1)
    if eids is not None:
        eids = _np(eids).reshape(-1)
    elif return_eids:
        raise ValueError("return_eids=True requires eids")
    out_n, out_c, out_e = [], [], []
    w_all = _np(weights).reshape(-1).astype(np.float64) \
        if weights is not None else None
    # reproducible under paddle.seed: the framework RNG stream seeds numpy
    from ..framework import random as frandom

    rng = np.random.default_rng(frandom.next_host_seed())
    for n in nodes.tolist():
        lo, hi = int(colptr[n]), int(colptr[n + 1])
        deg = hi - lo
        idx = np.arange(lo, hi)
        if 0 <= sample_size < deg:
            if w_all is None:
                idx = rng.choice(idx, size=sample_size, replace=False)
            else:
                w = w_all[lo:hi]
                p = w / w.sum() if w.sum() > 0 else None
                idx = rng.choice(idx, size=sample_size, replace=False, p=p)
            deg = sample_size
        out_n.append(row[idx])
        out_c.append(deg)
        if return_eids:
            out_e.append(eids[idx])
    neighbors = (np.concatenate(out_n) if out_n
                 else np.zeros((0,), row.dtype))
    counts = np.asarray(out_c, np.int32)
    if return_eids:
        e = np.concatenate(out_e) if out_e else np.zeros((0,), row.dtype)
        return Tensor(neighbors), Tensor(counts), Tensor(e)
    return Tensor(neighbors), Tensor(counts)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling over a CSC graph
    (sampling/neighbors.py:68): returns (neighbors, counts[, eids]).
    sample_size=-1 takes all neighbors."""
    return _sample(row, colptr, input_nodes, sample_size, eids, return_eids)


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weight-proportional sampling without replacement
    (sampling/neighbors.py weighted_sample_neighbors)."""
    return _sample(row, colptr, input_nodes, sample_size, eids, return_eids,
                   weights=edge_weight)
