"""PT-COST checks — diagnostics over a traced hot path's cost manifest.

Five code classes (docs/STATIC_ANALYSIS.md, PT-COST section), enforced by
tools/audit_program_cost.py against tools/program_cost_baseline.json:

- PT-COST-001  unintended f32 promotion of a bf16 path: a half-precision
               value widened by implicit promotion against a full-precision
               SCALAR constant (the ``x * np.float32(2.0)`` weak-type
               accident — jnp materializes it as an upcast convert feeding
               an op with an f32 scalar literal), plus contract drift on the
               program's total upcast-convert census.
- PT-COST-002  host-sync / host-transfer primitive inside a jitted program
               (callbacks, infeed/outfeed, device_put) — the jaxpr-level
               sibling of the PT-TRACE-004 source scan.
- PT-COST-003  a step-to-step carry buffer the jitted program does NOT
               donate (read from the traced pjit's ``donated_invars``) —
               every undonated carry doubles its HBM footprint and forces
               a copy per step.
- PT-COST-004  scatter/gather equation count exceeding the recorded
               contract — the scatter machinery is the part of the serving
               program that grows by accident.
- PT-COST-005  slot-scaling law violation: program text or FLOPs growing
               superlinearly in slot width across the traced width pair.

Every diagnostic carries a line-number-free ``finding_id``
(``CODE:program:detail``) so baseline waivers survive refactors — the
PT-RACE baseline discipline (tools/lint_concurrency.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..analysis.diagnostics import Diagnostic, Severity
from .flops import HOST_SYNC_PRIMS, closed_jaxpr_of, iter_eqn_costs
from .manifest import _NARROW, _WIDE, CostManifest, scaling_verdict

__all__ = ["check_dtype_promotion", "check_host_sync", "check_donation",
           "check_contract", "check_slot_scaling"]

_ANALYZER = "ProgramCostAuditor"


def _diag(code, severity, message, program, detail, prim=None):
    d = Diagnostic(code=code, severity=Severity(severity), message=message,
                   op_type=prim, analyzer=_ANALYZER)
    d.finding_id = f"{code}:{program}:{detail}"
    return d


def _is_scalar_wide_literal(var) -> bool:
    """A Literal (or 0-d constant) of full-precision float dtype — the
    poisoning operand of an accidental promotion."""
    val = getattr(var, "val", None)
    if val is None:
        return False
    aval = getattr(var, "aval", None)
    shape = tuple(getattr(aval, "shape", ()) or ())
    dtype = str(getattr(aval, "dtype", ""))
    return shape == () and dtype in _WIDE


def check_dtype_promotion(program_or_jaxpr,
                          name: str = "program") -> List[Diagnostic]:
    """PT-COST-001 (pattern form): find ops consuming BOTH an upcast of a
    half-precision value AND a full-precision scalar constant — the
    signature jnp leaves behind when a stray ``np.float32`` literal
    promotes a bf16 path (a weak-typed python scalar would have stayed
    bf16). Explicit ``.astype(f32)`` accumulations without a poisoning
    scalar (matmul/softmax internals) do not match; they are counted (not
    flagged) by the manifest's ``upcast_converts`` census and gated by
    contract drift instead.

    Known false positive (docs/STATIC_ANALYSIS.md limits): a DELIBERATE
    upcast scaled by a python scalar (``q.astype(f32) * 0.125``) traces to
    the identical jaxpr — promotion resolves the weak scalar to a strong
    f32 literal, so post-trace the two are indistinguishable. Waive such
    findings in the baseline with a justification."""
    from .flops import _inner_jaxprs

    findings: List[Diagnostic] = []
    closed = closed_jaxpr_of(program_or_jaxpr)
    if closed is None:
        return findings

    def scan_scope(jaxpr, scope):
        upcast_outs = set()
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim == "convert_element_type":
                src = eqn.invars[0]
                s_dt = str(getattr(getattr(src, "aval", None), "dtype", ""))
                o_dt = str(getattr(getattr(eqn.outvars[0], "aval", None),
                                   "dtype", ""))
                if s_dt in _NARROW and o_dt in _WIDE:
                    upcast_outs.add(id(eqn.outvars[0]))
                continue
            has_upcast = any(id(v) in upcast_outs for v in eqn.invars)
            has_scalar = any(_is_scalar_wide_literal(v) for v in eqn.invars)
            if has_upcast and has_scalar:
                findings.append(_diag(
                    "PT-COST-001", Severity.ERROR,
                    f"'{prim}'{scope or ''}: a half-precision value is "
                    "promoted to f32 against a full-precision scalar "
                    "constant — use a weak-typed python scalar (or cast "
                    "the constant to the narrow dtype) to keep the bf16 "
                    "path narrow", name, f"{prim}{scope}", prim=prim))
            for sub, _, sfx in _inner_jaxprs(eqn):
                scan_scope(getattr(sub, "jaxpr", sub),
                           scope + "/" + prim + sfx)
    scan_scope(getattr(closed, "jaxpr", closed), "")
    return findings


def check_host_sync(program_or_jaxpr,
                    name: str = "program") -> List[Diagnostic]:
    """PT-COST-002: host-sync/transfer primitives inside the traced
    program. Cross-link: PT-TRACE-004 catches the same class in SOURCE
    (``.item()``/``.numpy()`` before tracing chokes); this catches what
    actually made it into the jaxpr (callbacks, infeed/outfeed,
    device_put)."""
    findings = []
    for e in iter_eqn_costs(program_or_jaxpr):
        if e.prim in HOST_SYNC_PRIMS:
            findings.append(_diag(
                "PT-COST-002", Severity.ERROR,
                f"host-sync primitive '{e.prim}'{e.scope or ''} inside a "
                "jitted hot path — every dispatch round-trips the host "
                "(source-level sibling: PT-TRACE-004)",
                name, f"{e.prim}{e.scope}", prim=e.prim))
    return findings


def check_donation(manifest: CostManifest) -> List[Diagnostic]:
    """PT-COST-003: carries declared by the program's HotPathSpec that the
    traced jitted callable does NOT donate (``donated_invars`` audit)."""
    findings = []
    for carry in (manifest.donation or {}).get("missing", ()):
        findings.append(_diag(
            "PT-COST-003", Severity.ERROR,
            f"carry buffer '{carry}' is not donated by the jitted step "
            "program — the old buffer stays live across the step, doubling "
            "its HBM footprint (add donate_argnums for the carry)",
            manifest.program, carry))
    return findings


def check_contract(manifest: CostManifest,
                   baseline: Optional[Dict]) -> List[Diagnostic]:
    """PT-COST-004 (+ the census drift half of PT-COST-001): static
    equation counts exceeding the recorded per-program contract. Counts
    may go DOWN freely (refresh the baseline to ratchet); an increase is a
    finding until reviewed. A program with no baseline entry is itself a
    finding — an unreviewed hot path cannot silently pass."""
    name = manifest.program
    if not baseline:
        return [_diag(
            "PT-COST-004", Severity.ERROR,
            f"program '{name}' has no entry in the cost baseline — record "
            "it (tools/audit_program_cost.py --write-baseline) and review "
            "the manifest", name, "unbaselined")]
    findings = []
    for attr, code in (("scatter_ops", "PT-COST-004"),
                       ("gather_ops", "PT-COST-004"),
                       ("host_sync_eqns", "PT-COST-002"),
                       ("upcast_converts", "PT-COST-001")):
        have = int(getattr(manifest, attr))
        want = baseline.get(attr)
        if want is None:
            continue
        if have > int(want):
            findings.append(_diag(
                code, Severity.ERROR,
                f"{attr} grew {int(want)} -> {have} vs the recorded "
                f"contract for '{name}' — review the new "
                f"{attr.replace('_', ' ')} (or refresh the baseline with "
                "a justification)", name, f"{attr}-drift"))
    # gross program-text blowup guard for single-width programs (the
    # slot-scaling law only covers width pairs): a duplicated layer call
    # or an unrolled python loop roughly multiplies the eqn census.
    # Ordinary edits drift well within 1.5x and pass without a refresh.
    base_eqns = baseline.get("num_eqns")
    if base_eqns and manifest.num_eqns > 1.5 * int(base_eqns):
        findings.append(_diag(
            "PT-COST-004", Severity.ERROR,
            f"num_eqns grew {int(base_eqns)} -> {manifest.num_eqns} "
            f"(>1.5x) vs the recorded baseline for '{name}' — program "
            "text blew up (duplicated subgraph / unrolled loop?); review "
            "and refresh the baseline", name, "num_eqns-blowup"))
    return findings


def check_slot_scaling(manifests: Sequence[CostManifest],
                       tol: float = 0.25) -> List[Diagnostic]:
    """PT-COST-005: apply :func:`scaling_verdict` over the slot-width pair
    and flag a superlinear verdict."""
    rec = scaling_verdict(manifests, tol=tol)
    if rec["verdict"] == "superlinear":
        name = manifests[0].program.split("@")[0]
        return [_diag(
            "PT-COST-005", Severity.ERROR,
            f"program '{name}' scales SUPERLINEARLY in slots "
            f"(worst per-slot growth ratio {rec['worst_linear_ratio']}x "
            f"over widths {rec['slots']}; eqns {rec['num_eqns']}, flops "
            f"{[round(f) for f in rec['flops_total']]}) — an O(slots^2) "
            "term in the step machinery", name, "superlinear")]
    return []
