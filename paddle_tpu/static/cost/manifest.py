"""Cost manifest — the per-program record the PT-COST gate baselines.

``compute_manifest`` folds the walker stream (flops.py) into one JSON-able
:class:`CostManifest`: FLOPs per op family, byte traffic + arithmetic
intensity, a full dtype census, host-sync / scatter / gather / upcast
counts, the donation audit (read from the traced ``pjit`` equation's
``donated_invars`` — the actual donation the jitted callable declares, not
a hand-maintained list), and, once :func:`scaling_verdict` has seen the
same program at two slot widths, the slot-scaling law record.

Counts come in two flavors, deliberately:

- ``num_eqns`` / ``scatter_ops`` / ``gather_ops`` / ``upcast_converts`` /
  ``host_sync_eqns`` are STATIC equation counts (scan bodies count once) —
  they measure *program text growth*, the thing that explodes when a
  python loop accidentally unrolls per slot.
- ``flops`` / ``bytes_total`` apply the execution multipliers (a scan body
  of length L counts L times) — they measure *work*.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .flops import FAMILIES, HOST_SYNC_PRIMS, closed_jaxpr_of, iter_eqn_costs

__all__ = ["CostManifest", "HotPathSpec", "compute_manifest",
           "scaling_verdict"]

#: upcasts the dtype census calls out: a half-precision value widened to a
#: full-precision one (the bf16->f32 weak-type accident class)
_NARROW = ("bfloat16", "float16")
_WIDE = ("float32", "float64")


@dataclass
class HotPathSpec:
    """Reviewed registration of one hot-path program (tools/
    audit_program_cost.py): which argument subtrees are step-to-step
    carries (and therefore must be donated), where they sit in the traced
    callable's flat input order, and the program's slot width for the
    scaling law."""

    name: str
    #: carry name -> (lo, hi) flat-invar index range of the traced call
    carries: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    slots: Optional[int] = None
    notes: str = ""


@dataclass
class CostManifest:
    program: str
    slots: Optional[int] = None
    num_eqns: int = 0                     # static, containers recursed
    flops: Dict[str, float] = field(default_factory=dict)   # per family
    bytes_total: float = 0.0
    arithmetic_intensity: float = 0.0
    dtypes: Dict[str, Dict[str, float]] = field(default_factory=dict)
    upcast_converts: int = 0
    host_sync_eqns: int = 0
    host_sync_prims: List[str] = field(default_factory=list)
    scatter_ops: int = 0
    gather_ops: int = 0
    while_loops: int = 0                  # unknown-trip containers: the
    #                                       flop/byte totals UNDERCOUNT these
    donation: Dict[str, List[str]] = field(default_factory=dict)
    scaling: Optional[Dict] = None

    @property
    def flops_total(self) -> float:
        return self.flops.get("total", 0.0)

    def to_dict(self) -> Dict:
        return {
            "program": self.program, "slots": self.slots,
            "num_eqns": self.num_eqns, "flops": dict(self.flops),
            "bytes_total": self.bytes_total,
            "arithmetic_intensity": self.arithmetic_intensity,
            "dtypes": {k: dict(v) for k, v in self.dtypes.items()},
            "upcast_converts": self.upcast_converts,
            "host_sync_eqns": self.host_sync_eqns,
            "host_sync_prims": list(self.host_sync_prims),
            "scatter_ops": self.scatter_ops, "gather_ops": self.gather_ops,
            "while_loops": self.while_loops,
            "donation": {k: list(v) for k, v in self.donation.items()},
            "scaling": self.scaling,
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "CostManifest":
        m = cls(program=d.get("program", "?"))
        for k, v in d.items():
            if hasattr(m, k):
                setattr(m, k, v)
        return m


def _donation_audit(closed, carries: Dict[str, Tuple[int, int]]):
    """Read the ACTUAL donation off the outermost ``pjit`` equation of a
    traced jitted callable. A carry is donated iff every flat invar in its
    range is marked in ``donated_invars``. Programs traced from a bare
    function (no jit wrapper) have no pjit equation — nothing is donated."""
    donated_invars = None
    if closed is not None:
        jaxpr = getattr(closed, "jaxpr", closed)
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pjit":
                donated_invars = eqn.params.get("donated_invars")
                break
    names, donated, missing = [], [], []
    for name, (lo, hi) in carries.items():
        names.append(name)
        ok = (donated_invars is not None and hi <= len(donated_invars)
              and all(donated_invars[lo:hi]))
        (donated if ok else missing).append(name)
    return {"carries": names, "donated": donated, "missing": missing}


def compute_manifest(program_or_jaxpr, name: str = "program",
                     spec: Optional[HotPathSpec] = None) -> CostManifest:
    """Fold the cost walk into one manifest. Pure tracing arithmetic — no
    XLA compile, no device dispatch. When the argument is a traced Program
    import, the manifest is also attached as ``program._cost_manifest``."""
    m = CostManifest(program=name,
                     slots=spec.slots if spec is not None else None)
    flops = {f: 0.0 for f in FAMILIES}
    total_f = total_b = 0.0
    for e in iter_eqn_costs(program_or_jaxpr):
        m.num_eqns += 1
        flops[e.family] = flops.get(e.family, 0.0) + e.total_flops
        total_f += e.total_flops
        total_b += e.total_bytes
        if e.prim in HOST_SYNC_PRIMS:
            m.host_sync_eqns += 1
            m.host_sync_prims.append(e.prim)
        if e.family == "scatter":
            m.scatter_ops += 1
        elif e.family == "gather":
            m.gather_ops += 1
        if e.prim == "while":
            m.while_loops += 1
        if (e.prim == "convert_element_type" and e.in_dtypes
                and e.out_dtypes and e.in_dtypes[0] in _NARROW
                and e.out_dtypes[0] in _WIDE):
            m.upcast_converts += 1
        if e.out_dtypes:
            # census: the eqn and its traffic ride the first output's dtype
            slot = m.dtypes.setdefault(e.out_dtypes[0],
                                       {"eqns": 0, "bytes": 0.0})
            slot["eqns"] += 1
            slot["bytes"] += e.total_bytes
    m.flops = {k: v for k, v in flops.items() if v} or {}
    m.flops["total"] = total_f
    m.bytes_total = total_b
    m.arithmetic_intensity = (total_f / total_b) if total_b else 0.0
    closed = closed_jaxpr_of(program_or_jaxpr)
    if spec is not None and spec.carries:
        m.donation = _donation_audit(closed, spec.carries)
    if hasattr(program_or_jaxpr, "global_block"):
        program_or_jaxpr._cost_manifest = m
    return m


def scaling_verdict(manifests: Sequence[CostManifest],
                    tol: float = 0.25) -> Dict:
    """The slot-scaling law (PT-COST-005): given the SAME program traced at
    ascending slot widths, program text (``num_eqns``) and work
    (``flops_total``) must scale at most linearly in slots — an accidental
    O(slots^2) term (a per-slot python loop unrolling, a dense slot x slot
    interaction in the scatter machinery) fails the law. The verdict is
    recorded onto every participating manifest."""
    ms = sorted(manifests, key=lambda m: (m.slots or 0))
    slots = [m.slots for m in ms]
    if len(ms) < 2 or any(s is None or s <= 0 for s in slots):
        raise ValueError("scaling law needs >=2 manifests with slot widths")
    verdict, worst = "<=linear", 0.0
    for a, b in zip(ms, ms[1:]):
        grow = b.slots / a.slots
        for attr in ("num_eqns", "flops_total"):
            va, vb = float(getattr(a, attr)), float(getattr(b, attr))
            if va <= 0:
                continue
            ratio = (vb / va) / grow        # 1.0 == exactly linear
            worst = max(worst, ratio)
            if ratio > 1.0 + tol:
                verdict = "superlinear"
    rec = {"slots": slots, "num_eqns": [m.num_eqns for m in ms],
           "flops_total": [m.flops_total for m in ms],
           "verdict": verdict, "worst_linear_ratio": round(worst, 4),
           "tol": tol}
    for m in ms:
        m.scaling = rec
    return rec
