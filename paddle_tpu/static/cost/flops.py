"""Recursive jaxpr cost walker — the arithmetic under the PT-COST manifest.

A traced hot-path program (``trace_to_program`` keeps the ClosedJaxpr on the
imported Program as ``_closed_jaxpr``) is walked equation by equation,
RECURSING into container primitives — ``scan`` bodies multiply by their
trip count, ``pjit``/``remat``/``custom_*_call`` inline at 1x, ``while``
bodies count ONCE (trip count is data-dependent; the manifest records how
many unknown-trip loops the estimate leaves out), ``cond`` counts every
branch (a deliberate upper bound). Each equation yields an :class:`EqnInfo`
with a roofline-style FLOP estimate and an HBM byte-traffic estimate
(operand + result bytes — reuse inside XLA fusions is invisible at jaxpr
level, so treat both as *estimates for comparison across revisions of the
same program*, not absolute hardware counters; that is exactly what the
baseline gate needs).

FLOP conventions (documented in docs/STATIC_ANALYSIS.md): dot_general =
2*B*M*N*K from its dimension numbers; conv = 2 * out_elems * (C_in/groups *
prod(kernel_spatial)); reductions = input elems; sort/top_k = n*ceil(log2
(extent)); every other elementwise op = 1 FLOP per output element
(transcendentals deliberately NOT weighted — the census is a drift
detector, not a cycle model); pure data movement (reshape/transpose/
gather/scatter/convert/...) = 0 FLOPs, bytes only.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

__all__ = ["EqnInfo", "iter_eqn_costs", "closed_jaxpr_of", "FAMILIES"]

#: manifest flop/byte breakdown buckets
FAMILIES = ("dot", "conv", "elementwise", "reduce", "sort", "rng",
            "gather", "scatter", "shape", "callback", "container", "other")

#: container primitives — cost lives in their inner jaxprs
_CONTAINER_KEYS = {
    "scan": ("jaxpr",),
    "shard_map": ("jaxpr",),
    "while": ("cond_jaxpr", "body_jaxpr"),
    "cond": ("branches",),
    "pjit": ("jaxpr",),
    "xla_call": ("call_jaxpr",),
    "closed_call": ("call_jaxpr",),
    "core_call": ("call_jaxpr",),
    "remat2": ("jaxpr",),
    "remat": ("jaxpr",),
    "checkpoint": ("jaxpr",),
    "custom_jvp_call": ("call_jaxpr",),
    "custom_vjp_call": ("call_jaxpr",),
    "custom_vjp_call_jaxpr": ("fun_jaxpr",),
}

#: host-sync / host-transfer primitives inside a supposedly device-resident
#: program (PT-COST-002; the source-level sibling is PT-TRACE-004)
HOST_SYNC_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outfeed", "infeed", "device_put",
})

_ZERO_FLOP = frozenset({
    "reshape", "transpose", "broadcast_in_dim", "convert_element_type",
    "slice", "squeeze", "concatenate", "pad", "rev", "copy", "iota",
    "stop_gradient", "gather", "dynamic_slice", "dynamic_update_slice",
    "bitcast_convert_type", "expand_dims", "real", "imag",
})

_REDUCE = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "reduce_xor", "argmax", "argmin", "cumsum", "cumprod",
    "cummax", "cummin", "cumlogsumexp", "reduce_precision",
})

_RNG = frozenset({
    "random_bits", "random_seed", "random_fold_in", "random_wrap",
    "random_unwrap", "threefry2x32", "random_gamma",
})


@dataclass
class EqnInfo:
    """One walked equation (possibly nested): classification + cost."""

    prim: str
    family: str
    flops: float                  # per single execution of this eqn
    bytes: float                  # operand + result bytes, one execution
    mult: int                     # static execution multiplier (scan lengths)
    scope: str                    # "/scan" nesting path, "" at top level
    out_dtypes: Tuple[str, ...] = ()
    in_dtypes: Tuple[str, ...] = ()
    params: Optional[dict] = None
    eqn: object = None            # the jax eqn (dataflow checks); None for
    #                               op-level fallback walks
    is_container: bool = False

    @property
    def total_flops(self) -> float:
        return self.flops * self.mult

    @property
    def total_bytes(self) -> float:
        return self.bytes * self.mult


def _aval_of(x):
    """(shape, dtype) of a jaxpr var / Literal / Program arg (Variable or
    captured Tensor expose ``_data``; python scalars are 0-d)."""
    aval = getattr(x, "aval", None)
    if aval is not None:
        return tuple(getattr(aval, "shape", ())), getattr(aval, "dtype", None)
    data = getattr(x, "_data", None)
    if data is not None:
        return tuple(getattr(data, "shape", ())), getattr(data, "dtype", None)
    shape = getattr(x, "shape", None)
    if shape is not None:
        return tuple(shape), getattr(x, "dtype", None)
    return (), None


def _nbytes(shape, dtype) -> float:
    n = 1
    for s in shape:
        n *= max(int(s), 0)
    try:
        item = dtype.itemsize if dtype is not None else 4
    except Exception:   # jax extended dtypes (PRNG keys) — treat as 4 B
        item = 4
    return float(n * item)


def _nelems(shape) -> float:
    n = 1
    for s in shape:
        n *= max(int(s), 0)
    return float(n)


def _dot_flops(params, in_avals) -> float:
    (lc, rc), (lb, rb) = params["dimension_numbers"]
    lshape, rshape = in_avals[0][0], in_avals[1][0]
    batch = 1
    for d in lb:
        batch *= lshape[d]
    k = 1
    for d in lc:
        k *= lshape[d]
    m = 1
    for i, s in enumerate(lshape):
        if i not in lb and i not in lc:
            m *= s
    n = 1
    for i, s in enumerate(rshape):
        if i not in rb and i not in rc:
            n *= s
    return 2.0 * batch * m * n * k


def _conv_flops(params, in_avals, out_avals) -> float:
    dn = params["dimension_numbers"]
    rshape = in_avals[1][0]
    rhs_spec = getattr(dn, "rhs_spec", None)
    if rhs_spec is None:        # defensive: count as a dense product
        return 2.0 * _nelems(out_avals[0][0]) * _nelems(rshape)
    in_feat = rshape[rhs_spec[1]]
    kernel = 1
    for d in rhs_spec[2:]:
        kernel *= rshape[d]
    groups = int(params.get("feature_group_count", 1)) or 1
    return 2.0 * _nelems(out_avals[0][0]) * (in_feat / groups) * kernel


def _classify(prim: str) -> str:
    if prim in ("dot_general",):
        return "dot"
    if prim == "conv_general_dilated":
        return "conv"
    if prim in HOST_SYNC_PRIMS:
        return "callback"
    if prim in _REDUCE:
        return "reduce"
    if prim in ("sort", "top_k"):
        return "sort"
    if prim in _RNG:
        return "rng"
    if prim == "gather" or prim == "dynamic_slice":
        return "gather"
    if prim.startswith("scatter") or prim == "dynamic_update_slice":
        return "scatter"
    if prim in _ZERO_FLOP:
        return "shape"
    if prim in _CONTAINER_KEYS:
        return "container"
    return "elementwise"


def _eqn_flops(prim: str, family: str, params, in_avals, out_avals) -> float:
    if family in ("shape", "gather", "scatter", "callback", "rng",
                  "container"):
        if family == "rng" and out_avals:
            return _nelems(out_avals[0][0])
        return 0.0
    if family == "dot":
        return _dot_flops(params, in_avals)
    if family == "conv":
        return _conv_flops(params, in_avals, out_avals)
    if family == "reduce":
        return _nelems(in_avals[0][0]) if in_avals else 0.0
    if family == "sort":
        shape = in_avals[0][0] if in_avals else ()
        if not shape:
            return 0.0
        dim = params.get("dimension", len(shape) - 1) \
            if params else len(shape) - 1
        try:
            extent = shape[dim]
        except Exception:
            extent = shape[-1]
        return _nelems(shape) * max(1.0, math.log2(max(int(extent), 2)))
    # elementwise / other: one flop per output element
    return _nelems(out_avals[0][0]) if out_avals else 0.0


def _inner_jaxprs(eqn) -> List[Tuple[object, int, str]]:
    """(inner jaxpr, multiplier, scope suffix) triples for a container."""
    name = eqn.primitive.name
    keys = _CONTAINER_KEYS.get(name)
    if not keys:
        return []
    out = []
    if name == "scan":
        length = int(eqn.params.get("length", 1) or 1)
        out.append((eqn.params["jaxpr"], length, ""))
    elif name == "cond":
        for i, br in enumerate(eqn.params.get("branches", ()) or ()):
            out.append((br, 1, f".branch{i}"))
    else:
        for k in keys:
            sub = eqn.params.get(k)
            if sub is not None:
                sfx = "" if len(keys) == 1 else "." + k.split("_")[0]
                out.append((sub, 1, sfx))
    return out


def _walk_jaxpr(jaxpr, mult: int, scope: str) -> Iterator[EqnInfo]:
    inner = getattr(jaxpr, "jaxpr", jaxpr)   # ClosedJaxpr or Jaxpr
    for eqn in inner.eqns:
        prim = eqn.primitive.name
        in_avals = [_aval_of(v) for v in eqn.invars]
        out_avals = [_aval_of(v) for v in eqn.outvars]
        family = _classify(prim)
        subs = _inner_jaxprs(eqn)
        if subs:
            yield EqnInfo(
                prim=prim, family="container", flops=0.0, bytes=0.0,
                mult=mult, scope=scope, params=eqn.params, eqn=eqn,
                is_container=True,
                out_dtypes=tuple(str(d) for _, d in out_avals),
                in_dtypes=tuple(str(d) for _, d in in_avals))
            for sub, factor, sfx in subs:
                yield from _walk_jaxpr(sub, mult * factor,
                                       scope + "/" + prim + sfx)
            continue
        flops = _eqn_flops(prim, family, eqn.params, in_avals, out_avals)
        byt = sum(_nbytes(s, d) for s, d in in_avals) \
            + sum(_nbytes(s, d) for s, d in out_avals)
        yield EqnInfo(
            prim=prim, family=family, flops=flops, bytes=byt, mult=mult,
            scope=scope, params=eqn.params, eqn=eqn,
            out_dtypes=tuple(str(d) for _, d in out_avals),
            in_dtypes=tuple(str(d) for _, d in in_avals))


def _walk_program_ops(program) -> Iterator[EqnInfo]:
    """Fallback for hand-recorded Programs (no retained jaxpr): per-op
    costs via the ``trace_to_program`` kernel back-links where present;
    ops recorded through arbitrary python callables classify ``other``
    with IO bytes only (the walker cannot see inside them)."""
    for op in program.global_block().ops:
        prim = getattr(op.fn, "_primitive", None)
        params = getattr(op.fn, "_prim_params", None) or {}
        name = prim.name if prim is not None else op.type
        in_avals = [_aval_of(a) for a in list(op.inputs) + list(op.captured)]
        out_avals = [_aval_of(v) for v in op.outputs]
        family = _classify(name) if prim is not None else "other"
        flops = _eqn_flops(name, family, params, in_avals, out_avals) \
            if prim is not None else 0.0
        byt = sum(_nbytes(s, d) for s, d in in_avals) \
            + sum(_nbytes(s, d) for s, d in out_avals)
        yield EqnInfo(
            prim=name, family=family, flops=flops, bytes=byt, mult=1,
            scope="", params=params,
            out_dtypes=tuple(str(d) for _, d in out_avals),
            in_dtypes=tuple(str(d) for _, d in in_avals))


def closed_jaxpr_of(program_or_jaxpr):
    """The retained ClosedJaxpr of a traced import, or the argument itself
    when it already is one (``None`` for hand-recorded Programs)."""
    if hasattr(program_or_jaxpr, "jaxpr") or hasattr(program_or_jaxpr,
                                                     "eqns"):
        return program_or_jaxpr
    return getattr(program_or_jaxpr, "_closed_jaxpr", None)


def iter_eqn_costs(program_or_jaxpr) -> Iterator[EqnInfo]:
    """Walk a traced Program (``trace_to_program`` import) or a raw
    (Closed)Jaxpr, yielding one :class:`EqnInfo` per equation, containers
    recursed."""
    closed = closed_jaxpr_of(program_or_jaxpr)
    if closed is not None:
        yield from _walk_jaxpr(closed, 1, "")
    else:
        yield from _walk_program_ops(program_or_jaxpr)
