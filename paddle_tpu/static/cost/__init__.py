"""paddle_tpu.static.cost — static program-cost auditor (PT-COST).

PR 9 (PT-RACE) made thread-safety a lint-time property of the host stack;
this package does the same for DEVICE-PROGRAM COST. Every registered
hot-path program (the fused serving mega-step, the packed prefill chunk,
the hapi train step, the KV-migration scatters — tools/
audit_program_cost.py) is imported by pure tracing
(``static.analysis.trace_to_program`` — no XLA compile, machine
independent) and folded into a :class:`CostManifest`: FLOPs per op family,
HBM byte traffic + arithmetic intensity, a full dtype census, host-sync /
scatter / gather / upcast counts, the buffer-donation audit read off the
traced ``pjit``'s ``donated_invars``, and the slot-scaling law across a
width pair. The manifest is baselined in tools/program_cost_baseline.json
and enforced in CI, so a bf16 path silently widening to f32, a host sync
creeping into the jitted step, a lost ``donate_argnums``, scatter-count
drift, or an O(slots^2) term in the step machinery fails LINT — before any
hardware run, in the spirit of roofline-style static cost models.

Codes (docs/STATIC_ANALYSIS.md): PT-COST-001 f32 promotion of a bf16 path,
PT-COST-002 host sync inside a jitted program (jaxpr-level sibling of
PT-TRACE-004), PT-COST-003 undonated carry buffer, PT-COST-004
scatter/gather contract drift, PT-COST-005 superlinear slot scaling.
"""

from __future__ import annotations

from typing import List, Optional

from ..analysis.diagnostics import AnalysisPass, Diagnostic
from .checks import (check_contract, check_donation, check_dtype_promotion,
                     check_host_sync, check_slot_scaling)
from .manifest import (CostManifest, HotPathSpec, compute_manifest,
                       scaling_verdict)

__all__ = [
    "CostManifest", "HotPathSpec", "compute_manifest", "scaling_verdict",
    "ProgramCostPass", "check_dtype_promotion", "check_host_sync",
    "check_donation", "check_contract", "check_slot_scaling",
]


class ProgramCostPass(AnalysisPass):
    """AnalysisPass form of the auditor — composes with ``run_analysis`` /
    the ordinary PassManager beside the PR 1 analyzers. Computes the cost
    manifest (attached as ``program._cost_manifest``) and reports the
    program-local code classes: PT-COST-001 (promotion pattern),
    PT-COST-002 (host sync), and — when a :class:`HotPathSpec` declares
    carries — PT-COST-003 (donation). The cross-program classes
    (PT-COST-004 contract drift, PT-COST-005 slot scaling) need the
    baseline / a width pair and live in tools/audit_program_cost.py."""

    name = "cost"

    def __init__(self, spec: Optional[HotPathSpec] = None, suppress=()):
        super().__init__(suppress=suppress)
        self.spec = spec
        self.manifest: Optional[CostManifest] = None

    def analyze(self, program) -> List[Diagnostic]:
        name = self.spec.name if self.spec is not None else "program"
        self.manifest = compute_manifest(program, name=name, spec=self.spec)
        findings = list(check_dtype_promotion(program, name))
        findings += check_host_sync(program, name)
        if self.spec is not None and self.spec.carries:
            findings += check_donation(self.manifest)
        return findings
