"""Collective census walker — the arithmetic under the PT-COMM manifest.

Walks a traced program's jaxpr (``trace_to_program`` retains the
ClosedJaxpr as ``_closed_jaxpr``) and yields one :class:`CollectiveInfo`
per collective equation, recursing containers: ``shard_map`` bodies bind
their mesh axis sizes (read off the equation's ``mesh`` param — an
AbstractMesh at audit time), ``scan`` bodies multiply by trip count,
``while`` bodies count once (unknown trip; the manifest undercounts
these, same convention as PT-COST), ``cond`` counts every branch.

Per-dispatch wire bytes use the ring-algorithm volumes every production
collective library converges on (per participating device, ``n`` = the
product of the named axis sizes, ``b`` = the operand's per-shard bytes):

==================  ==============================  =====================
primitive           wire bytes                      note
==================  ==============================  =====================
psum / pmin / pmax  ``2 (n-1)/n * b``               reduce-scatter+gather
all_gather          ``(n-1) * b``                   b = the local shard
reduce_scatter      ``(n-1)/n * b``                 b = the full input
all_to_all          ``(n-1)/n * b``                 keeps 1/n locally
ppermute            ``b``                           one neighbour send
==================  ==============================  =====================

``psum2`` (the check_rep rewrite's name for psum) is normalized to
``psum`` so contracts do not depend on the ``check_vma`` flag;
``pbroadcast2`` is a replication *marker* the rewrite inserts — zero
wire bytes, not censused.

Loop-invariance (PT-COMM-002's input) is a taint walk: inside a scan
body the carries and the per-step slices are "varying", the scan consts
are not; an equation's outputs inherit taint from its inputs; a
collective all of whose inputs are untainted re-communicates the same
bytes every iteration and is marked ``loop_invariant``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional, Tuple

from ..cost.flops import _aval_of, _inner_jaxprs, _nbytes, closed_jaxpr_of
from .mesh import mesh_axis_sizes

__all__ = ["CollectiveInfo", "COLLECTIVE_PRIMS", "iter_collectives",
           "wire_bytes"]

#: jaxpr primitive names that move bytes between mesh participants
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmin", "pmax", "all_gather", "reduce_scatter",
    "all_to_all", "ppermute",
})

#: normalization: the check_rep rewrite renames psum -> psum2
_NORMALIZE = {"psum2": "psum"}


@dataclass
class CollectiveInfo:
    """One collective equation (possibly nested), censused."""

    prim: str                     # normalized (psum2 -> psum)
    raw_prim: str
    axes: Tuple[str, ...]         # mesh axes the collective spans
    group_size: int               # product of the named axes' sizes
    payload_bytes: float          # first operand's (per-shard) bytes
    bytes_wire: float             # per-device per-dispatch wire bytes
    mult: int                     # static execution multiplier (scan len)
    scope: str                    # "/shard_map/scan" nesting path
    loop_invariant: bool = False  # inside a scan/while, inputs all consts
    axis_sizes: Dict[str, int] = field(default_factory=dict)
    eqn: object = None

    @property
    def total_wire_bytes(self) -> float:
        return self.bytes_wire * self.mult


def wire_bytes(prim: str, payload_bytes: float, group_size: int) -> float:
    """Ring-algorithm per-device wire bytes for one dispatch (table in
    the module docstring). ``group_size <= 1`` moves nothing."""
    n = max(int(group_size), 1)
    if n <= 1:
        return 0.0
    b = float(payload_bytes)
    p = _NORMALIZE.get(prim, prim)
    if p in ("psum", "pmin", "pmax"):
        return 2.0 * (n - 1) / n * b
    if p == "all_gather":
        return (n - 1.0) * b
    if p in ("reduce_scatter", "all_to_all"):
        return (n - 1.0) / n * b
    if p == "ppermute":
        return b
    return 0.0


def _axes_of(params) -> Tuple[str, ...]:
    """Axis names off a collective's params: psum-family uses ``axes``,
    the rest ``axis_name`` (str or tuple)."""
    ax = params.get("axes", None)
    if ax is None:
        ax = params.get("axis_name", ())
    if isinstance(ax, (str, int)):
        ax = (ax,)
    return tuple(str(a) for a in ax)


def _is_literal(v) -> bool:
    return hasattr(v, "val") and not hasattr(v, "count")


def _tainted(invars, taint) -> bool:
    return any(taint.get(v, False) for v in invars if not _is_literal(v))


def _mark(outvars, taint, value: bool) -> None:
    if taint is None:
        return
    for v in outvars:
        taint[v] = value


def _walk(jaxpr, mult: int, scope: str, sizes: Dict[str, int],
          taint: Optional[dict]) -> Iterator[CollectiveInfo]:
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        prim = eqn.primitive.name
        t_in = _tainted(eqn.invars, taint) if taint is not None else False

        if prim == "shard_map":
            sub_sizes = dict(sizes)
            sub_sizes.update(mesh_axis_sizes(eqn.params.get("mesh")))
            sub = eqn.params.get("jaxpr")
            sub_taint = None
            if taint is not None:
                sj = getattr(sub, "jaxpr", sub)
                sub_taint = {v: taint.get(cv, False)
                             for v, cv in zip(sj.invars, eqn.invars)
                             if not _is_literal(cv)}
            yield from _walk(sub, mult, scope + "/shard_map", sub_sizes,
                             sub_taint)
            _mark(eqn.outvars, taint, t_in)
            continue

        if prim == "scan":
            length = int(eqn.params.get("length", 1) or 1)
            n_consts = int(eqn.params.get("num_consts", 0))
            sub = eqn.params["jaxpr"]
            sj = getattr(sub, "jaxpr", sub)
            # taint starts fresh at every scan: consts are invariant FOR
            # THIS loop whatever they were outside; carries/xs vary
            sub_taint = {v: i >= n_consts for i, v in enumerate(sj.invars)}
            yield from _walk(sub, mult * length, scope + "/scan", sizes,
                             sub_taint)
            _mark(eqn.outvars, taint, True)
            continue

        if prim == "while":
            cn = int(eqn.params.get("cond_nconsts", 0))
            bn = int(eqn.params.get("body_nconsts", 0))
            for key, nconsts, sfx in (("cond_jaxpr", cn, ".cond"),
                                      ("body_jaxpr", bn, ".body")):
                sub = eqn.params.get(key)
                if sub is None:
                    continue
                sj = getattr(sub, "jaxpr", sub)
                sub_taint = {v: i >= nconsts
                             for i, v in enumerate(sj.invars)}
                yield from _walk(sub, mult, scope + "/while" + sfx, sizes,
                                 sub_taint)
            _mark(eqn.outvars, taint, True)
            continue

        subs = _inner_jaxprs(eqn)
        if subs:
            call_in = eqn.invars[1:] if prim == "cond" else eqn.invars
            for sub, factor, sfx in subs:
                sub_taint = None
                if taint is not None:
                    sj = getattr(sub, "jaxpr", sub)
                    if len(sj.invars) == len(call_in):
                        sub_taint = {v: (taint.get(cv, False)
                                         if not _is_literal(cv) else False)
                                     for v, cv in zip(sj.invars, call_in)}
                    else:       # unknown calling convention: no false
                        sub_taint = {v: True for v in sj.invars}  # positives
                yield from _walk(sub, mult * factor,
                                 scope + "/" + prim + sfx, sizes, sub_taint)
            _mark(eqn.outvars, taint, t_in)
            continue

        if prim in COLLECTIVE_PRIMS:
            axes = _axes_of(eqn.params)
            n = 1
            axis_sizes = {}
            for a in axes:
                s = int(sizes.get(a, 1))
                axis_sizes[a] = s
                n *= s
            shape, dtype = _aval_of(eqn.invars[0]) if eqn.invars else ((),
                                                                       None)
            payload = _nbytes(shape, dtype)
            yield CollectiveInfo(
                prim=_NORMALIZE.get(prim, prim), raw_prim=prim, axes=axes,
                group_size=n, payload_bytes=payload,
                bytes_wire=wire_bytes(prim, payload, n), mult=mult,
                scope=scope,
                loop_invariant=(taint is not None and not t_in),
                axis_sizes=axis_sizes, eqn=eqn)
        _mark(eqn.outvars, taint, t_in)


def iter_collectives(program_or_jaxpr,
                     mesh: Optional[Dict[str, int]] = None
                     ) -> Iterator[CollectiveInfo]:
    """Yield every collective in a traced Program / (Closed)Jaxpr.
    ``mesh`` seeds axis sizes for collectives OUTSIDE any shard_map
    (pmap-style programs); shard_map equations bind their own mesh."""
    closed = closed_jaxpr_of(program_or_jaxpr)
    if closed is None:
        return
    yield from _walk(closed, 1, "", dict(mesh or {}), None)
