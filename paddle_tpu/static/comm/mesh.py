"""Symbolic mesh helpers for the PT-COMM auditor.

The auditor never touches real devices: programs are traced under
``jax.sharding.AbstractMesh`` (a mesh of *names and sizes*, no device
array), which jax's shard_map accepts at trace time — ``make_jaxpr``
through it yields the exact collective equations with per-shard avals,
no XLA compile. These helpers build such meshes from the plain
``{axis: size}`` dicts the tools layer records (the MULTICHIP_r01–r05
shapes), and read sizes back off whatever mesh object a ``shard_map``
equation carries (Mesh or AbstractMesh both expose ``.shape``).
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional

__all__ = ["abstract_mesh", "mesh_axis_sizes", "mesh_spec"]


def abstract_mesh(axes: Mapping[str, int]):
    """An ``AbstractMesh`` over ``{axis_name: size}`` — tracing-only, no
    devices. Size-1 axes are legal but add nothing; pass them through so
    the caller's spec names stay valid."""
    from jax.sharding import AbstractMesh

    items = tuple((str(k), int(v)) for k, v in axes.items())
    if not items:
        raise ValueError("abstract_mesh needs at least one axis")
    return AbstractMesh(items)


def mesh_axis_sizes(mesh) -> Dict[str, int]:
    """``{axis: size}`` off a Mesh/AbstractMesh (both expose ``.shape`` as
    an ordered mapping); tolerates anything else by returning {}."""
    try:
        return {str(k): int(v) for k, v in dict(mesh.shape).items()}
    except Exception:
        return {}


def mesh_spec(axes: Mapping[str, int], *entries: Optional[str]):
    """A ``PartitionSpec`` whose entries are masked against the mesh:
    an axis name absent from ``axes`` becomes ``None`` (replicated), so
    one spec expression serves every recorded mesh shape. Entries may be
    ``None``, an axis name, or a tuple of axis names (partial tuples
    keep only the present axes)."""
    from jax.sharding import PartitionSpec

    out = []
    for e in entries:
        if e is None:
            out.append(None)
        elif isinstance(e, tuple):
            kept = tuple(a for a in e if a in axes)
            out.append(kept if len(kept) > 1 else (kept[0] if kept else None))
        else:
            out.append(e if e in axes else None)
    return PartitionSpec(*out)
