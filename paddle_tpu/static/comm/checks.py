"""PT-COMM checks — diagnostics over a traced program's collective census.

Five code classes (docs/STATIC_ANALYSIS.md, PT-COMM section), enforced by
tools/audit_collectives.py against tools/collective_baseline.json:

- PT-COMM-001  accidental full replication: a LARGE operand entering a
               shard_map with no sharded dim while the same equation
               shards its siblings — every device holds (and the
               enclosing dispatch moves) the whole buffer.
- PT-COMM-002  loop-invariant collective inside a scan/while body: all
               of its inputs are loop constants, so the same bytes are
               re-gathered every iteration — hoist it out of the loop.
- PT-COMM-003  superlinear comm-byte scaling with mesh size across a
               traced width pair (the mesh-scaling law, manifest.py).
- PT-COMM-004  an ``all_gather`` whose output is summed over the
               gathered dimension — a reduce_scatter/psum_scatter
               contract moves ``(n-1)/n`` of the bytes instead of
               ``(n-1)``; matmul-reduction variants differ the same way.
- PT-COMM-005  baseline contract drift / unbaselined sharded program /
               a program breaking its explicit ``unsharded`` contract.

Every diagnostic carries a line-number-free ``finding_id``
(``CODE:program:detail``) so baseline waivers survive refactors — the
PT-RACE/PT-COST baseline discipline.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..analysis.diagnostics import Diagnostic, Severity
from ..cost.flops import _aval_of, _inner_jaxprs, _nbytes, closed_jaxpr_of
from .collectives import iter_collectives
from .manifest import CommManifest, mesh_scaling_verdict
from .mesh import mesh_axis_sizes

__all__ = ["check_replication", "check_loop_invariant_collectives",
           "check_mesh_scaling", "check_gather_reduce",
           "check_comm_contract"]

_ANALYZER = "CollectiveCommAuditor"

#: PT-COMM-001 only fires on operands at least this large — small
#: replicated scalars/tables are the normal case, not a defect
_REPLICATION_MIN_BYTES = 1 << 20


def _diag(code, severity, message, program, detail, prim=None):
    d = Diagnostic(code=code, severity=Severity(severity), message=message,
                   op_type=prim, analyzer=_ANALYZER)
    d.finding_id = f"{code}:{program}:{detail}"
    return d


def _shard_map_eqns(closed):
    """Every shard_map equation, recursing containers (scope-labelled)."""
    out = []

    def scan_scope(jaxpr, scope):
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim == "shard_map":
                out.append((eqn, scope))
            for sub, _, sfx in _inner_jaxprs(eqn):
                scan_scope(getattr(sub, "jaxpr", sub),
                           scope + "/" + prim + sfx)
    if closed is not None:
        scan_scope(getattr(closed, "jaxpr", closed), "")
    return out


def check_replication(program_or_jaxpr, name: str = "program",
                      min_bytes: int = _REPLICATION_MIN_BYTES
                      ) -> List[Diagnostic]:
    """PT-COMM-001: for each shard_map over a >1-device mesh whose
    ``in_names`` shard at least one operand, flag every operand of
    ``min_bytes`` or more entering with NO sharded dim (an empty names
    dict, or only size-1 axes) — full replication that is almost always
    an annotation accident on a mesh that shards its consumers."""
    findings: List[Diagnostic] = []
    for eqn, scope in _shard_map_eqns(closed_jaxpr_of(program_or_jaxpr)):
        sizes = mesh_axis_sizes(eqn.params.get("mesh"))
        world = 1
        for v in sizes.values():
            world *= max(int(v), 1)
        if world <= 1:
            continue
        in_names = eqn.params.get("in_names") or ()

        def effective(names_dict):
            return any(sizes.get(str(a), 1) > 1
                       for axs in (names_dict or {}).values() for a in axs)
        sharded = [i for i, nm in enumerate(in_names) if effective(nm)]
        if not sharded:
            continue
        for i, nm in enumerate(in_names):
            if effective(nm) or i >= len(eqn.invars):
                continue
            shape, dtype = _aval_of(eqn.invars[i])
            nb = _nbytes(shape, dtype)
            if nb < min_bytes:
                continue
            findings.append(_diag(
                "PT-COMM-001", Severity.ERROR,
                f"operand {i} of shard_map{scope or ''} "
                f"({'x'.join(map(str, shape))} {dtype}, {nb:.3g} B) enters "
                f"fully REPLICATED while the mesh {sizes} shards its "
                f"siblings — every device holds the whole buffer; shard it "
                f"(or waive with a justification if replication is the "
                f"contract)", name,
                f"replicated:in{i}:{'x'.join(map(str, shape))}",
                prim="shard_map"))
    return findings


def check_loop_invariant_collectives(program_or_jaxpr,
                                     name: str = "program"
                                     ) -> List[Diagnostic]:
    """PT-COMM-002: collectives inside a scan/while body whose inputs are
    all loop constants — the same bytes cross the wire every iteration.
    Hoist the collective above the loop (gather once, close over the
    result)."""
    findings: List[Diagnostic] = []
    for c in iter_collectives(program_or_jaxpr):
        if not c.loop_invariant:
            continue
        if "/scan" not in c.scope and "/while" not in c.scope:
            continue
        times = f"{c.mult}x" if c.mult > 1 else "every iteration"
        findings.append(_diag(
            "PT-COMM-002", Severity.ERROR,
            f"loop-invariant '{c.prim}' over {c.axes}{c.scope}: all inputs "
            f"are loop constants, so {c.bytes_wire:.3g} wire B are "
            f"re-communicated {times} — hoist the collective out of the "
            f"loop body", name, f"{c.prim}{c.scope}", prim=c.raw_prim))
    return findings


def check_gather_reduce(program_or_jaxpr,
                        name: str = "program") -> List[Diagnostic]:
    """PT-COMM-004: ``all_gather`` feeding a ``reduce_sum`` over the
    gathered dimension (directly or through a dtype convert) — the
    gather moves ``(n-1) * b`` where a reduce_scatter (+ small gather if
    the full result is truly needed) moves ``(n-1)/n * b``. The classic
    Megatron-style contract miss."""
    findings: List[Diagnostic] = []
    closed = closed_jaxpr_of(program_or_jaxpr)
    if closed is None:
        return findings

    def scan_scope(jaxpr, scope):
        gathers = {}   # id(var) -> (gathered dim, raw eqn)
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim == "all_gather":
                gathers[id(eqn.outvars[0])] = (
                    int(eqn.params.get("all_gather_dimension", 0)), eqn)
            elif prim == "convert_element_type" and eqn.invars:
                hit = gathers.get(id(eqn.invars[0]))
                if hit is not None:
                    gathers[id(eqn.outvars[0])] = hit
            elif prim == "reduce_sum":
                axes = tuple(int(a) for a in eqn.params.get("axes", ()))
                for v in eqn.invars:
                    hit = gathers.get(id(v))
                    if hit is not None and hit[0] in axes:
                        g_axes = hit[1].params.get("axis_name", ())
                        findings.append(_diag(
                            "PT-COMM-004", Severity.ERROR,
                            f"all_gather over {g_axes}{scope or ''} is "
                            f"summed over its gathered dim {hit[0]} — a "
                            f"reduce_scatter contract moves (n-1)/n of the "
                            f"bytes instead of (n-1); use psum_scatter (or "
                            f"psum if the full result must be replicated)",
                            name, f"all_gather+reduce_sum{scope}",
                            prim="all_gather"))
            for sub, _, sfx in _inner_jaxprs(eqn):
                scan_scope(getattr(sub, "jaxpr", sub),
                           scope + "/" + prim + sfx)
    scan_scope(getattr(closed, "jaxpr", closed), "")
    return findings


def check_mesh_scaling(manifests: Sequence[CommManifest],
                       tol: float = 0.25) -> List[Diagnostic]:
    """PT-COMM-003: apply :func:`mesh_scaling_verdict` over a width pair
    and flag a superlinear verdict."""
    rec = mesh_scaling_verdict(manifests, tol=tol)
    if rec["verdict"] == "superlinear":
        name = manifests[0].program.split("@")[0]
        return [_diag(
            "PT-COMM-003", Severity.ERROR,
            f"program family '{name}' scales SUPERLINEARLY in mesh size "
            f"(worst ring-envelope ratio {rec['worst_ring_ratio']}x over "
            f"widths {rec['widths']}; wire bytes {rec['comm_bytes']}, "
            f"collective eqns {rec['collective_eqns']}) — an O(mesh^2) "
            f"term in the collective plan", name, "superlinear")]
    return []


def check_comm_contract(manifest: CommManifest,
                        baseline: Optional[Dict]) -> List[Diagnostic]:
    """PT-COMM-005: the baseline contract. A program declaring
    ``unsharded: true`` must trace zero collectives; a program whose
    baseline records a mesh census must NOT silently revert to unsharded
    (or lose a recorded collective primitive) — sharding regressions gate
    exactly like sharding drift; an unbaselined program is itself a
    finding; per-primitive counts and total wire bytes may only change
    through a reviewed refresh."""
    name = manifest.program
    findings: List[Diagnostic] = []
    unsharded = manifest.unsharded or bool((baseline or {}).get("unsharded"))
    if unsharded and manifest.collective_eqns > 0:
        findings.append(_diag(
            "PT-COMM-005", Severity.ERROR,
            f"program '{name}' declares the unsharded contract but traces "
            f"{manifest.collective_eqns} collective(s) "
            f"({dict(manifest.collectives)}) — flip the contract (spec + "
            f"baseline) together with the sharding change",
            name, "unsharded-contract"))
    if not baseline:
        findings.append(_diag(
            "PT-COMM-005", Severity.ERROR,
            f"program '{name}' has no entry in the collective baseline — "
            f"record it (tools/audit_collectives.py --write-baseline) and "
            f"review the manifest", name, "unbaselined"))
        return findings
    base_counts = baseline.get("collectives", {}) or {}
    base_mesh = baseline.get("mesh") or {}
    if base_mesh and manifest.unsharded:
        findings.append(_diag(
            "PT-COMM-005", Severity.ERROR,
            f"program '{name}' reverted to the unsharded contract but its "
            f"baseline records a mesh census "
            f"({'x'.join(f'{k}{v}' for k, v in sorted(base_mesh.items()))},"
            f" {dict(base_counts)}) — the program silently LOST its "
            f"sharding; restore it or refresh the baseline with a "
            f"justification", name, "lost-sharding"))
    # elastic degrade exemption (docs/RESILIENCE.md "Elastic serving
    # mesh"): a baseline may record `degrade_widths` — the narrower tp
    # widths its PT-SRV-008 reshard path legitimately serves at. A
    # STILL-SHARDED manifest at a recorded degrade width is a planned
    # partial shrink: its per-primitive counts and wire bytes scale with
    # the width, so the count/drift/bytes gates below would misfire.
    # Losing sharding ENTIRELY is never exempt — that already gated as
    # lost-sharding above.
    if not manifest.unsharded:
        degrade_widths = {int(w) for w in
                          (baseline.get("degrade_widths") or ())}
        width = int(manifest.width
                    or (manifest.mesh or {}).get("tp") or 0)
        base_width = int(baseline.get("width")
                         or (base_mesh or {}).get("tp") or 0)
        if (degrade_widths and width and base_width
                and width != base_width and width in degrade_widths):
            return findings
    for prim, want in sorted(base_counts.items()):
        if int(want) and not manifest.collectives.get(prim, 0):
            findings.append(_diag(
                "PT-COMM-005", Severity.ERROR,
                f"'{name}' traces zero '{prim}' collective(s) but its "
                f"recorded contract expects {int(want)} — the collective "
                f"plan silently dropped a primitive; review and refresh "
                f"the baseline", name, f"lost-collective:{prim}",
                prim=prim))
    for prim, have in sorted(manifest.collectives.items()):
        want = base_counts.get(prim)
        if want is None:
            findings.append(_diag(
                "PT-COMM-005", Severity.ERROR,
                f"'{name}' now traces {have} '{prim}' collective(s) — a "
                f"primitive absent from its recorded contract; review and "
                f"refresh the baseline", name, f"new-collective:{prim}",
                prim=prim))
        elif have > int(want):
            findings.append(_diag(
                "PT-COMM-005", Severity.ERROR,
                f"'{prim}' count grew {int(want)} -> {have} vs the "
                f"recorded contract for '{name}' — review the new "
                f"collective(s) or refresh the baseline with a "
                f"justification", name, f"{prim}-drift", prim=prim))
    base_bytes = float(baseline.get("comm_bytes") or 0.0)
    if base_bytes and manifest.comm_bytes > 1.5 * base_bytes:
        findings.append(_diag(
            "PT-COMM-005", Severity.ERROR,
            f"wire bytes grew {base_bytes:.3g} -> {manifest.comm_bytes:.3g}"
            f" (>1.5x) vs the recorded contract for '{name}' — the "
            f"collective plan blew up; review and refresh the baseline",
            name, "comm-bytes-blowup"))
    return findings
