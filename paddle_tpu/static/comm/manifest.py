"""Comm manifest — the per-program record the PT-COMM gate baselines.

``compute_comm_manifest`` folds the collective walk (collectives.py)
into one JSON-able :class:`CommManifest`: a static census of collective
equations per (normalized) primitive, per-mesh-axis dispatch and wire-
byte totals (execution multipliers applied — a collective in a scan
body of length L counts L times), the loop-invariant count, and — once
:func:`mesh_scaling_verdict` has seen the same program family at two
mesh widths — the mesh-scaling law record.

Counts come in two flavors, same convention as PT-COST:

- ``collective_eqns`` / ``collectives`` are STATIC equation counts
  (scan bodies count once) — they measure *program text*, the thing
  that explodes when a python loop over mesh size unrolls.
- ``comm_bytes`` / ``dispatches`` apply the multipliers — they measure
  *wire traffic per program dispatch*.

The mesh-scaling law differs from PT-COST's slot law in one deliberate
way: ring collectives move ``(n-1)``-shaped volumes, which between
small widths grow FASTER than proportionally (2 -> 4 devices triples
``n-1``) while staying asymptotically linear. The law therefore allows
per-step growth up to ``max(n_b/n_a, (n_b-1)/(n_a-1))`` before calling
a family superlinear — an O(n^2) term still fails it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from .collectives import iter_collectives

__all__ = ["CommManifest", "CommPathSpec", "compute_comm_manifest",
           "mesh_scaling_verdict"]

#: per-program detail rows kept in the manifest (census stays bounded)
_MAX_DETAILS = 64


@dataclass
class CommPathSpec:
    """Reviewed registration of one mesh-sharded program
    (tools/audit_collectives.py): the symbolic mesh it is traced under,
    its width for the mesh-scaling law (``name@width`` families), and —
    for the single-device serving programs — the explicit ``unsharded``
    contract the sharding PR (ROADMAP item 1) must flip."""

    name: str
    mesh: Dict[str, int] = field(default_factory=dict)
    width: Optional[int] = None
    unsharded: bool = False
    notes: str = ""


@dataclass
class CommManifest:
    program: str
    mesh: Dict[str, int] = field(default_factory=dict)
    width: Optional[int] = None
    unsharded: bool = False
    collective_eqns: int = 0                  # static, containers recursed
    collectives: Dict[str, int] = field(default_factory=dict)  # per prim
    per_axis: Dict[str, Dict[str, float]] = field(default_factory=dict)
    dispatches: float = 0.0                   # multipliers applied
    comm_bytes: float = 0.0                   # wire bytes, mult applied
    payload_bytes: float = 0.0                # operand bytes, mult applied
    loop_invariant_eqns: int = 0
    details: List[Dict] = field(default_factory=list)
    scaling: Optional[Dict] = None

    def to_dict(self) -> Dict:
        return {
            "program": self.program, "mesh": dict(self.mesh),
            "width": self.width, "unsharded": self.unsharded,
            "collective_eqns": self.collective_eqns,
            "collectives": dict(self.collectives),
            "per_axis": {k: dict(v) for k, v in self.per_axis.items()},
            "dispatches": self.dispatches, "comm_bytes": self.comm_bytes,
            "payload_bytes": self.payload_bytes,
            "loop_invariant_eqns": self.loop_invariant_eqns,
            "details": [dict(d) for d in self.details],
            "scaling": self.scaling,
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "CommManifest":
        m = cls(program=d.get("program", "?"))
        for k, v in d.items():
            if hasattr(m, k):
                setattr(m, k, v)
        return m


def compute_comm_manifest(program_or_jaxpr, name: str = "program",
                          spec: Optional[CommPathSpec] = None
                          ) -> CommManifest:
    """Fold the collective walk into one manifest. Pure tracing
    arithmetic — no XLA compile, no device dispatch. When the argument
    is a traced Program import, the manifest is also attached as
    ``program._comm_manifest``."""
    m = CommManifest(program=name,
                     mesh=dict(spec.mesh) if spec is not None else {},
                     width=spec.width if spec is not None else None,
                     unsharded=spec.unsharded if spec is not None else False)
    for c in iter_collectives(program_or_jaxpr,
                              mesh=spec.mesh if spec is not None else None):
        m.collective_eqns += 1
        m.collectives[c.prim] = m.collectives.get(c.prim, 0) + 1
        m.dispatches += float(c.mult)
        m.comm_bytes += c.total_wire_bytes
        m.payload_bytes += c.payload_bytes * c.mult
        if c.loop_invariant:
            m.loop_invariant_eqns += 1
        for a in c.axes:
            slot = m.per_axis.setdefault(
                a, {"eqns": 0, "dispatches": 0.0, "bytes": 0.0})
            slot["eqns"] += 1
            slot["dispatches"] += float(c.mult)
            slot["bytes"] += c.total_wire_bytes
        for a, s in c.axis_sizes.items():
            m.mesh.setdefault(a, s)
        if len(m.details) < _MAX_DETAILS:
            m.details.append({
                "prim": c.prim, "axes": list(c.axes), "group": c.group_size,
                "scope": c.scope, "mult": c.mult,
                "wire_bytes": c.bytes_wire,
                "loop_invariant": c.loop_invariant})
    if hasattr(program_or_jaxpr, "global_block"):
        program_or_jaxpr._comm_manifest = m
    return m


def world_size(mesh: Dict[str, int]) -> int:
    n = 1
    for v in mesh.values():
        n *= max(int(v), 1)
    return n


def mesh_scaling_verdict(manifests: Sequence[CommManifest],
                         tol: float = 0.25) -> Dict:
    """The mesh-scaling law (PT-COMM-003): the SAME program family traced
    at ascending mesh widths must keep wire bytes and collective count
    within the ring envelope — per step ``a -> b`` the allowed growth is
    ``max(w_b/w_a, (w_b-1)/(w_a-1))`` (module docstring): ring volumes
    are (n-1)-shaped and legal; an O(n^2) term (a python loop over mesh
    size emitting a collective per rank, an all-gather whose payload
    itself grows with n) fails. The verdict is recorded onto every
    participating manifest."""
    ms = sorted(manifests, key=lambda m: (m.width or 0))
    widths = [m.width for m in ms]
    if len(ms) < 2 or any(w is None or w <= 0 for w in widths):
        raise ValueError("mesh scaling law needs >=2 manifests with widths")
    verdict, worst = "<=ring", 0.0
    for a, b in zip(ms, ms[1:]):
        grow = b.width / a.width
        if a.width > 1:
            grow = max(grow, (b.width - 1.0) / (a.width - 1.0))
        for attr in ("comm_bytes", "collective_eqns"):
            va, vb = float(getattr(a, attr)), float(getattr(b, attr))
            if va <= 0:
                if vb > 0:          # comm appears from nothing with width
                    worst = max(worst, float("inf"))
                    verdict = "superlinear"
                continue
            ratio = (vb / va) / grow    # 1.0 == exactly the ring envelope
            worst = max(worst, ratio)
            if ratio > 1.0 + tol:
                verdict = "superlinear"
    rec = {"widths": widths,
           "comm_bytes": [m.comm_bytes for m in ms],
           "collective_eqns": [m.collective_eqns for m in ms],
           "verdict": verdict,
           "worst_ring_ratio": (round(worst, 4)
                                if worst != float("inf") else "inf"),
           "tol": tol}
    for m in ms:
        m.scaling = rec
    return rec
