"""paddle_tpu.static.comm — static collective-communication auditor
(PT-COMM).

PT-COST made device-program cost a lint-time property; this package does
the same for COLLECTIVE COMMUNICATION, the axis ROADMAP item 1 (mesh-
sharded serving) lives or dies on. Every registered mesh-sharded program
(tools/audit_collectives.py: the per-MULTICHIP-shape train-step contract
programs, the ring-attention and MoE dispatch/combine spmd-rule
programs, and the single-device serving programs under an explicit
``unsharded`` contract) is imported by pure tracing — shard_map under a
symbolic ``jax.sharding.AbstractMesh``, NO XLA compile, no devices —
and folded into a :class:`CommManifest`: a census of every collective
primitive with axis attribution and ring-algorithm per-dispatch wire
bytes computed from mesh axis sizes and operand dtypes, multiplied
through scan bodies, plus the mesh-scaling law across a width pair. The
manifest is baselined in tools/collective_baseline.json and enforced in
CI, so an accidental replication, a collective re-gathered every scan
step, an O(mesh^2) term in the collective plan, an all_gather where a
reduce_scatter contract halves the bytes, or silent contract drift
fails LINT — before any multi-chip run.

Codes (docs/STATIC_ANALYSIS.md): PT-COMM-001 accidental replication,
PT-COMM-002 loop-invariant collective in a scan/while body, PT-COMM-003
superlinear comm scaling with mesh size, PT-COMM-004 all_gather+reduce
where reduce_scatter halves bytes, PT-COMM-005 contract drift /
unbaselined / broken unsharded contract.
"""

from __future__ import annotations

from typing import List, Optional

from ..analysis.diagnostics import AnalysisPass, Diagnostic
from .checks import (check_comm_contract, check_gather_reduce,
                     check_loop_invariant_collectives, check_mesh_scaling,
                     check_replication)
from .collectives import (COLLECTIVE_PRIMS, CollectiveInfo, iter_collectives,
                          wire_bytes)
from .manifest import (CommManifest, CommPathSpec, compute_comm_manifest,
                       mesh_scaling_verdict)
from .mesh import abstract_mesh, mesh_axis_sizes, mesh_spec

__all__ = [
    "COLLECTIVE_PRIMS", "CollectiveInfo", "CollectiveCommPass",
    "CommManifest", "CommPathSpec", "abstract_mesh", "check_comm_contract",
    "check_gather_reduce", "check_loop_invariant_collectives",
    "check_mesh_scaling", "check_replication", "compute_comm_manifest",
    "iter_collectives", "mesh_axis_sizes", "mesh_spec",
    "mesh_scaling_verdict", "wire_bytes",
]


class CollectiveCommPass(AnalysisPass):
    """AnalysisPass form of the auditor — composes with ``run_analysis``
    / the ordinary PassManager beside the PR 1 analyzers. Computes the
    comm manifest (attached as ``program._comm_manifest``) and reports
    the program-local code classes: PT-COMM-001 (replication),
    PT-COMM-002 (loop-invariant collective), PT-COMM-004
    (gather+reduce). The cross-program classes (PT-COMM-003 mesh
    scaling, PT-COMM-005 contract drift) need a width pair / the
    baseline and live in tools/audit_collectives.py."""

    name = "comm"

    def __init__(self, spec: Optional[CommPathSpec] = None, suppress=()):
        super().__init__(suppress=suppress)
        self.spec = spec
        self.manifest: Optional[CommManifest] = None

    def analyze(self, program) -> List[Diagnostic]:
        name = self.spec.name if self.spec is not None else "program"
        self.manifest = compute_comm_manifest(program, name=name,
                                              spec=self.spec)
        findings = list(check_replication(program, name))
        findings += check_loop_invariant_collectives(program, name)
        findings += check_gather_reduce(program, name)
        return findings
