"""Graph-health reporter (tentpole analyzer #4) — exposed as
``Program.diagnose()``.

Reports the structural smells the transform passes would act on, without
mutating: dead ops (what DCE would remove), duplicate subgraphs (what CSE
would merge), and unused parameters (weights the program captures — or was
handed — but never reads).

Codes: PT-GRAPH-001 (dead op, warning), PT-GRAPH-002 (duplicate subgraph,
warning), PT-GRAPH-003 (unused parameter, error).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ...core.static_graph import Program, Variable
from ..passes import cse_key, live_ops
from .diagnostics import AnalysisPass, Diagnostic, Severity

__all__ = ["GraphHealthReporter"]

_MAX_PER_CODE = 25  # cap repeated findings so huge graphs stay readable


class GraphHealthReporter(AnalysisPass):
    """``targets`` define liveness roots (defaults to the program's recorded
    outputs / loss; with neither, terminal ops are the roots and nothing is
    dead). ``parameters`` optionally hands in the model's full parameter list
    so weights that never even reach the program are flagged too."""

    name = "graph_health_reporter"

    def __init__(self, targets: Optional[Sequence[Variable]] = None,
                 parameters: Optional[Sequence] = None, suppress=()):
        super().__init__(suppress)
        self.targets = targets
        self.parameters = parameters

    def _roots(self, program: Program):
        targets = list(self.targets or [])
        if not targets:
            targets = list(getattr(program, "_outputs", []) or [])
        if program._loss is not None:
            targets.append(program._loss)
        return targets

    def analyze(self, program: Program) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        ops = program.global_block().ops
        aliases = getattr(program, "_aliases", {})
        roots = self._roots(program)

        # -- dead ops (what DCE would remove) -------------------------------
        live = set(map(id, ops))
        if roots:
            live = set(map(id, live_ops(ops, [id(v) for v in roots],
                                        aliases)))
            n_dead = 0
            for op in ops:
                if id(op) in live:
                    continue
                n_dead += 1
                if n_dead <= _MAX_PER_CODE:
                    out.append(self.diag(
                        "PT-GRAPH-001", Severity.WARNING,
                        f"op is dead — no path from its outputs "
                        f"({', '.join(v.name for v in op.outputs[:3])}) to "
                        f"the fetch targets; DCE would remove it", op=op))
            if n_dead > _MAX_PER_CODE:
                out.append(Diagnostic(
                    "PT-GRAPH-001", Severity.WARNING,
                    f"... and {n_dead - _MAX_PER_CODE} more dead ops",
                    analyzer=self.name))

        # -- duplicate subgraphs (what CSE would merge) ---------------------
        seen = {}
        n_dup = 0
        for op in ops:
            key = cse_key(op, aliases)
            if key is None:
                continue
            prev = seen.get(key)
            if prev is not None and len(prev.outputs) == len(op.outputs):
                n_dup += 1
                if n_dup <= _MAX_PER_CODE:
                    out.append(self.diag(
                        "PT-GRAPH-002", Severity.WARNING,
                        f"duplicate of op#{prev.idx} '{prev.type}' — same "
                        f"fn/inputs/kwargs; CSE would merge them", op=op))
            else:
                seen[key] = op
        if n_dup > _MAX_PER_CODE:
            out.append(Diagnostic(
                "PT-GRAPH-002", Severity.WARNING,
                f"... and {n_dup - _MAX_PER_CODE} more duplicate ops",
                analyzer=self.name))

        # -- unused parameters ---------------------------------------------
        out.extend(self._unused_params(program, ops, live))
        return out

    def _unused_params(self, program, ops, live) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        # captured parameters whose every capturing op is dead
        cap_live = {}
        for op in ops:
            for t in op.captured:
                if getattr(t, "is_parameter", False):
                    cap_live[id(t)] = cap_live.get(id(t), False) or (
                        id(op) in live)
        by_id = {id(t): t for op in ops for t in op.captured}
        for tid, is_live in cap_live.items():
            if not is_live:
                t = by_id[tid]
                out.append(Diagnostic(
                    "PT-GRAPH-003", Severity.ERROR,
                    f"parameter '{getattr(t, 'name', '?')}' "
                    f"{list(t._data.shape)} is captured only by dead ops — "
                    f"it never influences the program's outputs",
                    analyzer=self.name))

        # parameter-valued feed Variables (traced imports) consumed by no op
        consumed = {id(v) for op in ops for v in op.inputs}
        for v in program.list_vars():
            if getattr(v, "is_parameter", False) and id(v) not in consumed:
                out.append(Diagnostic(
                    "PT-GRAPH-003", Severity.ERROR,
                    f"parameter '{v.name}' {list(v._data.shape)} is an "
                    f"input of the program but no op consumes it",
                    analyzer=self.name))

        # externally-supplied parameter list: anything that never reached the
        # program at all
        if self.parameters:
            reached = set()
            for op in ops:
                for t in op.captured:
                    reached.add(id(t))
                    reached.add(id(t._data))
            for v in program.list_vars():
                pt = getattr(v, "_param", None)  # traced-import param link
                if pt is not None:
                    reached.add(id(pt))
                    reached.add(id(getattr(pt, "_data", pt)))
            for p in self.parameters:
                arr = getattr(p, "_data", p)
                if id(arr) not in reached and id(p) not in reached:
                    out.append(Diagnostic(
                        "PT-GRAPH-003", Severity.ERROR,
                        f"parameter '{getattr(p, 'name', '?')}' "
                        f"{list(arr.shape)} does not appear in the recorded "
                        f"program at all — the traced forward never reads it",
                        analyzer=self.name))
        return out
