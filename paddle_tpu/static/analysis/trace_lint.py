"""Trace-hazard linter (tentpole analyzer #2).

Catches the defect classes that surface as silent recompiles or frozen
randomness instead of errors:

- PT-TRACE-001 (error): an Executor accumulating many compiled plans for ONE
  program with per-step-varying feed signatures — each step pays a fresh XLA
  compile (reference: the _ExecutorCache growing unboundedly,
  python/paddle/base/executor.py:847).
- PT-TRACE-002 (error): a ``to_static`` function recompiled per call because a
  Python scalar kwarg is captured by value into the cache key — pass a tensor
  instead (reference: jit/sot guard churn).
- PT-TRACE-003 (error): a stochastic op (STOCHASTIC_KEYWORDS) recorded without
  an explicit seed — results are not reproducible run-to-run.
- PT-TRACE-004 (warning): ``.numpy()`` / ``.item()`` in the source of a traced
  callable — a host sync that breaks (or silently graph-breaks) tracing.
- PT-SCOPE-001 (warning): a Scope read of a never-written variable that
  silently materialized a ()-shaped float32 zero.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from collections import defaultdict
from typing import List, Optional

from ...core.static_graph import STOCHASTIC_KEYWORDS, Program
from .diagnostics import AnalysisPass, Diagnostic, Severity

__all__ = ["TraceHazardLinter", "lint_executor", "lint_static_function",
           "lint_scope"]

# distinct compiled variants of one program/function before we call it churn
RECOMPILE_THRESHOLD = 3


def _is_stochastic_type(op_type) -> bool:
    return any(k in (op_type or "") for k in STOCHASTIC_KEYWORDS)


class TraceHazardLinter(AnalysisPass):
    """Program-level hazards; optionally also lints live Executor /
    StaticFunction caches handed in as context."""

    name = "trace_hazard_linter"

    def __init__(self, suppress=(), executors=(), static_fns=(), scopes=(),
                 assume_seeded: Optional[bool] = None):
        super().__init__(suppress)
        self.executors = list(executors)
        self.static_fns = list(static_fns)
        self.scopes = list(scopes)
        self.assume_seeded = assume_seeded

    def _op_unseeded(self, program: Program, op) -> bool:
        """Was this stochastic op recorded without a seed? Prefers the
        record-time stamp (record_op) — a later unrelated paddle.seed() must
        not launder an unreproducible recording — and falls back to current
        process state for hand-built ops that carry no stamp."""
        if self.assume_seeded is not None:
            return not self.assume_seeded
        stamp = getattr(program, "_seed_stamps", {}).get(id(op))
        if stamp is not None:
            # the record-time stamp wins: setting program.random_seed (or
            # paddle.seed) AFTER recording must not launder the recording
            return stamp
        if getattr(program, "random_seed", 0):
            return False
        from ...framework import random as frandom

        return not frandom.explicitly_seeded()

    def analyze(self, program: Program) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for op in program.global_block().ops:
            if getattr(op.fn, "_jaxpr_import", False):
                # jaxpr-imported op: any PRNG key is a baked constant of the
                # trace — replays are bit-identical regardless of paddle.seed
                continue
            if _is_stochastic_type(op.type) and self._op_unseeded(program, op):
                out.append(self.diag(
                    "PT-TRACE-003", Severity.ERROR,
                    f"stochastic op recorded without an explicit seed — "
                    f"call paddle.seed(...) (or set program.random_seed) "
                    f"before recording '{op.type}' for reproducible replays",
                    op=op))
        for exe in self.executors:
            out.extend(lint_executor(exe, analyzer=self.name))
        for sf in self.static_fns:
            out.extend(lint_static_function(sf, analyzer=self.name))
        for sc in self.scopes:
            out.extend(lint_scope(sc, analyzer=self.name))
        return out


def lint_executor(executor, threshold: int = RECOMPILE_THRESHOLD,
                  analyzer: str = "trace_hazard_linter") -> List[Diagnostic]:
    """Flag programs whose compiled-plan cache shows per-step feed churn."""
    sigs_by_prog = defaultdict(set)
    for key in executor.cache_signatures():
        prog_id, _version, sig = key[0], key[1], key[2]
        sigs_by_prog[prog_id].add(sig)
    out: List[Diagnostic] = []
    for prog_id, sigs in sigs_by_prog.items():
        if len(sigs) >= threshold:
            shapes = sorted(str([(n, list(s)) for n, s, _ in sig])
                            for sig in sigs)[:4]
            out.append(Diagnostic(
                "PT-TRACE-001", Severity.ERROR,
                f"program {prog_id} compiled {len(sigs)} variants for "
                f"distinct feed signatures — the feed shape/dtype varies per "
                f"step and forces an XLA recompile each time (pad or bucket "
                f"the batch); e.g. {shapes}",
                analyzer=analyzer))
    return out


def lint_static_function(sf, threshold: int = RECOMPILE_THRESHOLD,
                         analyzer: str = "trace_hazard_linter"
                         ) -> List[Diagnostic]:
    """Flag to_static callables recompiled per call + host syncs in source."""
    out: List[Diagnostic] = []
    name = getattr(sf, "__name__", None) or getattr(
        getattr(sf, "_orig_fn", None), "__name__", "<fn>")

    keys = list(sf.cache_keys()) if hasattr(sf, "cache_keys") else []
    # keys are (n_state, sorted static_kwargs): variants differing only in
    # kwarg VALUES mean a Python scalar is baked into the executable
    by_kwnames = defaultdict(set)
    for _n_state, kw in keys:
        by_kwnames[tuple(k for k, _ in kw)].add(kw)
    for kwnames, variants in by_kwnames.items():
        if len(variants) >= threshold:
            out.append(Diagnostic(
                "PT-TRACE-002", Severity.ERROR,
                f"to_static '{name}' compiled {len(variants)} variants "
                f"driven by Python-scalar kwarg(s) {list(kwnames)} captured "
                f"by value — pass a tensor (traced) argument instead",
                analyzer=analyzer))

    # host-sync scan over the traced source
    fn = getattr(sf, "_orig_fn", sf)
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
        base = max(inspect.getsourcelines(fn)[1], 1)
        srcfile = inspect.getsourcefile(fn) or "<source>"
    except (OSError, TypeError, SyntaxError):
        return out
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("numpy", "item")):
            out.append(Diagnostic(
                "PT-TRACE-004", Severity.WARNING,
                f"'.{node.func.attr}()' inside traced '{name}' is a host "
                f"sync — it breaks tracing (or forces an eager graph break)",
                source=f"{srcfile}:{base + node.lineno - 1}",
                analyzer=analyzer))
    return out


def lint_scope(scope, analyzer: str = "trace_hazard_linter"
               ) -> List[Diagnostic]:
    """Warn for every scope variable read before (and never) written — the
    lenient ``Scope.var`` materialized a ()-shaped float32 zero for it."""
    out: List[Diagnostic] = []
    for name, n in sorted(getattr(scope, "_lazy_reads", {}).items()):
        if name in getattr(scope, "_written", ()):
            continue
        out.append(Diagnostic(
            "PT-SCOPE-001", Severity.WARNING,
            f"scope variable '{name}' read {n}x but never written — the "
            f"lenient lookup materialized a ()-float32 zero; use "
            f"scope.var(name, strict=True) to fail fast",
            analyzer=analyzer))
    return out
