"""Trace-hazard linter (tentpole analyzer #2).

Catches the defect classes that surface as silent recompiles or frozen
randomness instead of errors:

- PT-TRACE-001 (error): an Executor accumulating many compiled plans for ONE
  program with per-step-varying feed signatures — each step pays a fresh XLA
  compile (reference: the _ExecutorCache growing unboundedly,
  python/paddle/base/executor.py:847).
- PT-TRACE-002 (error): a ``to_static`` function recompiled per call because a
  Python scalar kwarg is captured by value into the cache key — pass a tensor
  instead (reference: jit/sot guard churn).
- PT-TRACE-003 (error): a stochastic op (STOCHASTIC_KEYWORDS) recorded without
  an explicit seed — results are not reproducible run-to-run.
- PT-TRACE-004 (warning): ``.numpy()`` / ``.item()`` in the source of a traced
  callable — a host sync that breaks (or silently graph-breaks) tracing.
- PT-TRACE-005 (error): ``jnp.asarray(buf)`` on a host buffer that is
  mutated later in the same scope — jax BORROWS the numpy buffer for an
  async transfer, so the device can observe the post-mutation bytes
  (the serving-engine bug class: ~1/30 runs decoded against post-mutation
  block tables until ``.copy()`` snapshots were uploaded instead).
- PT-SCOPE-001 (warning): a Scope read of a never-written variable that
  silently materialized a ()-shaped float32 zero.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from collections import defaultdict
from typing import List, Optional

from ...core.static_graph import STOCHASTIC_KEYWORDS, Program
from .diagnostics import AnalysisPass, Diagnostic, Severity

__all__ = ["TraceHazardLinter", "lint_executor", "lint_static_function",
           "lint_scope", "lint_host_borrow"]

# distinct compiled variants of one program/function before we call it churn
RECOMPILE_THRESHOLD = 3


def _is_stochastic_type(op_type) -> bool:
    return any(k in (op_type or "") for k in STOCHASTIC_KEYWORDS)


class TraceHazardLinter(AnalysisPass):
    """Program-level hazards; optionally also lints live Executor /
    StaticFunction caches handed in as context."""

    name = "trace_hazard_linter"

    def __init__(self, suppress=(), executors=(), static_fns=(), scopes=(),
                 borrow_fns=(), assume_seeded: Optional[bool] = None):
        super().__init__(suppress)
        self.executors = list(executors)
        self.static_fns = list(static_fns)
        self.scopes = list(scopes)
        self.borrow_fns = list(borrow_fns)
        self.assume_seeded = assume_seeded

    def _op_unseeded(self, program: Program, op) -> bool:
        """Was this stochastic op recorded without a seed? Prefers the
        record-time stamp (record_op) — a later unrelated paddle.seed() must
        not launder an unreproducible recording — and falls back to current
        process state for hand-built ops that carry no stamp."""
        if self.assume_seeded is not None:
            return not self.assume_seeded
        stamp = getattr(program, "_seed_stamps", {}).get(id(op))
        if stamp is not None:
            # the record-time stamp wins: setting program.random_seed (or
            # paddle.seed) AFTER recording must not launder the recording
            return stamp
        if getattr(program, "random_seed", 0):
            return False
        from ...framework import random as frandom

        return not frandom.explicitly_seeded()

    def analyze(self, program: Program) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for op in program.global_block().ops:
            if getattr(op.fn, "_jaxpr_import", False):
                # jaxpr-imported op: any PRNG key is a baked constant of the
                # trace — replays are bit-identical regardless of paddle.seed
                continue
            if _is_stochastic_type(op.type) and self._op_unseeded(program, op):
                out.append(self.diag(
                    "PT-TRACE-003", Severity.ERROR,
                    f"stochastic op recorded without an explicit seed — "
                    f"call paddle.seed(...) (or set program.random_seed) "
                    f"before recording '{op.type}' for reproducible replays",
                    op=op))
        for exe in self.executors:
            out.extend(lint_executor(exe, analyzer=self.name))
        for sf in self.static_fns:
            out.extend(lint_static_function(sf, analyzer=self.name))
        for sc in self.scopes:
            out.extend(lint_scope(sc, analyzer=self.name))
        for fn in self.borrow_fns:
            out.extend(lint_host_borrow(fn, analyzer=self.name))
        return out


def lint_executor(executor, threshold: int = RECOMPILE_THRESHOLD,
                  analyzer: str = "trace_hazard_linter") -> List[Diagnostic]:
    """Flag programs whose compiled-plan cache shows per-step feed churn."""
    sigs_by_prog = defaultdict(set)
    for key in executor.cache_signatures():
        prog_id, _version, sig = key[0], key[1], key[2]
        sigs_by_prog[prog_id].add(sig)
    out: List[Diagnostic] = []
    for prog_id, sigs in sigs_by_prog.items():
        if len(sigs) >= threshold:
            shapes = sorted(str([(n, list(s)) for n, s, _ in sig])
                            for sig in sigs)[:4]
            out.append(Diagnostic(
                "PT-TRACE-001", Severity.ERROR,
                f"program {prog_id} compiled {len(sigs)} variants for "
                f"distinct feed signatures — the feed shape/dtype varies per "
                f"step and forces an XLA recompile each time (pad or bucket "
                f"the batch); e.g. {shapes}",
                analyzer=analyzer))
    return out


def lint_static_function(sf, threshold: int = RECOMPILE_THRESHOLD,
                         analyzer: str = "trace_hazard_linter"
                         ) -> List[Diagnostic]:
    """Flag to_static callables recompiled per call + host syncs in source."""
    out: List[Diagnostic] = []
    name = getattr(sf, "__name__", None) or getattr(
        getattr(sf, "_orig_fn", None), "__name__", "<fn>")

    keys = list(sf.cache_keys()) if hasattr(sf, "cache_keys") else []
    # keys are (n_state, sorted static_kwargs): variants differing only in
    # kwarg VALUES mean a Python scalar is baked into the executable
    by_kwnames = defaultdict(set)
    for _n_state, kw in keys:
        by_kwnames[tuple(k for k, _ in kw)].add(kw)
    for kwnames, variants in by_kwnames.items():
        if len(variants) >= threshold:
            out.append(Diagnostic(
                "PT-TRACE-002", Severity.ERROR,
                f"to_static '{name}' compiled {len(variants)} variants "
                f"driven by Python-scalar kwarg(s) {list(kwnames)} captured "
                f"by value — pass a tensor (traced) argument instead",
                analyzer=analyzer))

    # host-sync scan over the traced source
    fn = getattr(sf, "_orig_fn", sf)
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
        base = max(inspect.getsourcelines(fn)[1], 1)
        srcfile = inspect.getsourcefile(fn) or "<source>"
    except (OSError, TypeError, SyntaxError):
        return out
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("numpy", "item")):
            out.append(Diagnostic(
                "PT-TRACE-004", Severity.WARNING,
                f"'.{node.func.attr}()' inside traced '{name}' is a host "
                f"sync — it breaks tracing (or forces an eager graph break)",
                source=f"{srcfile}:{base + node.lineno - 1}",
                analyzer=analyzer))
    return out


_ASARRAY_MODS = ("jnp", "jax")       # jnp.asarray / jax.numpy.asarray


def _buffer_expr(node):
    """Dotted-name string for a Name/Attribute chain, else None (calls,
    subscripts etc. are not trackable buffers)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _is_jnp_asarray(call: ast.Call) -> bool:
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "asarray"):
        return False
    base = f.value
    if isinstance(base, ast.Name):
        return base.id in _ASARRAY_MODS
    # jax.numpy.asarray
    return (isinstance(base, ast.Attribute) and base.attr == "numpy"
            and isinstance(base.value, ast.Name)
            and base.value.id in _ASARRAY_MODS)


# numpy methods that mutate the receiver in place — a post-upload call on
# the uploaded buffer is the same hazard as a subscript store
_MUTATORS = ("fill", "sort", "resize", "put", "partition", "setfield")


def lint_host_borrow(fn, analyzer: str = "trace_hazard_linter"
                     ) -> List[Diagnostic]:
    """PT-TRACE-005: flag ``jnp.asarray(buf)`` on a host buffer mutated
    later in the same scope.

    ``jnp.asarray`` on a numpy array BORROWS the buffer for an async
    host->device transfer; a later in-place mutation (``buf[i] = ...``,
    ``buf += ...``, ``buf.fill(...)``) can land before the transfer drains,
    and the device silently reads the post-mutation bytes. Upload
    ``buf.copy()`` instead. "Later" means a mutation on a line after the
    upload, or anywhere inside a loop that also contains the upload (the
    next iteration's mutation races the previous iteration's transfer —
    exactly how the serving engine hit it). ``fn`` may be a callable or a
    source string."""
    out: List[Diagnostic] = []
    if isinstance(fn, str):
        src, base, srcfile, name = fn, 1, "<source>", "<source>"
    else:
        try:
            src = textwrap.dedent(inspect.getsource(fn))
            base = max(inspect.getsourcelines(fn)[1], 1)
            srcfile = inspect.getsourcefile(fn) or "<source>"
        except (OSError, TypeError):
            return out
        name = getattr(fn, "__name__", "<fn>")
    try:
        tree = ast.parse(textwrap.dedent(src))
    except SyntaxError:
        return out

    # uploads: buffer expr -> [(lineno, loop-ids containing the call)]
    loops: List[ast.AST] = []

    def loop_stack(target):
        """ids of the loop nodes whose body contains ``target``."""
        hits = []
        for ln in loops:
            for sub in ast.walk(ln):
                if sub is target:
                    hits.append(id(ln))
                    break
        return hits

    loops = [n for n in ast.walk(tree) if isinstance(n, (ast.For, ast.While))]
    uploads = []                      # (expr, lineno, set(loop ids))
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jnp_asarray(node) and node.args:
            expr = _buffer_expr(node.args[0])
            if expr is not None:
                uploads.append((expr, node.lineno, set(loop_stack(node))))
    if not uploads:
        return out
    mutations = []                    # (expr, lineno, set(loop ids))
    for node in ast.walk(tree):
        tgt = None
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            tgts = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in tgts:
                if isinstance(t, ast.Subscript):
                    tgt = _buffer_expr(t.value)
                elif isinstance(node, ast.AugAssign):
                    # ``buf += 1`` is an IN-PLACE ndarray op (same buffer);
                    # a plain ``buf = ...`` rebinds and is not a mutation
                    tgt = _buffer_expr(t)
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS):
            tgt = _buffer_expr(node.func.value)
        if tgt is not None:
            mutations.append((tgt, node.lineno, set(loop_stack(node))))
    for expr, up_line, up_loops in uploads:
        for mexpr, m_line, m_loops in mutations:
            if mexpr != expr:
                continue
            if m_line > up_line or (up_loops & m_loops):
                out.append(Diagnostic(
                    "PT-TRACE-005", Severity.ERROR,
                    f"'{name}': jnp.asarray({expr}) borrows the host buffer "
                    f"for an async transfer, but {expr} is mutated at line "
                    f"{base + m_line - 1} — the device can read the "
                    f"post-mutation bytes; upload {expr}.copy() instead",
                    source=f"{srcfile}:{base + up_line - 1}",
                    analyzer=analyzer))
                break
    return out


def lint_scope(scope, analyzer: str = "trace_hazard_linter"
               ) -> List[Diagnostic]:
    """Warn for every scope variable read before (and never) written — the
    lenient ``Scope.var`` materialized a ()-shaped float32 zero for it."""
    out: List[Diagnostic] = []
    for name, n in sorted(getattr(scope, "_lazy_reads", {}).items()):
        if name in getattr(scope, "_written", ()):
            continue
        out.append(Diagnostic(
            "PT-SCOPE-001", Severity.WARNING,
            f"scope variable '{name}' read {n}x but never written — the "
            f"lenient lookup materialized a ()-float32 zero; use "
            f"scope.var(name, strict=True) to fail fast",
            analyzer=analyzer))
    return out
