"""Diagnostic framework for program analysis.

Parity anchors: the reference's PIR verifiers and analysis passes
(pir/include/pass/pass_manager.h:35 — pass_manager composes verification
between transforms; pir/include/core/verify.h) which reject malformed
programs before execution. Here the same idea runs over the recorded
``Program`` IR: analyzers walk the op list and *report* findings instead of
mutating, so a bad graph is named at record time — with the offending op and
source line — instead of surfacing as an opaque XLA error inside
``Executor.run``.

Every finding carries a stable diagnostic code (``PT-<AREA>-<NNN>``, see
docs/STATIC_ANALYSIS.md) so CI gates can suppress or ratchet per-code.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Sequence

from ..passes import Pass

__all__ = ["Severity", "Diagnostic", "AnalysisReport", "AnalysisPass"]


class Severity(enum.IntEnum):
    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self):
        return self.name.lower()


@dataclass
class Diagnostic:
    """One analyzer finding, anchored to an op when possible."""

    code: str                       # e.g. "PT-SHAPE-001"
    severity: Severity
    message: str
    op_type: Optional[str] = None   # offending op's type
    op_idx: Optional[int] = None    # its index in the block
    source: Optional[str] = None    # "file:line" provenance
    analyzer: Optional[str] = None  # producing pass name

    def format(self) -> str:
        loc = ""
        if self.op_idx is not None or self.op_type:
            loc = f" op#{self.op_idx if self.op_idx is not None else '?'}" \
                  f" {self.op_type or ''}".rstrip()
        src = f" @{self.source}" if self.source else ""
        return f"{self.code} [{self.severity}]{loc}{src}: {self.message}"

    __str__ = format


def _from_op(code, severity, message, op=None, analyzer=None):
    """Diagnostic constructor taking provenance straight off an Operation."""
    return Diagnostic(
        code=code, severity=Severity(severity), message=message,
        op_type=getattr(op, "type", None),
        op_idx=getattr(op, "idx", None),
        source=getattr(op, "src", None),
        analyzer=analyzer,
    )


class AnalysisReport:
    """Ordered collection of findings with severity queries."""

    def __init__(self, findings: Optional[Iterable[Diagnostic]] = None):
        self.findings: List[Diagnostic] = list(findings or [])

    def extend(self, more: Iterable[Diagnostic]) -> "AnalysisReport":
        self.findings.extend(more)
        return self

    def at_least(self, severity: Severity) -> List[Diagnostic]:
        return [d for d in self.findings if d.severity >= severity]

    def errors(self) -> List[Diagnostic]:
        return self.at_least(Severity.ERROR)

    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.findings if d.severity == Severity.WARNING]

    def by_code(self, code: str) -> List[Diagnostic]:
        return [d for d in self.findings if d.code == code]

    def codes(self) -> List[str]:
        return sorted({d.code for d in self.findings})

    @property
    def ok(self) -> bool:
        """No error-severity findings."""
        return not self.errors()

    def __len__(self):
        return len(self.findings)

    def __iter__(self):
        return iter(self.findings)

    def __bool__(self):
        # truthiness == "has findings", so `if report:` reads naturally
        return bool(self.findings)

    def summary(self) -> str:
        n_e, n_w = len(self.errors()), len(self.warnings())
        head = f"{len(self.findings)} finding(s): {n_e} error, {n_w} warning"
        return "\n".join([head] + ["  " + d.format() for d in self.findings])

    __str__ = summary


class AnalysisPass(Pass):
    """A Pass that reports findings instead of mutating — composes with the
    existing PassManager (its run() stat is the finding count; the program
    version is NOT bumped, so compiled Executor plans stay valid).

    Subclasses implement ``analyze(program) -> list[Diagnostic]``. ``suppress``
    drops findings by exact code (docs/STATIC_ANALYSIS.md documents each)."""

    name = "analysis"
    mutates = False

    def __init__(self, suppress: Sequence[str] = ()):
        self.suppress = frozenset(suppress)
        self.report: AnalysisReport = AnalysisReport()

    def analyze(self, program) -> List[Diagnostic]:
        raise NotImplementedError

    def diag(self, code, severity, message, op=None) -> Diagnostic:
        return _from_op(code, severity, message, op=op, analyzer=self.name)

    def apply(self, program) -> int:
        findings = [d for d in self.analyze(program)
                    if d.code not in self.suppress]
        self.report = AnalysisReport(findings)
        # latest report per pass name lives on the program (inspectable after
        # PassManager-driven runs; keyed so repeated diagnose() calls on a
        # long-lived program replace instead of accumulate)
        reports = getattr(program, "_analysis_reports", None)
        if reports is None:
            reports = program._analysis_reports = {}
        reports[self.name] = self.report
        return len(findings)
