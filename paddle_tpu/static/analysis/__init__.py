"""paddle_tpu.static.analysis — diagnostics over the recorded Program IR.

The reference's L1 layer ships verifiers and analysis passes alongside its
transforms (pir/include/pass/pass_manager.h:35); this package is the
TPU-native analogue: a non-mutating ``AnalysisPass`` kind that composes with
the existing ``PassManager`` and reports findings (``Diagnostic`` with stable
PT-* codes, severity, op + source-line provenance) instead of rewriting the
graph. See docs/STATIC_ANALYSIS.md for the code catalogue.

Four analyzers ship:
- ShapeDtypeVerifier    — forward shape/dtype re-inference vs the recorded
                          graph; fp64 leaks; promotion surprises
- TraceHazardLinter     — recompile hazards (feed-signature churn, Python
                          scalars captured by value), unseeded stochastic
                          ops, host syncs in traced source, lenient-scope
                          reads
- SpmdConsistencyChecker — placements vs mesh (invalid axis, uneven shards,
                          conflicting shardings) before pjit lowering
- GraphHealthReporter   — dead ops, duplicate subgraphs, unused parameters
                          (``Program.diagnose()``)

``trace_to_program`` / ``layer_to_program`` import any traceable callable —
including every in-repo model family — into the Program IR so the analyzers
(and tools/lint_graph.py) can run over real models.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ...core.static_graph import Program
from ..passes import PassManager
from .diagnostics import AnalysisPass, AnalysisReport, Diagnostic, Severity
from .graph_health import GraphHealthReporter
from .shape_check import ShapeDtypeVerifier
from .spmd_check import SpmdConsistencyChecker, check_axis_names, check_placements
from .trace_import import layer_to_program, trace_to_program
from .trace_lint import (TraceHazardLinter, lint_executor, lint_host_borrow,
                         lint_scope, lint_static_function)

__all__ = [
    "Severity", "Diagnostic", "AnalysisReport", "AnalysisPass",
    "ShapeDtypeVerifier", "TraceHazardLinter", "SpmdConsistencyChecker",
    "GraphHealthReporter", "run_analysis", "default_analysis_passes",
    "trace_to_program", "layer_to_program",
    "lint_executor", "lint_static_function", "lint_scope",
    "lint_host_borrow", "check_placements", "check_axis_names",
]


def default_analysis_passes(targets=None, parameters=None, suppress=(),
                            executors=(), static_fns=(), scopes=(),
                            borrow_fns=(), assume_seeded=None):
    return [
        ShapeDtypeVerifier(suppress=suppress),
        TraceHazardLinter(suppress=suppress, executors=executors,
                          static_fns=static_fns, scopes=scopes,
                          borrow_fns=borrow_fns,
                          assume_seeded=assume_seeded),
        SpmdConsistencyChecker(suppress=suppress),
        GraphHealthReporter(targets=targets, parameters=parameters,
                            suppress=suppress),
    ]


def run_analysis(program: Program, passes: Optional[Sequence[AnalysisPass]] = None,
                 targets=None, parameters=None, suppress=(),
                 executors=(), static_fns=(), scopes=(),
                 borrow_fns=(), assume_seeded=None) -> AnalysisReport:
    """Run the analyzer suite over a Program; return the combined report.
    Composes through the ordinary PassManager — analysis passes are regular
    passes that happen not to mutate."""
    passes = list(passes if passes is not None else default_analysis_passes(
        targets=targets, parameters=parameters, suppress=suppress,
        executors=executors, static_fns=static_fns, scopes=scopes,
        borrow_fns=borrow_fns, assume_seeded=assume_seeded))
    PassManager(passes).run(program)
    report = AnalysisReport()
    for p in passes:
        report.extend(p.report)
    return report
