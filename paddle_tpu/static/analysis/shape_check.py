"""Shape/dtype inference verifier (tentpole analyzer #1).

Walks ``Program.ops`` forward, re-runs shape/dtype inference per op (the same
``jax.eval_shape``-over-the-op-fn contract record_op used — one source of
truth, cf. the reference's InferMeta/phi infermeta verifiers) and flags
disagreements with what the graph actually records, fp64 leaks that a TPU
backend cannot execute natively, and int→float promotion surprises.

Codes: PT-SHAPE-001 (shape/rank mismatch, error), PT-SHAPE-002 (dtype
mismatch, error), PT-SHAPE-003 (op no longer type-checks, error),
PT-DTYPE-001 (fp64/complex128 leak, error), PT-DTYPE-002 (implicit int→float
promotion, warning).
"""

from __future__ import annotations

from typing import List

import jax
import numpy as np

from ...core.static_graph import Program, Variable
from ...core.tensor import Tensor
from .diagnostics import AnalysisPass, Diagnostic, Severity

__all__ = ["ShapeDtypeVerifier"]

# op types where an int input legitimately produces a float output
_PROMOTION_OK = ("cast", "astype", "convert_element_type", "div", "mean",
                 "average", "softmax", "normalize", "linspace", "to_tensor",
                 "exp", "log", "sqrt", "rsqrt", "sin", "cos", "erf", "pow",
                 "sigmoid", "tanh", "random", "uniform", "normal", "dropout")


def _is_extended(dt) -> bool:
    """jax extended dtype (PRNG key avals) — numpy can't represent these;
    skip numeric checks on them."""
    try:
        return jax.dtypes.issubdtype(dt, jax.dtypes.extended)
    except Exception:  # pragma: no cover - defensive vs jax version drift
        return False


def _struct_of(a):
    if isinstance(a, Variable):
        return a._data
    if isinstance(a, Tensor):
        return jax.ShapeDtypeStruct(tuple(a._data.shape), a._data.dtype)
    return None


class ShapeDtypeVerifier(AnalysisPass):
    name = "shape_dtype_verifier"

    def analyze(self, program: Program) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for op in program.global_block().ops:
            out.extend(self._check_op(op))
        return out

    # -- per-op checks ------------------------------------------------------
    def _check_op(self, op) -> List[Diagnostic]:
        found: List[Diagnostic] = []

        # 1) dtype-hygiene over the RECORDED outputs (independent of
        #    re-inference, so a tampered/stale graph is still caught)
        for v in op.outputs:
            dt = v._data.dtype
            if _is_extended(dt):
                continue
            if np.dtype(dt) in (np.float64, np.complex128):
                found.append(self.diag(
                    "PT-DTYPE-001", Severity.ERROR,
                    f"output '{v.name}' is {np.dtype(dt).name} — TPUs have no "
                    f"native fp64; cast to float32/bfloat16 before recording",
                    op=op))

        # 2) re-run inference and compare against the recorded outputs
        structs, has_ext = [], False
        for a in op.args:
            s = _struct_of(a)
            if s is not None:
                structs.append(s)
                has_ext = has_ext or _is_extended(s.dtype)
        has_ext = has_ext or any(_is_extended(v._data.dtype)
                                 for v in op.outputs)
        if not has_ext:
            found.extend(self._reinfer(op, structs))

        # 3) promotion surprise: every tensor input integral, output floating
        in_dts = [s.dtype for s in structs if not _is_extended(s.dtype)]
        if in_dts and all(np.issubdtype(np.dtype(d), np.integer)
                          for d in in_dts):
            for v in op.outputs:
                dt = v._data.dtype
                if _is_extended(dt) or not np.issubdtype(np.dtype(dt),
                                                         np.floating):
                    continue
                if any(k in (op.type or "") for k in _PROMOTION_OK):
                    continue
                found.append(self.diag(
                    "PT-DTYPE-002", Severity.WARNING,
                    f"op promotes all-integer inputs to "
                    f"{np.dtype(dt).name} output '{v.name}' — implicit "
                    f"int→float promotion; make the cast explicit",
                    op=op))
        return found

    def _reinfer(self, op, structs) -> List[Diagnostic]:
        args, kwargs = op.args, op.kwargs

        def pure(*sym):
            full = list(args)
            it = iter(sym)
            for i, a in enumerate(full):
                if isinstance(a, (Variable, Tensor)):
                    full[i] = next(it)
            return op.fn(*full, **kwargs)

        try:
            inferred = jax.eval_shape(pure, *structs)
        except Exception as e:  # the op itself no longer type-checks
            return [self.diag(
                "PT-SHAPE-003", Severity.ERROR,
                f"op no longer type-checks against its recorded inputs: "
                f"{type(e).__name__}: {str(e).splitlines()[0][:200]}",
                op=op)]
        inf_list = (list(inferred) if isinstance(inferred, (tuple, list))
                    else [inferred])
        found: List[Diagnostic] = []
        if len(inf_list) != len(op.outputs):
            return [self.diag(
                "PT-SHAPE-001", Severity.ERROR,
                f"op records {len(op.outputs)} output(s) but inference "
                f"produces {len(inf_list)}", op=op)]
        for v, s in zip(op.outputs, inf_list):
            rec = v._data
            if tuple(rec.shape) != tuple(s.shape):
                kind = ("rank" if len(rec.shape) != len(s.shape) else "shape")
                found.append(self.diag(
                    "PT-SHAPE-001", Severity.ERROR,
                    f"{kind} mismatch on '{v.name}': recorded "
                    f"{list(rec.shape)}, inference gives {list(s.shape)}",
                    op=op))
            elif rec.dtype != s.dtype:
                found.append(self.diag(
                    "PT-SHAPE-002", Severity.ERROR,
                    f"dtype mismatch on '{v.name}': recorded {rec.dtype}, "
                    f"inference gives {s.dtype}", op=op))
        return found
