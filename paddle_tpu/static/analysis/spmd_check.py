"""SPMD consistency checker (tentpole analyzer #3).

Validates ``distributed.auto_parallel`` placements against their mesh BEFORE
pjit lowering, where a mistake still has a name — at lowering time it surfaces
as a silent wrong-mesh recompile or an XLA sharding error with no framework
context (reference: the ~60 C++ SPMD rules in phi/infermeta/spmd_rules/*
each validate their inputs; GSPMD gives us propagation but not validation).

Codes: PT-SPMD-001 (invalid placement/axis, error), PT-SPMD-002 (uneven
shard, error), PT-SPMD-003 (conflicting shardings reaching one op, error).
Every diagnostic carries a line-number-free ``finding_id``
(``CODE:scope:detail``, scope = tensor/op names) — the PT-RACE/PT-COST
baseline scheme, so waivers survive unrelated edits.

Placements and meshes are duck-typed (``is_shard()/get_dim()`` /
``ndim/shape/dim_names``) so this module never imports the distributed
package — it stays importable from the core static layer.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ...core.static_graph import Program
from .diagnostics import AnalysisPass, Diagnostic, Severity

__all__ = ["SpmdConsistencyChecker", "check_placements", "check_axis_names"]


def _fid(code: str, scope: str, detail: str) -> str:
    """Line-number-free finding id (``CODE:scope:detail``) — the PT-RACE/
    PT-COST baseline scheme: ids survive unrelated edits because they
    name WHAT is wrong where (tensor/op names), never source positions
    (``op_idx``/``source`` stay on the Diagnostic for display only)."""
    scope = (scope or "?").replace("'", "").replace('"', "")
    return f"{code}:{scope.replace(' ', '_')}:{detail}"


def _diag(code, msg, op=None, analyzer="spmd_consistency_checker",
          scope="?", detail="?"):
    d = Diagnostic(code, Severity.ERROR, msg,
                   op_type=getattr(op, "type", None),
                   op_idx=getattr(op, "idx", None),
                   source=getattr(op, "src", None),
                   analyzer=analyzer)
    d.finding_id = _fid(code, scope, detail)
    return d


def check_placements(shape: Sequence[int], mesh, placements,
                     where: str = "tensor") -> List[Diagnostic]:
    """Validate one (tensor shape, mesh, placements) triple.

    The i-th placement names what the i-th MESH axis does — so the placement
    list must match the mesh rank, Shard dims must be valid tensor dims, and
    every sharded dim must divide evenly by the product of the mesh-axis sizes
    sharding it."""
    out: List[Diagnostic] = []
    ndim = len(shape)
    mesh_shape = list(mesh.shape)
    names = list(mesh.dim_names)
    placements = list(placements)

    # FEWER placements than mesh axes is valid — placements_to_spec zips and
    # the remaining axes replicate. MORE placements are silently DROPPED by
    # that zip, so the intent (a Shard, say) would never lower: flag it.
    if len(placements) > len(mesh_shape):
        out.append(_diag(
            "PT-SPMD-001",
            f"{where}: {len(placements)} placement(s) for a {len(mesh_shape)}"
            f"-axis mesh {names} — the extras are silently dropped at "
            f"lowering; give at most one placement per mesh axis",
            scope=where, detail="placement-count"))
        # still validate the overlapping prefix below

    shard_factor = {}  # tensor dim -> product of mesh-axis sizes sharding it
    for axis, p in enumerate(placements[: len(mesh_shape)]):
        if not p.is_shard():
            continue
        d = p.get_dim()
        if not (-ndim <= d < ndim):
            out.append(_diag(
                "PT-SPMD-001",
                f"{where}: Shard(dim={d}) on mesh axis '{names[axis]}' is "
                f"out of range for a rank-{ndim} tensor (shape "
                f"{list(shape)}) — placements_to_spec would silently wrap "
                f"it to dim {d % ndim if ndim else 0}",
                scope=where, detail=f"shard-dim:{d}:{names[axis]}"))
            continue
        d = d % ndim
        shard_factor[d] = shard_factor.get(d, 1) * int(mesh_shape[axis])
    for d, factor in sorted(shard_factor.items()):
        size = shape[d]
        if size in (-1, None):  # dynamic dim: divisibility is a runtime fact
            continue
        if int(size) % factor != 0:
            out.append(_diag(
                "PT-SPMD-002",
                f"{where}: dim {d} of size {size} does not divide evenly "
                f"over {factor} shards (mesh {dict(zip(names, mesh_shape))})"
                f" — pad to a multiple of {factor} or reshard",
                scope=where, detail=f"uneven:dim{d}:x{factor}"))
    return out


def check_axis_names(mesh, axis_names: Sequence[Optional[str]],
                     where: str = "spec") -> List[Diagnostic]:
    """Validate that every named axis in a PartitionSpec-style entry list
    exists on the mesh (axis entries may be None / str / tuple of str)."""
    known = set(mesh.dim_names)
    out: List[Diagnostic] = []
    for e in axis_names:
        if e is None:
            continue
        for a in (e if isinstance(e, tuple) else (e,)):
            if a not in known:
                out.append(_diag(
                    "PT-SPMD-001",
                    f"{where}: axis '{a}' does not exist on the mesh "
                    f"(axes: {sorted(known)})",
                    scope=where, detail=f"unknown-axis:{a}"))
    return out


def _dist_meta(t):
    """(mesh, placements) attached by shard_tensor, or None."""
    mesh = getattr(t, "process_mesh", None)
    placements = getattr(t, "placements", None)
    if mesh is None or placements is None:
        return None
    return mesh, placements


class SpmdConsistencyChecker(AnalysisPass):
    """Walk the program and validate every input carrying dist metadata; flag
    conflicting shardings converging on one op."""

    name = "spmd_consistency_checker"

    def analyze(self, program: Program) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        validated = set()  # id(tensor): validate each placed tensor ONCE,
        # at its first consuming op, instead of once per consumer
        for op in program.global_block().ops:
            placed = []
            for t in list(op.inputs) + list(op.captured):
                meta = _dist_meta(t)
                if meta is None:
                    continue
                mesh, placements = meta
                name = getattr(t, "name", "tensor") or "tensor"
                shape = tuple(getattr(t, "decl_shape", None)
                              or t._data.shape)
                if id(t) not in validated:
                    validated.add(id(t))
                    for d in check_placements(shape, mesh, placements,
                                              where=f"input '{name}'"):
                        d.op_type, d.op_idx = op.type, op.idx
                        d.source = d.source or getattr(op, "src", None)
                        out.append(d)
                placed.append((name, shape, mesh, list(placements)))
            out.extend(self._conflicts(op, placed))
        return out

    def _conflicts(self, op, placed) -> List[Diagnostic]:
        if len(placed) < 2:
            return []
        out: List[Diagnostic] = []
        name0, _, mesh0, _ = placed[0]
        for name, _, mesh, _ in placed[1:]:
            same = (list(mesh.shape) == list(mesh0.shape)
                    and list(mesh.dim_names) == list(mesh0.dim_names)
                    and np.array_equal(np.asarray(mesh.mesh),
                                       np.asarray(mesh0.mesh)))
            if not same:
                d = self.diag(
                    "PT-SPMD-003", Severity.ERROR,
                    f"inputs '{name0}' and '{name}' reach this op on "
                    f"DIFFERENT meshes ({mesh0} vs {mesh}) — reshard one "
                    f"side before combining", op=op)
                d.finding_id = _fid("PT-SPMD-003", op.type,
                                    f"mesh-conflict:{name0}:{name}")
                out.append(d)
        # same-shape inputs that disagree on placements: often legitimate
        # (row/col tensor parallelism shards matmul operands differently), but
        # GSPMD will silently reshard one side — surface it as a WARNING so
        # divergence is visible without failing correct TP programs
        by_shape = {}
        for name, shape, mesh, placements in placed:
            key = tuple(shape)
            if key in by_shape:
                pname, pplace = by_shape[key]
                if pplace != placements:
                    d = self.diag(
                        "PT-SPMD-003", Severity.WARNING,
                        f"same-shape inputs '{pname}' and '{name}' carry "
                        f"conflicting shardings {pplace} vs {placements} — "
                        f"GSPMD will reshard one side; if unintended, align "
                        f"them explicitly (reshard) before this op", op=op)
                    d.finding_id = _fid("PT-SPMD-003", op.type,
                                        f"divergent:{pname}:{name}")
                    out.append(d)
            else:
                by_shape[key] = (name, placements)
        return out
