"""Import a traced callable into the Program IR (``trace_to_program``).

The model families execute eager jax on raw arrays for speed, so their
forwards never pass through ``record_op`` — but they ARE pure under tracing
(that's what jit.to_static exploits). This bridge runs ``jax.make_jaxpr``
over a functionalized forward and rebuilds the jaxpr as a ``Program``: one
``Operation`` per equation (the "kernel" is ``primitive.bind`` with the
equation's params, so the imported program replays under the Executor too),
parameters as named parameter Variables, trace-time constants as captured
Tensors, and per-equation source provenance from jaxpr source_info.

This is how tools/lint_graph.py records every in-repo model family for the
analyzer suite without requiring models to adopt the recording op path.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

from ...core.static_graph import Operation, Program, Variable
from ...core.tensor import Tensor

__all__ = ["trace_to_program", "layer_to_program"]


def _summarize_src(eqn) -> Optional[str]:
    try:
        from jax._src import source_info_util

        s = source_info_util.summarize(eqn.source_info)
        return s or None
    except Exception:  # pragma: no cover - jax internals drift
        return None


def trace_to_program(fn, *input_structs, input_names: Optional[Sequence[str]] = None,
                     param_structs: Sequence = (), param_names: Sequence[str] = (),
                     param_tensors: Sequence = ()) -> Program:
    """Trace ``fn(params..., inputs...)`` (flat positional arrays) and rebuild
    the jaxpr as a Program. ``param_*`` describe the leading arguments that
    are model parameters (named Variables with ``is_parameter=True``)."""
    closed = jax.make_jaxpr(lambda *a: fn(*a))(*param_structs, *input_structs)
    jaxpr = closed.jaxpr
    prog = Program()
    blk = prog.global_block()

    env = {}
    n_params = len(list(param_structs))
    names = list(param_names) + [
        (input_names[i] if input_names and i < len(input_names)
         else f"feed_{i}")
        for i in range(len(jaxpr.invars) - n_params)]
    param_tensors = list(param_tensors)
    for i, var in enumerate(jaxpr.invars):
        name = names[i] if i < len(names) else f"arg_{i}"
        v = blk.create_var(var.aval.shape, var.aval.dtype, name=name,
                           is_feed=(i >= n_params))
        if i < n_params:
            v.is_parameter = True
            if i < len(param_tensors):
                v._param = param_tensors[i]  # back-link for analyzers
        env[var] = v

    for const_var, const_val in zip(jaxpr.constvars, closed.consts):
        t = Tensor(const_val) if not isinstance(const_val, Tensor) else const_val
        t.name = getattr(t, "name", None) or f"const_{len(env)}"
        env[const_var] = t

    for eqn in jaxpr.eqns:
        args = []
        for iv in eqn.invars:
            if isinstance(iv, jax.core.Literal):
                args.append(np.asarray(iv.val) if hasattr(iv.val, "shape")
                            else iv.val)
            else:
                args.append(env[iv])
        prim, params = eqn.primitive, dict(eqn.params)

        # params live in the CLOSURE, not default args: closure cells holding
        # a dict are unfingerprintable, so CSE can never merge two same-
        # primitive eqns that differ only in params (e.g. two reshapes)
        def make_kernel(prim, params):
            def kernel(*xs):
                out = prim.bind(*xs, **params)
                return tuple(out) if prim.multiple_results else out
            # random_* eqns replay a PRNG key BAKED into the jaxpr — they are
            # deterministic, so the trace linter must not flag them unseeded
            kernel._jaxpr_import = True
            # back-links for the cost auditor's op-level fallback walk
            # (static/cost — Operation has __slots__, so they ride the fn)
            kernel._primitive = prim
            kernel._prim_params = params
            return kernel

        op = Operation(len(blk.ops), prim.name, make_kernel(prim, params),
                       args, {}, src=_summarize_src(eqn))
        blk.ops.append(op)
        prog._version += 1
        for ov in eqn.outvars:
            v = blk.create_var(ov.aval.shape, ov.aval.dtype,
                               name=prog._next_name(prim.name), op=op)
            op.outputs.append(v)
            env[ov] = v

    outs = []
    for ov in jaxpr.outvars:
        if isinstance(ov, jax.core.Literal):
            continue
        o = env.get(ov)
        if isinstance(o, Variable):
            outs.append(o)
    prog._outputs = outs  # liveness roots for Program.diagnose()
    # the full ClosedJaxpr rides along for analyzers that must recurse into
    # container primitives (scan bodies, pjit calls) and read dataflow the
    # flattened op list cannot express — the PT-COST walker (static/cost)
    prog._closed_jaxpr = closed
    return prog


def layer_to_program(layer, *input_structs, input_names=None,
                     extra_kwargs=None) -> Program:
    """Functionalize a Layer (params+buffers become named inputs — the same
    split jit.to_static uses) and import its traced forward as a Program."""
    from ...jit.api import _collect_state, _Swap, _tree_unwrap

    names, tensors = _collect_state(layer)
    state_structs = [jax.ShapeDtypeStruct(tuple(t._data.shape), t._data.dtype)
                     for t in tensors]
    n_state = len(state_structs)
    kwargs = dict(extra_kwargs or {})

    def flat(*arrays):
        state, ins = arrays[:n_state], arrays[n_state:]
        with _Swap(tensors, list(state)):
            out = layer(*[Tensor(a) for a in ins], **kwargs)
        return _tree_unwrap(out)

    return trace_to_program(
        flat, *input_structs, input_names=input_names,
        param_structs=state_structs, param_names=names,
        param_tensors=tensors)
