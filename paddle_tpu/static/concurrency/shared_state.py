"""Shared-state inference over a module's thread model.

A state key (instance attribute / module global / closure variable —
``thread_model.Access.key``) is **shared** when some access to it happens
on a thread role and the union of roles across all its accesses is not a
single role — i.e. two different threads, or a thread and the main path,
can touch it concurrently. A function carrying both ``main`` and a thread
role (a helper called from a daemon loop *and* from public methods) makes
everything it touches shared by itself: it races with its own other
incarnation.

Happens-before exclusions applied here (the model records them):

- ``__init__`` accesses (object unpublished);
- ``prestart`` writes (lexically before the ``.start()`` in the spawning
  function — thread start is a synchronization edge);
- closure variables whose spawning function joins the worker after the
  spawn (reads after ``join`` are happens-after; the model keeps the
  key only when no such join exists).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Set

from .thread_model import MAIN_ROLE, Access, ModuleModel

__all__ = ["SharedKey", "infer_shared_state"]


@dataclasses.dataclass
class SharedKey:
    """One shared-state candidate with its guard summary."""

    key: str
    accesses: List[Access]
    roles: Set[str]                  # union of roles across accesses
    guards: Set[str]                 # locks seen on >=1 guarded access
    writes: List[Access]
    unguarded_writes: List[Access]
    unguarded_reads: List[Access]

    @property
    def name(self) -> str:
        """Human-facing name: strip the key-space prefix."""
        return self.key.split(":", 1)[1]

    @property
    def fully_unguarded(self) -> bool:
        return not self.guards

    def funcs(self) -> List[str]:
        seen: List[str] = []
        for a in self.accesses:
            if a.func not in seen:
                seen.append(a.func)
        return seen


def _relevant(a: Access) -> bool:
    return not a.in_init and not a.prestart


def infer_shared_state(model: ModuleModel) -> Dict[str, SharedKey]:
    """Group accesses by key, decide sharedness, summarize guards."""
    by_key: Dict[str, List[Access]] = {}
    roles_of_func = {q: f.roles for q, f in model.funcs.items()}
    for info in model.funcs.values():
        for a in info.accesses:
            by_key.setdefault(a.key, []).append(a)

    out: Dict[str, SharedKey] = {}
    for key, accesses in by_key.items():
        live = [a for a in accesses if _relevant(a)]
        if not live:
            continue
        roles: Set[str] = set()
        for a in live:
            roles |= roles_of_func.get(a.func, {MAIN_ROLE})
        thread_roles = {r for r in roles if r != MAIN_ROLE}
        if not thread_roles or len(roles) < 2:
            continue                      # single-role: no concurrency
        if key.startswith("L:"):
            # closure var: the spawning function joining the worker after
            # the spawn makes later reads happens-after — not shared
            owner = key[2:].rsplit(".", 1)[0]
            oinfo = model.funcs.get(owner)
            if oinfo is not None and oinfo.join_after is not None:
                continue
        writes = [a for a in live if a.kind == "write"]
        if not writes:
            # read-only after publication (``__init__``/prestart writes
            # are happens-before the spawn): immutable enough
            continue
        guards: Set[str] = set()
        for a in live:
            guards |= set(a.locks)
        out[key] = SharedKey(
            key=key, accesses=live, roles=roles, guards=guards,
            writes=writes,
            unguarded_writes=[a for a in writes if not a.locks],
            unguarded_reads=[a for a in live
                             if a.kind == "read" and not a.locks])
    return out
