"""PT-RACE lock-discipline checks over the thread model + shared state.

=========== ============================================================
PT-RACE-001 unguarded write to shared state — no lock anywhere on the key
PT-RACE-002 inconsistent guarding — same key sometimes under a lock,
            sometimes not (error for an unguarded WRITE, warning for an
            unguarded read while writes are locked)
PT-RACE-003 lock-order inversion — a cycle in the lock-acquisition graph
            (includes re-acquiring a non-reentrant ``Lock``)
PT-RACE-004 check-then-act outside the guarding lock — an ``if``/``while``
            test reads a guarded shared key without its lock and the suite
            then mutates it (decision made on stale state)
PT-RACE-005 leaked thread — a non-daemon ``Thread`` that can never be
            joined (fire-and-forget ``.start()`` chain, or a module with
            no join at all)
=========== ============================================================

Findings are ordinary :class:`~paddle_tpu.static.analysis.diagnostics.
Diagnostic` objects (severity + ``file:line`` provenance) so they compose
with the existing report machinery; each additionally carries a stable
``finding_id`` (``CODE:relpath:scope:detail`` — line-number free) that the
lint gate's baseline file keys on (docs/STATIC_ANALYSIS.md).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..analysis.diagnostics import Diagnostic, Severity
from .shared_state import SharedKey, infer_shared_state
from .thread_model import MAIN_ROLE, ModuleModel

__all__ = ["run_checks", "finding_id"]

ANALYZER = "concurrency"


def finding_id(code: str, relpath: str, scope: str, detail: str) -> str:
    return f"{code}:{relpath}:{scope}:{detail}"


def _diag(code: str, severity, message: str, relpath: str, lineno: int,
          scope: str, detail: str) -> Diagnostic:
    d = Diagnostic(code=code, severity=Severity(severity), message=message,
                   source=f"{relpath}:{lineno}", analyzer=ANALYZER)
    d.finding_id = finding_id(code, relpath, scope, detail)
    return d


def _scope_of(key: str) -> str:
    """Baseline scope for a state key: the owning class (``A:`` keys) or
    the module level (``G:``/``L:`` keys)."""
    kind, _, rest = key.partition(":")
    if kind == "A":
        return rest.rsplit(".", 1)[0]
    if kind == "L":
        return rest.rsplit(".", 1)[0]
    return "<module>"


def _site_list(accesses, limit=3) -> str:
    sites = []
    for a in accesses[:limit]:
        sites.append(f"{a.func}:{a.lineno}")
    more = len(accesses) - limit
    return ", ".join(sites) + (f" (+{more} more)" if more > 0 else "")


def _role_list(roles: Set[str]) -> str:
    return "/".join(sorted(roles))


# ---------------------------------------------------------------------------
# PT-RACE-001 / 002: guarding discipline
# ---------------------------------------------------------------------------

def _check_guarding(model: ModuleModel,
                    shared: Dict[str, SharedKey]) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    rel = model.relpath
    for key, sk in sorted(shared.items()):
        scope = _scope_of(key)
        if sk.fully_unguarded:
            w = sk.unguarded_writes[0]
            out.append(_diag(
                "PT-RACE-001", Severity.ERROR,
                f"'{sk.name}' is written from {_role_list(sk.roles)} with "
                f"no lock anywhere (writes at {_site_list(sk.writes)}; "
                f"touched by {', '.join(sk.funcs()[:4])})",
                rel, w.lineno, scope, sk.name))
            continue
        if sk.unguarded_writes:
            w = sk.unguarded_writes[0]
            locks = "/".join(sorted(sk.guards))
            out.append(_diag(
                "PT-RACE-002", Severity.ERROR,
                f"'{sk.name}' is guarded by {locks} elsewhere but written "
                f"WITHOUT it at {_site_list(sk.unguarded_writes)} "
                f"(roles: {_role_list(sk.roles)})",
                rel, w.lineno, scope, sk.name))
        elif sk.unguarded_reads:
            r = sk.unguarded_reads[0]
            locks = "/".join(sorted(sk.guards))
            out.append(_diag(
                "PT-RACE-002", Severity.WARNING,
                f"'{sk.name}' writes are guarded by {locks} but it is read "
                f"WITHOUT the lock at {_site_list(sk.unguarded_reads)} — "
                "torn/stale read",
                rel, r.lineno, scope, sk.name))
    return out


# ---------------------------------------------------------------------------
# PT-RACE-003: lock-order inversion
# ---------------------------------------------------------------------------

def _check_lock_order(model: ModuleModel) -> List[Diagnostic]:
    edges: Dict[str, Set[str]] = {}
    sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
    self_reacquire: List = []
    for info in model.funcs.values():
        for acq in info.acquires:
            for held in acq.held:
                if held == acq.lock:
                    if not acq.reentrant:
                        self_reacquire.append(acq)
                    continue
                edges.setdefault(held, set()).add(acq.lock)
                sites.setdefault((held, acq.lock), (acq.func, acq.lineno))
    out: List[Diagnostic] = []
    rel = model.relpath
    for acq in self_reacquire:
        out.append(_diag(
            "PT-RACE-003", Severity.ERROR,
            f"non-reentrant lock {acq.lock} re-acquired while already held "
            f"in {acq.func} — self-deadlock",
            rel, acq.lineno, acq.func.split(".")[0], f"{acq.lock}-self"))
    # cycle detection: DFS from each node (graphs here are tiny)
    seen_cycles: Set[frozenset] = set()
    for start in sorted(edges):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(edges.get(node, ())):
                if nxt == start and len(path) > 1:
                    cyc = frozenset(path)
                    if cyc in seen_cycles:
                        continue
                    seen_cycles.add(cyc)
                    order = path + [start]
                    func, lineno = sites.get((path[-1], start),
                                             ("<module>", 0))
                    where = " -> ".join(order)
                    out.append(_diag(
                        "PT-RACE-003", Severity.ERROR,
                        f"lock-order inversion: {where} (closing edge in "
                        f"{func}) — concurrent holders can deadlock",
                        rel, lineno, "<module>",
                        "->".join(sorted(cyc))))
                elif nxt not in path:
                    stack.append((nxt, path + [nxt]))
    return out


# ---------------------------------------------------------------------------
# PT-RACE-004: check-then-act outside the guarding lock
# ---------------------------------------------------------------------------

def _check_toctou(model: ModuleModel,
                  shared: Dict[str, SharedKey]) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    rel = model.relpath
    # direct write keys per function (for one-level call-through bodies)
    writes_of: Dict[str, Set[str]] = {}
    for q, info in model.funcs.items():
        writes_of[q] = {a.key for a in info.accesses if a.kind == "write"}
    reported: Set[str] = set()
    for info in model.funcs.values():
        for t in info.toctous:
            body_writes = set(t.body_writes)
            for callee in t.body_callees:
                body_writes |= writes_of.get(callee, set())
            for key, test_locks in t.test_reads:
                sk = shared.get(key)
                if sk is None or not sk.guards:
                    continue                  # 001 territory (or unshared)
                if sk.guards & test_locks:
                    continue                  # test holds a guarding lock
                if key not in body_writes:
                    continue
                fid = finding_id("PT-RACE-004", rel, _scope_of(key), sk.name)
                if fid in reported:
                    continue
                reported.add(fid)
                locks = "/".join(sorted(sk.guards))
                out.append(_diag(
                    "PT-RACE-004", Severity.ERROR,
                    f"check-then-act on '{sk.name}' in {t.func}: the test "
                    f"reads it outside {locks} and the suite then mutates "
                    "it — the decision can be stale by the time it acts",
                    rel, t.lineno, _scope_of(key), sk.name))
    return out


# ---------------------------------------------------------------------------
# PT-RACE-005: leaked / unjoinable threads
# ---------------------------------------------------------------------------

def _check_thread_leaks(model: ModuleModel) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    rel = model.relpath
    for sp in model.spawns:
        if sp.kind != "thread" or sp.daemon:
            continue
        detail = sp.target or sp.target_text
        if sp.chained_start:
            out.append(_diag(
                "PT-RACE-005", Severity.ERROR,
                f"non-daemon Thread(target={sp.target_text}) is started "
                f"without binding it ({sp.func}) — it can never be joined "
                "and will block interpreter exit",
                rel, sp.lineno, sp.func, detail))
        elif not model.has_thread_join:
            out.append(_diag(
                "PT-RACE-005", Severity.ERROR,
                f"non-daemon Thread(target={sp.target_text}) started in "
                f"{sp.func} but nothing in this module ever joins a "
                "thread — leaked thread blocks interpreter exit",
                rel, sp.lineno, sp.func, detail))
    return out


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------

def run_checks(model: ModuleModel,
               shared: Optional[Dict[str, SharedKey]] = None
               ) -> List[Diagnostic]:
    """All PT-RACE rules over one module model, ordered by rule then line."""
    if shared is None:
        shared = infer_shared_state(model)
    findings: List[Diagnostic] = []
    findings += _check_guarding(model, shared)
    findings += _check_lock_order(model)
    findings += _check_toctou(model, shared)
    findings += _check_thread_leaks(model)
    return findings
