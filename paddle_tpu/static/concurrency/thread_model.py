"""Thread-model builder: which code runs on which thread, per module.

The graph analyzers (``static/analysis``) see the device program; this layer
sees the HOST program — the threaded Python that keeps serving alive
(journaled supervisors, step watchdogs, metrics servers, heartbeat loops,
async checkpoint writers). Everything here is pure ``ast``: no imports of
the analyzed code, no jax, so the whole package sweeps in well under a
second and the lint gate (tools/lint_concurrency.py) costs CI nothing.

The model answers three questions for one module:

1. **Where do threads start?** ``threading.Thread(target=...)``,
   ``ThreadPoolExecutor.submit(fn, ...)``, ``atexit.register(fn)``,
   ``socketserver``/``http.server`` handler classes (their methods run on
   per-connection server threads), plus caller-supplied *extra roots* for
   entry points that cross module boundaries (e.g. ``retry_call`` running
   on a fleet ``parallel_step`` thread — the gate's ``THREAD_ROOTS``).
2. **What runs on those threads?** Roles propagate through the intra-module
   call graph: a spawn target seeds ``thread:<entry>``; every function a
   thread-role function calls inherits the role. Every function that is
   not *exclusively* a thread target also carries ``main`` (it is callable
   from the main path), so a helper invoked from both a daemon loop and a
   public method carries both roles — exactly the functions whose state
   accesses can race.
3. **Which locks guard what?** ``self.X = threading.Lock()/RLock()/
   Condition()/Semaphore()`` and module-level equivalents are recognized as
   locks; ``with self.X:`` (and ``.acquire()``/``.release()``) tracks the
   held-lock set at every state access and every nested acquisition (the
   raw material for the lock-order graph). Locks are keyed by the ROOT
   in-module base class that the attribute belongs to, so ``Counter`` and
   ``Histogram`` sharing ``_Instrument._lock`` unify.

Happens-before edges the model understands (and therefore does not flag):

- ``__init__`` writes — the object is not published yet;
- writes lexically before a ``.start()`` call in the function that spawns
  the thread (``prestart`` — thread start is a synchronization edge);
- closure variables written by a worker and read only after the spawning
  function ``join``\\ s it.

See docs/STATIC_ANALYSIS.md (PT-RACE section) for the rule catalogue built
on top of this model (shared_state.py + checks.py).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["Access", "Acquire", "Spawn", "FuncInfo", "ModuleModel",
           "build_module_model", "MAIN_ROLE"]

MAIN_ROLE = "main"

#: threading factories that produce a lock-like object (Condition counts:
#: ``with cond:`` owns the underlying lock)
LOCK_FACTORIES = ("Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore")

#: socketserver / http.server bases whose subclasses' methods run on
#: per-connection server threads (ThreadingTCPServer / ThreadingHTTPServer)
HANDLER_BASES = ("BaseRequestHandler", "StreamRequestHandler",
                 "DatagramRequestHandler", "BaseHTTPRequestHandler",
                 "SimpleHTTPRequestHandler", "CGIHTTPRequestHandler")

#: method names that mutate their receiver — ``self.attr.append(x)`` is a
#: WRITE to ``attr`` for lock-discipline purposes
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "add", "discard",
    "setdefault", "sort", "reverse", "rotate", "put", "put_nowait",
})

#: lock-object methods (never state accesses)
LOCK_METHODS = frozenset({"acquire", "release", "wait", "wait_for",
                          "notify", "notify_all", "locked"})


@dataclasses.dataclass
class Access:
    """One read/write of a shared-state candidate.

    ``key`` forms: ``"A:<RootClass>.<attr>"`` (instance attribute),
    ``"G:<name>"`` (module global), ``"L:<func>.<var>"`` (closure var of
    ``func`` touched by a nested worker)."""

    key: str
    kind: str                    # "read" | "write"
    func: str                    # qualname of the accessing function
    lineno: int
    locks: frozenset             # lock keys held (syntactic + caller-held)
    in_init: bool = False
    prestart: bool = False


@dataclasses.dataclass
class Acquire:
    lock: str
    held: frozenset              # locks already held at this acquisition
    func: str
    lineno: int
    reentrant: bool = False      # RLock/Condition/Semaphore


@dataclasses.dataclass
class Spawn:
    kind: str                    # "thread" | "pool" | "atexit" | "handler"
    target: Optional[str]        # resolved in-module qualname (or None)
    target_text: str             # source text of the target expr (reports)
    daemon: bool
    chained_start: bool          # Thread(...).start() — can never be joined
    func: str                    # spawning function ("<module>" at top level)
    lineno: int


@dataclasses.dataclass
class Toctou:
    """An if/while whose test reads shared state — evaluated by checks.py
    once guard sets are known (PT-RACE-004)."""

    func: str
    lineno: int
    test_reads: List[Tuple[str, frozenset]]     # (key, locks at test)
    body_writes: List[str]                      # keys written in the suite
    body_callees: List[str]                     # self-calls inside the suite


@dataclasses.dataclass
class FuncInfo:
    qualname: str
    cls: Optional[str]           # OWN class name (None for module funcs)
    root_cls: Optional[str]      # root in-module base (attr/lock key space)
    node: ast.AST
    parent: Optional[str]        # enclosing function qualname (nested defs)
    is_target: bool = False      # referenced as a spawn target
    roles: Set[str] = dataclasses.field(default_factory=set)
    accesses: List[Access] = dataclasses.field(default_factory=list)
    acquires: List[Acquire] = dataclasses.field(default_factory=list)
    calls: List[Tuple[str, frozenset, int]] = dataclasses.field(
        default_factory=list)   # (callee qualname, locks held at site, line)
    local_names: Set[str] = dataclasses.field(default_factory=set)
    toctous: List[Toctou] = dataclasses.field(default_factory=list)
    spawn_lines: List[int] = dataclasses.field(default_factory=list)
    #: happens-before boundary for this function's spawns: the first
    #: ``.start()`` after a Thread construction (falling back to the
    #: construction itself) — writes lexically before it are pre-publication
    prestart_line: Optional[int] = None
    join_after: Optional[int] = None   # first .join() lineno after a spawn


class ModuleModel:
    """Everything the checks need to know about one module."""

    def __init__(self, relpath: str):
        self.relpath = relpath
        self.funcs: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, List[str]] = {}       # name -> base names
        self.class_methods: Dict[str, Set[str]] = {}  # name -> method names
        self.lock_attrs: Dict[str, Dict[str, str]] = {}  # root cls -> {attr: factory}
        self.module_locks: Dict[str, str] = {}           # name -> factory
        self.mutable_globals: Set[str] = set()
        self.spawns: List[Spawn] = []
        self.has_thread_join: bool = False

    # -- class/key helpers -------------------------------------------------
    def root_class(self, name: Optional[str]) -> Optional[str]:
        """Walk the in-module base chain to the top — the namespace
        instance attributes and locks are keyed under (``Counter`` and
        ``Histogram`` both key under ``_Instrument``)."""
        if name is None:
            return None
        seen = set()
        cur = name
        while cur in self.classes and cur not in seen:
            seen.add(cur)
            nxt = next((b for b in self.classes[cur] if b in self.classes),
                       None)
            if nxt is None:
                break
            cur = nxt
        return cur

    def is_lock_attr(self, root_cls: Optional[str], attr: str) -> bool:
        return attr in self.lock_attrs.get(root_cls or "", {})

    def lock_factory(self, key: str) -> str:
        if key.startswith("M:"):
            return self.module_locks.get(key[2:], "Lock")
        cls, _, attr = key.partition(".")
        return self.lock_attrs.get(cls, {}).get(attr, "Lock")

    def methods_of(self, cls: str) -> Set[str]:
        """Method names visible on ``cls`` through the in-module MRO."""
        out: Set[str] = set()
        seen = set()
        cur: Optional[str] = cls
        while cur in self.classes and cur not in seen:
            seen.add(cur)
            out |= self.class_methods.get(cur, set())
            cur = next((b for b in self.classes[cur] if b in self.classes),
                       None)
        return out


# ---------------------------------------------------------------------------
# phase 1: module scan (classes, locks, globals, import aliases)
# ---------------------------------------------------------------------------

def _call_name(node: ast.AST) -> Optional[str]:
    """Dotted name of a call's func: ``threading.Thread`` -> that string,
    bare ``Thread`` -> ``"Thread"``; anything else -> None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _resolve_dotted(name: Optional[str],
                    aliases: Dict[str, str]) -> Optional[str]:
    """Resolve a dotted call name through the module's import aliases:
    ``ax.register`` -> ``atexit.register`` (``import atexit as ax``),
    ``register`` -> ``atexit.register`` (``from atexit import register``),
    ``_threading.Thread`` -> ``threading.Thread``."""
    if name is None:
        return None
    head, sep, rest = name.partition(".")
    head = aliases.get(head, head)
    return head + sep + rest


def _is_thread_ctor(full: Optional[str]) -> bool:
    return bool(full) and full.rsplit(".", 1)[-1] == "Thread"


def _lock_factory_of(expr: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Return the factory name (``Lock``/``RLock``/...) if ``expr``
    constructs a threading lock — including guarded forms like
    ``lock or threading.Lock()`` and ``X if c else threading.Lock()``."""
    if isinstance(expr, ast.Call):
        full = _resolve_dotted(_call_name(expr.func), aliases)
        if full is None:
            return None
        last = full.rsplit(".", 1)[-1]
        if last in LOCK_FACTORIES and (full == last
                                       or full.startswith("threading.")
                                       or full.startswith("multiprocessing.")):
            return last
        return None
    if isinstance(expr, ast.BoolOp):
        for v in expr.values:
            f = _lock_factory_of(v, aliases)
            if f:
                return f
    if isinstance(expr, ast.IfExp):
        for v in (expr.body, expr.orelse):
            f = _lock_factory_of(v, aliases)
            if f:
                return f
    return None


def _is_mutable_literal(expr: ast.AST) -> bool:
    return isinstance(expr, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                             ast.ListComp, ast.SetComp)) or (
        isinstance(expr, ast.Call)
        and _call_name(expr.func) in ("dict", "list", "set", "collections.deque",
                                      "deque", "defaultdict",
                                      "collections.defaultdict",
                                      "collections.OrderedDict",
                                      "OrderedDict"))


class _Phase1(ast.NodeVisitor):
    def __init__(self, model: ModuleModel):
        self.m = model
        self.aliases: Dict[str, str] = {}   # imported-name -> canonical
        self._cls_stack: List[str] = []
        self._func_depth = 0                # module-global detection only
        #                                     applies at depth 0

    def visit_FunctionDef(self, node):
        self._func_depth += 1
        self.generic_visit(node)
        self._func_depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Import(self, node):
        for a in node.names:
            self.aliases[a.asname or a.name] = a.name

    def visit_ImportFrom(self, node):
        for a in node.names:
            # keep the module qualifier so `from atexit import register`
            # resolves to "atexit.register", not a bare "register"
            self.aliases[a.asname or a.name] = (
                f"{node.module}.{a.name}" if node.module else a.name)

    def visit_ClassDef(self, node):
        bases = []
        for b in node.bases:
            name = _call_name(b)
            if name:
                bases.append(name.rsplit(".", 1)[-1])
        self.m.classes[node.name] = bases
        self.m.class_methods[node.name] = {
            n.name for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self._cls_stack.append(node.name)
        self.generic_visit(node)
        self._cls_stack.pop()

    def visit_Assign(self, node):
        factory = _lock_factory_of(node.value, self.aliases)
        for t in node.targets:
            if isinstance(t, ast.Name) and not self._cls_stack \
                    and not self._func_depth:
                if factory:
                    self.m.module_locks[t.id] = factory
                elif _is_mutable_literal(node.value):
                    self.m.mutable_globals.add(t.id)
            elif (isinstance(t, ast.Attribute)
                  and isinstance(t.value, ast.Name) and t.value.id == "self"
                  and factory and self._cls_stack):
                root = self.m.root_class(self._cls_stack[-1])
                self.m.lock_attrs.setdefault(root, {})[t.attr] = factory
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is None:
            return
        factory = _lock_factory_of(node.value, self.aliases)
        t = node.target
        if isinstance(t, ast.Name) and not self._cls_stack \
                and not self._func_depth:
            if factory:
                self.m.module_locks[t.id] = factory
            elif _is_mutable_literal(node.value):
                self.m.mutable_globals.add(t.id)
        elif (isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name)
              and t.value.id == "self" and factory and self._cls_stack):
            root = self.m.root_class(self._cls_stack[-1])
            self.m.lock_attrs.setdefault(root, {})[t.attr] = factory
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# phase 2: per-function body walk (accesses, locks, calls, spawns)
# ---------------------------------------------------------------------------

def _looks_like_thread_join(call: ast.Call) -> bool:
    """``x.join()`` / ``x.join(2.0)`` / ``x.join(timeout=...)`` — excludes
    ``",".join(parts)`` / ``os.path.join(a, b)`` by receiver/arg shape."""
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr != "join":
        return False
    if isinstance(f.value, ast.Constant):       # "sep".join(...)
        return False
    name = _call_name(f)
    if name and (name.startswith("os.path.") or name.startswith("posixpath.")
                 or name.startswith("ntpath.")):
        return False
    if len(call.args) > 1:
        return False
    if call.args and not (isinstance(call.args[0], ast.Constant)
                          and isinstance(call.args[0].value, (int, float))):
        # a positional arg must be a literal timeout — ``sep.join(parts)``
        # style string joins pass a non-numeric value here
        return False
    if any(kw.arg != "timeout" for kw in call.keywords):
        return False
    return True


class _FuncWalker:
    """Walks ONE function body, linearly per block, tracking held locks."""

    def __init__(self, model: ModuleModel, info: FuncInfo,
                 aliases: Dict[str, str],
                 enclosing_locals: Set[str]):
        self.m = model
        self.info = info
        self.aliases = aliases
        self.enclosing_locals = enclosing_locals
        self.global_decls: Set[str] = set()
        self.sticky: Set[str] = set()          # .acquire()'d, not released
        self._nested_defs: Set[str] = set()

    # -- naming -------------------------------------------------------------
    def _lock_key_of(self, expr: ast.AST) -> Optional[str]:
        """Lock key for a ``with X:`` context or ``X.acquire()`` receiver."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            root = self.info.root_cls
            if self.m.is_lock_attr(root, expr.attr):
                return f"{root}.{expr.attr}"
            return None
        if isinstance(expr, ast.Name) and expr.id in self.m.module_locks:
            return f"M:{expr.id}"
        return None

    def _attr_key(self, attr: str) -> str:
        return f"A:{self.info.root_cls}.{attr}"

    def _resolve_call(self, func_expr: ast.AST) -> Optional[str]:
        """Resolve an in-module callee qualname for role/guard propagation."""
        if isinstance(func_expr, ast.Attribute) and \
                isinstance(func_expr.value, ast.Name) and \
                func_expr.value.id == "self" and self.info.cls:
            if func_expr.attr in self.m.methods_of(self.info.cls):
                # record against the class that DEFINES it (walk MRO)
                cur = self.info.cls
                while cur in self.m.classes:
                    if func_expr.attr in self.m.class_methods.get(cur, ()):
                        return f"{cur}.{func_expr.attr}"
                    cur = next((b for b in self.m.classes[cur]
                                if b in self.m.classes), None)
                    if cur is None:
                        break
                return f"{self.info.cls}.{func_expr.attr}"
        if isinstance(func_expr, ast.Name):
            name = func_expr.id
            nested = f"{self.info.qualname}.<locals>.{name}"
            if nested in self.m.funcs or nested in self._nested_defs:
                return nested
            if name in self.m.funcs:
                return name
        return None

    def _resolve_target(self, expr: ast.AST) -> Optional[str]:
        """Resolve a callable EXPRESSION (thread target, submit arg)."""
        return self._resolve_call(expr)

    # -- access recording ----------------------------------------------------
    def _rec(self, key: str, kind: str, lineno: int, held: frozenset):
        self.info.accesses.append(Access(
            key=key, kind=kind, func=self.info.qualname, lineno=lineno,
            locks=held, in_init=self.info.qualname.endswith(".__init__"),
            prestart=(self.info.prestart_line is not None
                      and lineno < self.info.prestart_line)))

    # -- expressions ---------------------------------------------------------
    def expr(self, node: ast.AST, held: frozenset) -> None:
        """Collect accesses/calls/spawns from one expression tree."""
        if node is None:
            return
        if isinstance(node, ast.Call):
            self._call(node, held)
            return
        if isinstance(node, ast.Attribute):
            self._attribute(node, held)
            return
        if isinstance(node, ast.Subscript):
            self._subscript(node, held)
            return
        if isinstance(node, ast.Name):
            self._name(node, held)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return                          # nested scopes handled elsewhere
        for child in ast.iter_child_nodes(node):
            self.expr(child, held)

    def _attribute(self, node: ast.Attribute, held: frozenset) -> None:
        if isinstance(node.value, ast.Name) and node.value.id == "self" \
                and self.info.cls:
            if self.m.is_lock_attr(self.info.root_cls, node.attr):
                return                      # the lock object itself
            kind = ("write" if isinstance(node.ctx, (ast.Store, ast.Del))
                    else "read")
            self._rec(self._attr_key(node.attr), kind, node.lineno, held)
            return
        self.expr(node.value, held)

    def _subscript(self, node: ast.Subscript, held: frozenset) -> None:
        base = node.value
        store = isinstance(node.ctx, (ast.Store, ast.Del))
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and base.value.id == "self" \
                and self.info.cls:
            self._rec(self._attr_key(base.attr),
                      "write" if store else "read", node.lineno, held)
        elif isinstance(base, ast.Name) and self._is_shared_name(base.id):
            self._rec(self._name_key(base.id),
                      "write" if store else "read", node.lineno, held)
        else:
            self.expr(base, held)
        self.expr(node.slice, held)

    def _is_shared_name(self, name: str) -> bool:
        if name in ("self", "cls"):
            return False            # attr accesses key under the class
        if name in self.m.mutable_globals:
            return True
        return (self.info.parent is not None
                and name in self.enclosing_locals
                and name not in self.info.local_names)

    def _name_key(self, name: str) -> str:
        if name in self.m.mutable_globals:
            return f"G:{name}"
        return f"L:{self.info.parent}.{name}"

    def _name(self, node: ast.Name, held: frozenset) -> None:
        if isinstance(node.ctx, ast.Load):
            if node.id in self.m.mutable_globals:
                self._rec(f"G:{node.id}", "read", node.lineno, held)
        elif isinstance(node.ctx, (ast.Store, ast.Del)):
            if node.id in self.global_decls and \
                    node.id in self.m.mutable_globals:
                self._rec(f"G:{node.id}", "write", node.lineno, held)

    def _call(self, node: ast.Call, held: frozenset) -> None:
        f = node.func
        handled_func = False
        # threading.Thread(...) — spawn (alias-aware: `import threading as
        # t`, `from threading import Thread` both resolve)
        full = _resolve_dotted(_call_name(f), self.aliases)
        if _is_thread_ctor(full):
            self._spawn_thread(node, chained=False)
            handled_func = True
        elif isinstance(f, ast.Attribute) and f.attr == "start" and \
                isinstance(f.value, ast.Call):
            inner = _resolve_dotted(_call_name(f.value.func), self.aliases)
            if _is_thread_ctor(inner):
                self._spawn_thread(f.value, chained=True)
                handled_func = True
        # pool.submit(fn, ...) / atexit.register(fn, ...)
        if isinstance(f, ast.Attribute) and f.attr == "submit" and node.args:
            tgt = self._resolve_target(node.args[0])
            self.m.spawns.append(Spawn(
                "pool", tgt, ast.unparse(node.args[0]), daemon=True,
                chained_start=False, func=self.info.qualname,
                lineno=node.lineno))
        if full == "atexit.register" and node.args:
            tgt = self._resolve_target(node.args[0])
            self.m.spawns.append(Spawn(
                "atexit", tgt, ast.unparse(node.args[0]), daemon=True,
                chained_start=False, func=self.info.qualname,
                lineno=node.lineno))
        # .join() bookkeeping (thread-leak + closure happens-after edges)
        if _looks_like_thread_join(node):
            self.m.has_thread_join = True
            if self.info.spawn_lines and node.lineno > min(
                    self.info.spawn_lines) and self.info.join_after is None:
                self.info.join_after = node.lineno
        # lock method calls / attr-method mutations / self-calls
        if isinstance(f, ast.Attribute) and not handled_func:
            recv = f.value
            lock_key = self._lock_key_of(recv)
            if lock_key is not None and f.attr in LOCK_METHODS:
                if f.attr == "acquire":
                    self.info.acquires.append(Acquire(
                        lock_key, frozenset(held | self.sticky),
                        self.info.qualname, node.lineno,
                        reentrant=self.m.lock_factory(lock_key) != "Lock"))
                    self.sticky.add(lock_key)
                elif f.attr == "release":
                    self.sticky.discard(lock_key)
                handled_func = True
            elif isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self" and self.info.cls and \
                    not self.m.is_lock_attr(self.info.root_cls, recv.attr):
                # self.A.m(...): mutation or read of attr A
                kind = "write" if f.attr in MUTATOR_METHODS else "read"
                self._rec(self._attr_key(recv.attr), kind, node.lineno,
                          held)
                handled_func = True
            elif isinstance(recv, ast.Name) and \
                    self._is_shared_name(recv.id):
                kind = "write" if f.attr in MUTATOR_METHODS else "read"
                self._rec(self._name_key(recv.id), kind, node.lineno, held)
                handled_func = True
            elif isinstance(recv, ast.Name) and recv.id == "self" and \
                    self.info.cls:
                callee = self._resolve_call(f)
                if callee is not None:
                    self.info.calls.append(
                        (callee, frozenset(held | self.sticky), node.lineno))
                else:
                    self._rec(self._attr_key(f.attr), "read", node.lineno,
                              held)
                handled_func = True
        elif isinstance(f, ast.Name) and not handled_func:
            callee = self._resolve_call(f)
            if callee is not None:
                self.info.calls.append(
                    (callee, frozenset(held | self.sticky), node.lineno))
                handled_func = True
        if not handled_func:
            self.expr(f, held)
        for a in node.args:
            self.expr(a, held)
        for kw in node.keywords:
            self.expr(kw.value, held)

    def _spawn_thread(self, call: ast.Call, chained: bool) -> None:
        target = None
        target_text = "?"
        daemon = False
        for kw in call.keywords:
            if kw.arg == "target":
                target = self._resolve_target(kw.value)
                target_text = ast.unparse(kw.value)
            elif kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
        self.m.spawns.append(Spawn(
            "thread", target, target_text, daemon=daemon,
            chained_start=chained, func=self.info.qualname,
            lineno=call.lineno))   # spawn_lines already filled by pre-scan

    # -- statements ----------------------------------------------------------
    def block(self, stmts: List[ast.stmt], held: frozenset) -> None:
        for st in stmts:
            self.stmt(st, held)

    def stmt(self, node: ast.stmt, held: frozenset) -> None:
        eff = frozenset(held | self.sticky)
        if isinstance(node, ast.With):
            new = set()
            for item in node.items:
                key = self._lock_key_of(item.context_expr)
                if key is not None:
                    self.info.acquires.append(Acquire(
                        key, frozenset(eff | new), self.info.qualname,
                        node.lineno,
                        reentrant=self.m.lock_factory(key) != "Lock"))
                    new.add(key)
                else:
                    self.expr(item.context_expr, eff)
            self.block(node.body, frozenset(eff | new))
        elif isinstance(node, (ast.If, ast.While)):
            # TOCTOU candidate: remember what the test reads and what the
            # suite writes; checks.py judges it once guard sets are known
            pre = len(self.info.accesses)
            self.expr(node.test, eff)
            test_reads = [(a.key, a.locks) for a in self.info.accesses[pre:]
                          if a.kind == "read"]
            pre_body = len(self.info.accesses)
            pre_calls = len(self.info.calls)
            self.block(node.body, frozenset(held))
            body_writes = [a.key for a in self.info.accesses[pre_body:]
                           if a.kind == "write"]
            body_callees = [c for c, _, _ in self.info.calls[pre_calls:]]
            if test_reads:
                self.info.toctous.append(Toctou(
                    self.info.qualname, node.lineno, test_reads,
                    body_writes, body_callees))
            self.block(node.orelse, frozenset(held))
        elif isinstance(node, ast.Try):
            self.block(node.body, frozenset(held))
            for h in node.handlers:
                self.block(h.body, frozenset(held))
            self.block(node.orelse, frozenset(held))
            self.block(node.finalbody, frozenset(held))
        elif isinstance(node, ast.For):
            self.expr(node.iter, eff)
            self.expr(node.target, eff)
            self.block(node.body, frozenset(held))
            self.block(node.orelse, frozenset(held))
        elif isinstance(node, ast.Global):
            self.global_decls.update(node.names)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._nested_defs.add(f"{self.info.qualname}.<locals>.{node.name}")
        elif isinstance(node, ast.ClassDef):
            pass                            # nested classes walked separately
        elif isinstance(node, ast.Return):
            self.expr(node.value, eff)
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.stmt):
                    self.stmt(child, frozenset(held))
                else:
                    self.expr(child, eff)


def _local_names(node) -> Set[str]:
    """Names assigned anywhere in a function body (its locals), args
    included — used to distinguish closure reads from true locals."""
    names: Set[str] = set()
    args = node.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs
              + ([args.vararg] if args.vararg else [])
              + ([args.kwarg] if args.kwarg else [])):
        names.add(a.arg)
    for sub in ast.walk(node):
        if sub is not node and isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
            names.add(sub.id)
        elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
            names.add(sub.name)
    return names


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _walk_functions(model: ModuleModel, tree: ast.Module,
                    aliases: Dict[str, str]) -> None:
    """Register every function (any nesting) and walk its body."""

    def register(node, qual, cls, parent, enclosing_locals):
        root = model.root_class(cls)
        info = FuncInfo(qualname=qual, cls=cls, root_cls=root, node=node,
                        parent=parent)
        info.local_names = _local_names(node)
        model.funcs[qual] = info
        walker = _FuncWalker(model, info, aliases, enclosing_locals)
        # pre-scan for spawn/start lines so `prestart` classification works
        # on the main walk: the happens-before boundary is the first
        # .start() AFTER a Thread construction — writes between construct
        # and start (publish-then-start) are still pre-publication. Nested
        # defs are excluded (their spawns are their own).
        constructs: List[int] = []
        starts: List[int] = []

        def prescan(n):
            for sub in ast.iter_child_nodes(n):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda, ast.ClassDef)):
                    continue
                if isinstance(sub, ast.Call):
                    if _is_thread_ctor(
                            _resolve_dotted(_call_name(sub.func), aliases)):
                        constructs.append(sub.lineno)
                    elif isinstance(sub.func, ast.Attribute) and \
                            sub.func.attr == "start":
                        starts.append(sub.lineno)
                prescan(sub)

        prescan(node)
        info.spawn_lines = sorted(set(constructs))
        if constructs:
            after = [ln for ln in starts if ln >= min(constructs)]
            info.prestart_line = min(after) if after else min(constructs)
        walker.block(node.body, frozenset())
        # recurse into nested defs/classes
        for sub in node.body:
            descend(sub, qual, cls, info.local_names)

    def descend(node, parent_qual, cls, enclosing_locals):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if parent_qual is None:
                qual = node.name if cls is None else f"{cls}.{node.name}"
                register(node, qual, cls, None, enclosing_locals)
            else:
                qual = f"{parent_qual}.<locals>.{node.name}"
                register(node, qual, cls, parent_qual, enclosing_locals)
        elif isinstance(node, ast.ClassDef):
            inner_cls = node.name
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if parent_qual is None:
                        register(sub, f"{inner_cls}.{sub.name}", inner_cls,
                                 None, set())
                    else:
                        register(sub,
                                 f"{parent_qual}.<locals>."
                                 f"{inner_cls}.{sub.name}",
                                 inner_cls, parent_qual, enclosing_locals)
                elif isinstance(sub, ast.ClassDef):
                    descend(sub, parent_qual, inner_cls, enclosing_locals)
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    descend(child, parent_qual, cls, enclosing_locals)

    # module-level statements: spawns (atexit.register at import time) and
    # top-level defs
    mod_info = FuncInfo(qualname="<module>", cls=None, root_cls=None,
                        node=tree, parent=None)
    model.funcs["<module>"] = mod_info
    walker = _FuncWalker(model, mod_info, aliases, set())
    for st in tree.body:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            descend(st, None, None, set())
        else:
            walker.stmt(st, frozenset())


def _match_root(qual: str, cls: Optional[str], patterns) -> bool:
    for p in patterns:
        if p == qual:
            return True
        if p.endswith(".*") and qual.startswith(p[:-2] + "."):
            return True
        if p.endswith(".*") and cls == p[:-2]:
            return True
    return False


def build_module_model(source: str, relpath: str = "<string>",
                       extra_roots=()) -> ModuleModel:
    """Parse ``source`` and build the full thread model: entry discovery,
    role propagation, caller-held lock inheritance.

    ``extra_roots``: qualname patterns (exact, or ``Class.*``) for
    functions that run on threads started OUTSIDE this module — the
    cross-module edges the per-module AST cannot see (the lint gate's
    ``THREAD_ROOTS``)."""
    tree = ast.parse(source, filename=relpath)
    model = ModuleModel(relpath)
    p1 = _Phase1(model)
    p1.visit(tree)
    _walk_functions(model, tree, p1.aliases)

    # -- thread-role seeding ------------------------------------------------
    targets: Dict[str, str] = {}            # qualname -> entry label
    for sp in model.spawns:
        if sp.target and sp.target in model.funcs:
            targets.setdefault(sp.target, f"thread:{sp.target}")
    # handler classes: every method runs on a per-connection server thread
    # (match by FuncInfo.cls so classes nested inside functions count too)
    handler_classes = {cls for cls, bases in model.classes.items()
                       if any(b in HANDLER_BASES for b in bases)}
    if handler_classes:
        for qual, info in model.funcs.items():
            if info.cls in handler_classes:
                targets.setdefault(qual, f"thread:{info.cls}")
    for qual, info in model.funcs.items():
        if _match_root(qual, info.cls, extra_roots):
            targets.setdefault(qual, f"thread:{qual}")

    for qual, label in targets.items():
        info = model.funcs.get(qual)
        if info is not None:
            info.is_target = True
            info.roles.add(label)

    # main role: everything not referenced exclusively as a thread target
    for qual, info in model.funcs.items():
        if not info.is_target:
            info.roles.add(MAIN_ROLE)

    # nested non-target functions inherit their definer's roles (closures
    # run where their definer runs — or wherever the definer hands them)
    changed = True
    while changed:
        changed = False
        for qual, info in model.funcs.items():
            if info.parent and not info.is_target:
                parent = model.funcs.get(info.parent)
                if parent and not parent.roles <= info.roles:
                    info.roles |= parent.roles
                    changed = True
            # roles flow caller -> callee
            for callee, _, _ in info.calls:
                ci = model.funcs.get(callee)
                if ci is not None and not info.roles <= ci.roles:
                    ci.roles |= info.roles
                    changed = True

    # -- caller-held lock inheritance ----------------------------------------
    # If EVERY in-module call site of g holds lock L (directly or itself
    # inherited), g's accesses are effectively guarded by L — the
    # ``_row()``-called-under-``self._lock`` pattern. Entry points
    # (targets, roots, <module>) inherit nothing.
    callers: Dict[str, List[Tuple[str, frozenset]]] = {}
    for qual, info in model.funcs.items():
        for callee, held, _ in info.calls:
            callers.setdefault(callee, []).append((qual, held))
    inherited: Dict[str, Optional[frozenset]] = {
        q: None for q in model.funcs}       # None = unknown (top)
    for q, info in model.funcs.items():
        if info.is_target or q == "<module>" or q not in callers:
            inherited[q] = frozenset()
    for _ in range(len(model.funcs) + 1):
        changed = False
        for q in model.funcs:
            if inherited[q] is not None and not callers.get(q):
                continue
            if model.funcs[q].is_target:
                continue
            sets = []
            for caller, held in callers.get(q, ()):
                ih = inherited.get(caller)
                sets.append(held | (ih or frozenset()))
            if not sets:
                continue
            new = frozenset.intersection(*[frozenset(s) for s in sets])
            if new != inherited[q]:
                inherited[q] = new
                changed = True
        if not changed:
            break
    for q, info in model.funcs.items():
        extra = inherited.get(q) or frozenset()
        if extra:
            for a in info.accesses:
                a.locks = frozenset(a.locks | extra)
            for acq in info.acquires:
                acq.held = frozenset(acq.held | extra)
            info.toctous = [dataclasses.replace(
                t, test_reads=[(k, frozenset(l | extra))
                               for k, l in t.test_reads])
                for t in info.toctous]
    return model
