"""paddle_tpu.static.concurrency — PT-RACE: whole-package static
concurrency analysis for the threaded host stack.

PR 1 gave the DEVICE graph a lint layer (``static/analysis`` +
``tools/lint_graph.py``); this package is the same idea for the HOST side —
the ~15 thread entry points and ~12 locks that keep production serving
alive (supervisor step watchdogs, fleet ``parallel_step``, metrics/HTTP
server threads, heartbeat loops, async checkpoint writers, rpc handler
pools). It is pure ``ast``: analyzing a module never imports it, never
touches jax, and sweeps the whole package in well under a second.

Pipeline (one module at a time):

1. :func:`~paddle_tpu.static.concurrency.thread_model.build_module_model`
   — discover thread entry points (``threading.Thread``, executor
   ``submit``, ``atexit``, socketserver/http handler classes, plus
   caller-supplied cross-module roots), propagate thread roles through
   the intra-module call graph, and track the held-lock set at every
   state access (``with self._lock:`` nesting, ``acquire``/``release``,
   caller-held inheritance for helpers only ever called under a lock).
2. :func:`~paddle_tpu.static.concurrency.shared_state.infer_shared_state`
   — state keys (instance attrs / module globals / closure vars) touched
   from more than one thread role, with happens-before exclusions
   (``__init__``, pre-``start()`` writes, join-after-spawn closures).
3. :func:`~paddle_tpu.static.concurrency.checks.run_checks` — the
   PT-RACE-001..005 rule catalogue (docs/STATIC_ANALYSIS.md), emitting
   the same :class:`~paddle_tpu.static.analysis.diagnostics.Diagnostic`
   objects the graph analyzers use, each with a stable line-number-free
   ``finding_id`` for the lint gate's reviewed baseline file.

CI gate: ``tools/lint_concurrency.py`` (whole-package sweep + seeded
defect ``--selftest``), registered in tests/test_ci_gates.py beside
lint_graph / fault_drill / scrape_metrics.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..analysis.diagnostics import AnalysisReport, Diagnostic, Severity
from .checks import finding_id, run_checks
from .shared_state import SharedKey, infer_shared_state
from .thread_model import (MAIN_ROLE, Access, ModuleModel, Spawn,
                           build_module_model)

__all__ = [
    "analyze_source", "analyze_file", "analyze_paths",
    "build_module_model", "infer_shared_state", "run_checks",
    "finding_id", "ModuleModel", "SharedKey",
]


def analyze_source(source: str, relpath: str = "<string>",
                   extra_roots: Sequence[str] = (),
                   suppress: Sequence[str] = ()) -> AnalysisReport:
    """Analyze one module's source text; returns an
    :class:`~paddle_tpu.static.analysis.diagnostics.AnalysisReport`."""
    model = build_module_model(source, relpath, extra_roots=extra_roots)
    findings = [d for d in run_checks(model)
                if d.code not in set(suppress)]
    return AnalysisReport(findings)


def analyze_file(path: str, relpath: Optional[str] = None,
                 extra_roots: Sequence[str] = (),
                 suppress: Sequence[str] = ()) -> AnalysisReport:
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    return analyze_source(src, relpath or path, extra_roots=extra_roots,
                          suppress=suppress)


def _iter_py_files(root: str) -> Iterable[str]:
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__",))
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def analyze_paths(paths: Sequence[str], base: Optional[str] = None,
                  thread_roots: Optional[Dict[str, Sequence[str]]] = None,
                  suppress: Sequence[str] = ()
                  ) -> Tuple[AnalysisReport, List[str]]:
    """Whole-package sweep: analyze every ``.py`` under ``paths``.

    ``thread_roots`` maps a base-relative path to extra thread-root
    qualname patterns for that module (cross-module thread entries the
    per-module AST cannot see). Returns ``(report, analyzed_relpaths)``.
    """
    report = AnalysisReport()
    analyzed: List[str] = []
    roots = thread_roots or {}
    for p in paths:
        for path in _iter_py_files(p):
            rel = (os.path.relpath(path, base) if base else path)
            rel = rel.replace(os.sep, "/")
            try:
                report.extend(analyze_file(
                    path, relpath=rel,
                    extra_roots=roots.get(rel, ()), suppress=suppress))
            except SyntaxError as e:
                d = Diagnostic(code="PT-RACE-000", severity=Severity.ERROR,
                               message=f"module failed to parse: {e}",
                               source=f"{rel}:{getattr(e, 'lineno', 0)}",
                               analyzer="concurrency")
                d.finding_id = finding_id("PT-RACE-000", rel, "<module>",
                                          "syntax")
                report.extend([d])
            analyzed.append(rel)
    return report, analyzed
