"""paddle_tpu.static.nn — static-graph layer helpers (reference:
python/paddle/static/nn/common.py fc/conv2d/batch_norm/embedding).

Each helper instantiates the dygraph layer (eager parameters — our "startup
program" is eager initialization) and applies it to the symbolic Variable, so
the op recording flows through the one op registry.
"""

from __future__ import annotations

from ..core.static_graph import Variable

__all__ = ["fc", "embedding", "conv2d", "batch_norm", "dropout"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from .. import nn

    in_features = 1
    for s in x.shape[num_flatten_dims:]:
        if s is None or int(s) < 0:
            raise ValueError(
                f"static.nn.fc: feature dims of '{getattr(x, 'name', 'x')}' must "
                f"be static, got shape {x.shape} (only the leading "
                f"{num_flatten_dims} batch dim(s) may be dynamic)")
        in_features *= int(s)
    layer = nn.Linear(in_features, size)
    if x.ndim > num_flatten_dims + 1:
        from .. import tensor as T

        x = T.reshape(x, list(x.shape[:num_flatten_dims]) + [in_features])
    out = layer(x)
    if activation:
        out = getattr(nn.functional, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, param_attr=None,
              dtype="float32"):
    from .. import nn

    layer = nn.Embedding(size[0], size[1], padding_idx=padding_idx)
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, data_format="NCHW"):
    from .. import nn

    in_ch = int(input.shape[1 if data_format == "NCHW" else -1])
    layer = nn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                      padding=padding, dilation=dilation, groups=groups,
                      data_format=data_format)
    return layer(input)


def batch_norm(input, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW"):
    from .. import nn

    ch = int(input.shape[1 if data_layout == "NCHW" else -1])
    layer = nn.BatchNorm2D(ch, momentum=momentum, epsilon=epsilon,
                           data_format=data_layout)
    if is_test:
        layer.eval()
    return layer(input)


def dropout(x, dropout_prob=0.5, is_test=False):
    from ..nn import functional as F

    return F.dropout(x, p=dropout_prob, training=not is_test)
