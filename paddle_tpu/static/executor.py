"""Static-graph Executor — replay a recorded Program inside jax.jit.

Parity anchors: the reference's StandaloneExecutor + PirInterpreter
(/root/reference/paddle/fluid/framework/new_executor/standalone_executor.h:34,
pir_interpreter.cc:1603 TraceRunImpl) and the Python wrapper with its plan cache
(/root/reference/python/paddle/base/executor.py:1285 run, :847 _ExecutorCache).

TPU-native redesign: no instruction scheduler, no per-op kernel launches, no
GC/event machinery — the whole dependency-pruned op list is traced once into a
single XLA program (jit) and cached per (program version, feed signature,
fetch set). Async multi-stream execution, instruction reordering and memory
planning are XLA's job. Training programs (Optimizer.minimize on a symbolic
loss) compute parameter gradients with jax.value_and_grad over the same replay
trace, then apply the eager optimizer — the analogue of the reference's
appended backward + optimizer ops.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..core import static_graph
from ..core.static_graph import Program, Variable
from ..core.tensor import Tensor

__all__ = ["Executor", "Scope", "global_scope"]


class Scope:
    """Name → Tensor map (reference: paddle/fluid/framework/scope.h).

    ``var(name)`` keeps Paddle's lenient contract — an unknown name silently
    materializes a ()-shaped float32 zero — but every such lazy materialization
    is tracked so the analyzer can flag reads of never-written variables
    (PT-SCOPE-001). ``var(name, strict=True)`` raises instead."""

    def __init__(self):
        self._vars: Dict[str, Tensor] = {}
        self._written: set = set()
        self._lazy_reads: Dict[str, int] = {}

    def var(self, name, strict: bool = False):
        # _vars is populated only by set() (-> _written) or the lazy branch
        # below (-> _lazy_reads), so this single check covers both
        if name not in self._written:
            if strict:
                raise KeyError(
                    f"scope variable '{name}' was never written "
                    f"(strict lookup); known: {sorted(self._written)[:10]}")
            self._lazy_reads[name] = self._lazy_reads.get(name, 0) + 1
            self._vars.setdefault(name, Tensor(np.zeros((), np.float32)))
        return self._vars[name]

    def find_var(self, name):
        return self._vars.get(name)

    def set(self, name, t: Tensor):
        self._written.add(name)
        self._vars[name] = t


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def _is_stochastic_type(op_type) -> bool:
    return any(k in (op_type or "") for k in static_graph.STOCHASTIC_KEYWORDS)


class Executor:
    """``Executor(place).run(program, feed, fetch_list)``."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[Any, Any] = {}

    def close(self):
        self._cache.clear()

    def cache_signatures(self):
        """Introspection for the trace-hazard linter: one
        ``(program_id, version, feed_sig, fetch_ids, train)`` tuple per
        compiled plan. A program id accumulating many distinct feed signatures
        is recompiling every step (PT-TRACE-001)."""
        return list(self._cache.keys())

    # -- replay construction ------------------------------------------------
    def _build(self, program: Program, feed_vars, fetch_vars, train: bool):
        from .passes import live_ops, resolve_alias

        aliases = getattr(program, "_aliases", {})
        targets = list(fetch_vars) + ([program._loss] if train else [])
        ops = live_ops(program.global_block().ops,
                       [id(v) for v in targets], aliases)

        # ordered distinct captured eager tensors
        caps: List[Tensor] = []
        cap_pos: Dict[int, int] = {}
        for op in ops:
            for t in op.captured:
                if id(t) not in cap_pos:
                    cap_pos[id(t)] = len(caps)
                    caps.append(t)
        folded = getattr(program, "_folded", {})  # id(var) -> Tensor constant

        diff_pos: Dict[int, int] = {}
        diff_params: List[Tensor] = []
        if train:
            for p in program._optimizer._static_params:
                if id(p) in cap_pos:
                    diff_pos[id(p)] = len(diff_params)
                    diff_params.append(p)

        has_stochastic = any(_is_stochastic_type(op.type) for op in ops)
        feed_ids = [id(v) for v in feed_vars]
        # chain-resolve like live_ops does, so a multi-hop alias map (stacked
        # view passes) fetches the true canonical producer's value
        fetch_ids = [resolve_alias(aliases, id(v)) for v in fetch_vars]

        def lookup(env, vid):
            if vid in env:
                return env[vid]
            if vid in folded:
                return folded[vid]._data
            raise KeyError(f"fetch target {vid} was never computed")

        def replay(feed_arrs, cap_arrs, diff_arrs, seed):
            """seed: traced scalar; stochastic ops draw keys from it through
            the rng_guard context, so every Executor.run gets fresh randomness
            (dropout masks etc.) without retracing."""
            import contextlib

            from ..framework.random import rng_guard

            env: Dict[int, Any] = dict(zip(feed_ids, feed_arrs))

            def resolve(a):
                if isinstance(a, Variable):
                    vid = resolve_alias(aliases, id(a))
                    if vid in env:
                        return env[vid]
                    if vid in folded:
                        return folded[vid]._data
                    raise KeyError(
                        f"Variable '{a.name}' has no value — is it a feed you "
                        f"forgot to pass?")
                if isinstance(a, Tensor):
                    if id(a) in diff_pos:
                        return diff_arrs[diff_pos[id(a)]]
                    return cap_arrs[cap_pos[id(a)]]
                return a

            guard = (rng_guard(jax.random.key(seed)) if has_stochastic
                     else contextlib.nullcontext())
            with guard:
                for op in ops:
                    out = op.fn(*[resolve(a) for a in op.args], **op.kwargs)
                    if isinstance(out, (tuple, list)):
                        for v, o in zip(op.outputs, out):
                            env[id(v)] = o
                    else:
                        env[id(op.outputs[0])] = out
            return env

        if not train:
            def fwd(feed_arrs, cap_arrs, seed):
                env = replay(feed_arrs, cap_arrs, [], seed)
                return [lookup(env, i) for i in fetch_ids]

            return jax.jit(fwd), caps, diff_params

        loss_id = resolve_alias(aliases, id(program._loss))

        def loss_and_fetch(diff_arrs, feed_arrs, cap_arrs, seed):
            env = replay(feed_arrs, cap_arrs, diff_arrs, seed)
            return lookup(env, loss_id), [lookup(env, i) for i in fetch_ids]

        vg = jax.value_and_grad(loss_and_fetch, has_aux=True)

        def train_fn(feed_arrs, cap_arrs, diff_arrs, seed):
            (loss, fetches), grads = vg(diff_arrs, feed_arrs, cap_arrs, seed)
            return fetches, grads

        return jax.jit(train_fn), caps, diff_params

    # -- public API ---------------------------------------------------------
    def run(self, program: Optional[Program] = None, feed: Optional[Dict] = None,
            fetch_list: Optional[Sequence] = None, return_numpy: bool = True,
            scope: Optional[Scope] = None, **kwargs):
        from . import CompiledProgram

        if isinstance(program, CompiledProgram):
            program._ensure_optimized()
            program = program._program
        if program is None:
            program = static_graph.default_main_program()
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        if program.num_ops == 0 and not fetch_list:
            # startup program: parameter init already ran eagerly (dygraph-style
            # initializers) — nothing to execute. Cf. reference startup programs.
            # (With a fetch_list the normal path still serves folded constants
            # and feed variables out of an op-free program.)
            return []

        by_name = {v.name: v for v in program.list_vars()}
        fetch_vars = [by_name[f] if isinstance(f, str) else f for f in fetch_list]
        feed_vars, feed_arrs = [], []
        for k, val in feed.items():
            v = by_name.get(k)
            if v is None:
                raise KeyError(f"feed '{k}' is not a variable of this program")
            feed_vars.append(v)
            feed_arrs.append(jax.numpy.asarray(
                val._data if isinstance(val, Tensor) else val, dtype=v._data.dtype))

        train = program._optimizer is not None and program._loss is not None
        sig = tuple((v.name, tuple(a.shape), str(a.dtype))
                    for v, a in zip(feed_vars, feed_arrs))
        key = (id(program), program._version, sig,
               tuple(id(v) for v in fetch_vars), train)
        if key not in self._cache:
            self._cache[key] = self._build(program, feed_vars, fetch_vars, train)
        fn, caps, diff_params = self._cache[key]
        cap_arrs = [t._data for t in caps]
        from ..framework.random import next_host_seed

        seed = np.uint32(next_host_seed())  # fresh per run, paddle.seed-reproducible

        if train:
            fetches, grads = fn(feed_arrs, cap_arrs,
                                [p._data for p in diff_params], seed)
            for p, g in zip(diff_params, grads):
                p._grad = Tensor(g)
            opt = program._optimizer
            opt.step()
            opt.clear_grad()
        else:
            fetches = fn(feed_arrs, cap_arrs, seed)

        sc = scope or _global_scope
        for v, a in zip(fetch_vars, fetches):
            sc.set(v.name, Tensor(a))
        if return_numpy:
            return [np.asarray(a) for a in fetches]
        return [Tensor(a) for a in fetches]
