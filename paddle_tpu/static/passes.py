"""Graph-level passes over a recorded Program.

Parity anchors: the reference's PIR pass infrastructure
(/root/reference/paddle/pir/include/pass/pass_manager.h:35) and the general
transforms it ships (fluid/pir/transforms/general/: dead_code_elimination_pass.cc,
constant_folding_pass.cc, common_subexpression_elimination_pass.cc).

TPU-native scope note: XLA already performs fusion, layout assignment, scheduling
and most algebraic simplification after jit tracing — the passes kept here are the
ones with value *before* tracing: shrinking the recorded op list (DCE), hoisting
feed-independent subgraphs out of the per-step program (constant folding — the
analogue of the reference folding weights through transformations), and merging
duplicate recorded calls (CSE) so the jit trace itself is smaller.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.static_graph import Operation, Program, Variable
from ..core.tensor import Tensor

__all__ = ["Pass", "PassManager", "DeadCodeEliminationPass",
           "ConstantFoldingPass", "CommonSubexpressionEliminationPass",
           "apply_default_passes", "live_ops", "resolve_alias", "cse_key"]

from ..core.static_graph import STOCHASTIC_KEYWORDS


def _is_stochastic(op: Operation) -> bool:
    return any(k in (op.type or "") for k in STOCHASTIC_KEYWORDS)


def resolve_alias(aliases, vid):
    """Follow an alias chain to its canonical id. CSE flattens as it inserts,
    but view-op chains built elsewhere (or merged alias maps) may be multi-hop
    — a one-step lookup would drop the producing op from the live set."""
    hops = 0
    while vid in aliases and aliases[vid] != vid:
        vid = aliases[vid]
        hops += 1
        if hops > len(aliases):  # defensive: cyclic map
            break
    return vid


def live_ops(ops, target_ids, aliases=None):
    """Reverse liveness sweep: the subsequence of ``ops`` whose outputs reach
    ``target_ids`` (ids resolved through ``aliases``, chains included). Shared
    by the DCE pass, the Executor's replay builder, and the graph-health
    analyzer."""
    aliases = aliases or {}
    needed = {resolve_alias(aliases, t) for t in target_ids}
    keep = []
    for op in reversed(ops):
        if any(id(o) in needed for o in op.outputs):
            keep.append(op)
            needed.update(resolve_alias(aliases, id(v)) for v in op.inputs)
    keep.reverse()
    return keep


class Pass:
    name = "pass"
    # transform passes mutate the program; analysis passes (static/analysis)
    # set mutates=False — they report findings and must not invalidate the
    # Executor's compiled-plan cache
    mutates = True

    def apply(self, program: Program) -> int:
        """Mutate program; return number of changes."""
        raise NotImplementedError


class PassManager:
    """Ordered pass pipeline (cf. pir::PassManager::Run). Composes transform
    passes (DCE/CSE/fold) with non-mutating AnalysisPass instances; the stat
    for an analysis pass is its finding count."""

    def __init__(self, passes: Optional[Sequence[Pass]] = None):
        self.passes: List[Pass] = list(passes or [])

    def add_pass(self, p: Pass):
        self.passes.append(p)
        return self

    def run(self, program: Program) -> Dict[str, int]:
        stats = {}
        for p in self.passes:
            stats[p.name] = p.apply(program)
            if p.mutates:
                program._version += 1
        return stats


class DeadCodeEliminationPass(Pass):
    """Drop ops whose outputs never reach ``targets`` (or any later op)."""

    name = "dead_code_elimination"

    def __init__(self, targets: Optional[Sequence[Variable]] = None):
        self.targets = targets

    def apply(self, program: Program) -> int:
        blk = program.global_block()
        if self.targets is None:
            return 0  # without targets every terminal op is live
        targets = [id(v) for v in self.targets]
        if program._loss is not None:
            targets.append(id(program._loss))
        keep = live_ops(blk.ops, targets, getattr(program, "_aliases", None))
        removed = len(blk.ops) - len(keep)
        blk.ops = keep
        return removed


class ConstantFoldingPass(Pass):
    """Evaluate feed-independent, non-stochastic ops once; replace their outputs
    with captured constants (reference: constant_folding_pass.cc)."""

    name = "constant_folding"

    def apply(self, program: Program) -> int:
        blk = program.global_block()
        folded: Dict[int, Tensor] = getattr(program, "_folded", {})
        kept, n = [], 0
        for op in blk.ops:
            # foldable: deterministic, every symbolic input already folded
            # (feeds are never folded, so feed-derived ops stay), and no
            # captured eager Tensor at all — captures are late-bound by
            # contract (Operation docstring) and folding would snapshot them
            foldable = (
                not _is_stochastic(op)
                and all(id(v) in folded for v in op.inputs)
                and not op.captured
            )
            if foldable:
                def resolve(a):
                    if isinstance(a, Variable):
                        return folded[id(a)]._data
                    if isinstance(a, Tensor):
                        return a._data
                    return a

                out = op.fn(*[resolve(a) for a in op.args], **op.kwargs)
                outs = out if isinstance(out, (tuple, list)) else [out]
                for v, o in zip(op.outputs, outs):
                    folded[id(v)] = Tensor(o)
                n += 1
            else:
                kept.append(op)
        blk.ops = kept
        program._folded = folded
        return n


def _closure_fingerprint(fn):
    """Hashable description of a python closure, or None if unfingerprintable.

    Recorded op fns are often per-call lambdas (e.g. ``lambda x: x.astype(dt)``);
    two recordings of the same source op are mergeable only when their captured
    cells hold equal simple values.
    """
    code = getattr(fn, "__code__", None)
    if code is None:
        # closure-free shared callable (jnp ufunc, PjitFunction): identity is
        # the fingerprint — same object + same inputs => same value
        return ("id", id(fn))
    cells = ()
    if fn.__closure__:
        vals = []
        for c in fn.__closure__:
            v = c.cell_contents
            if isinstance(v, (int, float, bool, str, bytes, tuple, type(None))):
                vals.append(v)
            elif isinstance(v, np.dtype) or type(v).__module__ == "jax.numpy":
                vals.append(str(v))
            else:
                return None
        cells = tuple(vals)
    return (code.co_code, code.co_consts if all(
        isinstance(c, (int, float, bool, str, bytes, type(None), tuple))
        for c in code.co_consts) else None, cells)


def cse_key(op: Operation, aliases: Dict[int, int]):
    """Hashable merge key for an op, or None when the op must never merge
    (stochastic, unfingerprintable closure, array-literal args). Shared by the
    CSE pass and the graph-health duplicate-subgraph reporter."""
    if _is_stochastic(op):
        return None
    fp = _closure_fingerprint(op.fn)
    if fp is None:
        return None
    try:
        kw = tuple(sorted((k, repr(v)) for k, v in op.kwargs.items()))
    except Exception:
        return None
    in_key = []
    for a in op.args:
        if isinstance(a, Variable):
            in_key.append(("v", resolve_alias(aliases, id(a))))
        elif isinstance(a, Tensor):
            in_key.append(("c", id(a)))
        elif isinstance(a, (int, float, bool, str, bytes, type(None))):
            # key the TYPE too: True == 1 == 1.0 under dict equality, but
            # merging ops whose scalar differs only in type changes dtypes
            in_key.append(("l", type(a).__name__, a))
        else:
            # repr() of arrays/objects can truncate ("...") and collide
            # across different values — never CSE on it
            return None
    return (op.type, fp, tuple(in_key), kw)


class CommonSubexpressionEliminationPass(Pass):
    """Merge duplicate recorded ops (same fn fingerprint, same inputs, same
    kwargs) — reference: common_subexpression_elimination_pass.cc. Duplicate
    outputs become aliases resolved by the Executor."""

    name = "cse"

    def apply(self, program: Program) -> int:
        blk = program.global_block()
        aliases: Dict[int, int] = getattr(program, "_aliases", {})
        seen: Dict[tuple, Operation] = {}
        kept, n = [], 0
        for op in blk.ops:
            key = cse_key(op, aliases)
            if key is None:
                kept.append(op)
                continue
            prev = seen.get(key)
            if prev is not None and len(prev.outputs) == len(op.outputs):
                for dup, canon in zip(op.outputs, prev.outputs):
                    aliases[id(dup)] = resolve_alias(aliases, id(canon))
                n += 1
            else:
                seen[key] = op
                kept.append(op)
        blk.ops = kept
        program._aliases = aliases
        return n


def apply_default_passes(program: Program, targets=None) -> Dict[str, int]:
    pm = PassManager([
        CommonSubexpressionEliminationPass(),
        ConstantFoldingPass(),
        DeadCodeEliminationPass(targets),
    ])
    return pm.run(program)
