"""Graph-level passes over a recorded Program.

Parity anchors: the reference's PIR pass infrastructure
(/root/reference/paddle/pir/include/pass/pass_manager.h:35) and the general
transforms it ships (fluid/pir/transforms/general/: dead_code_elimination_pass.cc,
constant_folding_pass.cc, common_subexpression_elimination_pass.cc).

TPU-native scope note: XLA already performs fusion, layout assignment, scheduling
and most algebraic simplification after jit tracing — the passes kept here are the
ones with value *before* tracing: shrinking the recorded op list (DCE), hoisting
feed-independent subgraphs out of the per-step program (constant folding — the
analogue of the reference folding weights through transformations), and merging
duplicate recorded calls (CSE) so the jit trace itself is smaller.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.static_graph import Operation, Program, Variable
from ..core.tensor import Tensor

__all__ = ["Pass", "PassManager", "DeadCodeEliminationPass",
           "ConstantFoldingPass", "CommonSubexpressionEliminationPass",
           "apply_default_passes"]

from ..core.static_graph import STOCHASTIC_KEYWORDS


def _is_stochastic(op: Operation) -> bool:
    return any(k in (op.type or "") for k in STOCHASTIC_KEYWORDS)


def live_ops(ops, target_ids, aliases=None):
    """Reverse liveness sweep: the subsequence of ``ops`` whose outputs reach
    ``target_ids`` (ids pre-resolved through ``aliases``). Shared by the DCE
    pass and the Executor's replay builder."""
    aliases = aliases or {}
    needed = {aliases.get(t, t) for t in target_ids}
    keep = []
    for op in reversed(ops):
        if any(id(o) in needed for o in op.outputs):
            keep.append(op)
            needed.update(aliases.get(id(v), id(v)) for v in op.inputs)
    keep.reverse()
    return keep


class Pass:
    name = "pass"

    def apply(self, program: Program) -> int:
        """Mutate program; return number of changes."""
        raise NotImplementedError


class PassManager:
    """Ordered pass pipeline (cf. pir::PassManager::Run)."""

    def __init__(self, passes: Optional[Sequence[Pass]] = None):
        self.passes: List[Pass] = list(passes or [])

    def add_pass(self, p: Pass):
        self.passes.append(p)
        return self

    def run(self, program: Program) -> Dict[str, int]:
        stats = {}
        for p in self.passes:
            stats[p.name] = p.apply(program)
            program._version += 1
        return stats


class DeadCodeEliminationPass(Pass):
    """Drop ops whose outputs never reach ``targets`` (or any later op)."""

    name = "dead_code_elimination"

    def __init__(self, targets: Optional[Sequence[Variable]] = None):
        self.targets = targets

    def apply(self, program: Program) -> int:
        blk = program.global_block()
        if self.targets is None:
            return 0  # without targets every terminal op is live
        targets = [id(v) for v in self.targets]
        if program._loss is not None:
            targets.append(id(program._loss))
        keep = live_ops(blk.ops, targets, getattr(program, "_aliases", None))
        removed = len(blk.ops) - len(keep)
        blk.ops = keep
        return removed


class ConstantFoldingPass(Pass):
    """Evaluate feed-independent, non-stochastic ops once; replace their outputs
    with captured constants (reference: constant_folding_pass.cc)."""

    name = "constant_folding"

    def apply(self, program: Program) -> int:
        blk = program.global_block()
        folded: Dict[int, Tensor] = getattr(program, "_folded", {})
        kept, n = [], 0
        for op in blk.ops:
            # foldable: deterministic, every symbolic input already folded
            # (feeds are never folded, so feed-derived ops stay), and no
            # captured eager Tensor at all — captures are late-bound by
            # contract (Operation docstring) and folding would snapshot them
            foldable = (
                not _is_stochastic(op)
                and all(id(v) in folded for v in op.inputs)
                and not op.captured
            )
            if foldable:
                def resolve(a):
                    if isinstance(a, Variable):
                        return folded[id(a)]._data
                    if isinstance(a, Tensor):
                        return a._data
                    return a

                out = op.fn(*[resolve(a) for a in op.args], **op.kwargs)
                outs = out if isinstance(out, (tuple, list)) else [out]
                for v, o in zip(op.outputs, outs):
                    folded[id(v)] = Tensor(o)
                n += 1
            else:
                kept.append(op)
        blk.ops = kept
        program._folded = folded
        return n


def _closure_fingerprint(fn):
    """Hashable description of a python closure, or None if unfingerprintable.

    Recorded op fns are often per-call lambdas (e.g. ``lambda x: x.astype(dt)``);
    two recordings of the same source op are mergeable only when their captured
    cells hold equal simple values.
    """
    code = getattr(fn, "__code__", None)
    if code is None:
        # closure-free shared callable (jnp ufunc, PjitFunction): identity is
        # the fingerprint — same object + same inputs => same value
        return ("id", id(fn))
    cells = ()
    if fn.__closure__:
        vals = []
        for c in fn.__closure__:
            v = c.cell_contents
            if isinstance(v, (int, float, bool, str, bytes, tuple, type(None))):
                vals.append(v)
            elif isinstance(v, np.dtype) or type(v).__module__ == "jax.numpy":
                vals.append(str(v))
            else:
                return None
        cells = tuple(vals)
    return (code.co_code, code.co_consts if all(
        isinstance(c, (int, float, bool, str, bytes, type(None), tuple))
        for c in code.co_consts) else None, cells)


class CommonSubexpressionEliminationPass(Pass):
    """Merge duplicate recorded ops (same fn fingerprint, same inputs, same
    kwargs) — reference: common_subexpression_elimination_pass.cc. Duplicate
    outputs become aliases resolved by the Executor."""

    name = "cse"

    def apply(self, program: Program) -> int:
        blk = program.global_block()
        aliases: Dict[int, int] = getattr(program, "_aliases", {})
        seen: Dict[tuple, Operation] = {}
        kept, n = [], 0
        for op in blk.ops:
            if _is_stochastic(op):
                kept.append(op)
                continue
            fp = _closure_fingerprint(op.fn)
            if fp is None:
                kept.append(op)
                continue
            try:
                kw = tuple(sorted((k, repr(v)) for k, v in op.kwargs.items()))
            except Exception:
                kept.append(op)
                continue
            in_key = []
            for a in op.args:
                if isinstance(a, Variable):
                    in_key.append(("v", aliases.get(id(a), id(a))))
                elif isinstance(a, Tensor):
                    in_key.append(("c", id(a)))
                elif isinstance(a, (int, float, bool, str, bytes, type(None))):
                    in_key.append(("l", a))
                else:
                    # repr() of arrays/objects can truncate ("...") and collide
                    # across different values — never CSE on it
                    in_key = None
                    break
            if in_key is None:
                kept.append(op)
                continue
            in_key = tuple(in_key)
            key = (op.type, fp, in_key, kw)
            prev = seen.get(key)
            if prev is not None and len(prev.outputs) == len(op.outputs):
                for dup, canon in zip(op.outputs, prev.outputs):
                    aliases[id(dup)] = aliases.get(id(canon), id(canon))
                n += 1
            else:
                seen[key] = op
                kept.append(op)
        blk.ops = kept
        program._aliases = aliases
        return n


def apply_default_passes(program: Program, targets=None) -> Dict[str, int]:
    pm = PassManager([
        CommonSubexpressionEliminationPass(),
        ConstantFoldingPass(),
        DeadCodeEliminationPass(targets),
    ])
    return pm.run(program)
