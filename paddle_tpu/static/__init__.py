"""paddle_tpu.static — static-graph parity layer (reference: python/paddle/static).

TPU-native design: "static mode" is jit tracing; a Program is a traced, compiled
callable (see paddle_tpu.jit). This module keeps the mode switch + InputSpec.
"""

_static_mode = [False]


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape) if shape is not None else None
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"
