"""paddle_tpu.static — static-graph parity layer.

Parity anchors: python/paddle/static/__init__.py (Program/Executor/program_guard/
data/nn), python/paddle/base/executor.py:1285 (Executor.run),
paddle/fluid/framework/new_executor/standalone_executor.h:34.

TPU-native design: a Program is a lazily-recorded op list over the single runtime
op registry (core/static_graph.py); the Executor replays it under jax.jit so XLA
is the graph compiler. See executor.py / passes.py module docs.
"""

from __future__ import annotations

from ..core import static_graph as _sg
from ..core.static_graph import (  # noqa: F401
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    program_guard,
)
from .executor import Executor, Scope, global_scope  # noqa: F401
from .passes import (  # noqa: F401
    CommonSubexpressionEliminationPass,
    ConstantFoldingPass,
    DeadCodeEliminationPass,
    Pass,
    PassManager,
    apply_default_passes,
)
from . import nn  # noqa: F401


def __getattr__(name):
    # PEP 562 lazy submodules: the analysis package (6 modules), the
    # concurrency analyzer (PT-RACE, pure-ast), the program-cost auditor
    # (PT-COST) and the collective-communication auditor (PT-COMM) load
    # on first use, not at `import paddle_tpu` time
    if name in ("analysis", "concurrency", "cost", "comm"):
        import importlib

        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "InputSpec", "Program", "Variable", "Executor", "Scope", "global_scope",
    "program_guard", "default_main_program", "default_startup_program",
    "data", "CompiledProgram", "BuildStrategy", "ExecutionStrategy",
    "append_backward", "name_scope", "PassManager", "apply_default_passes",
    "nn", "analysis", "concurrency", "cost", "comm",
]


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape) if shape is not None else None
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def data(name: str, shape, dtype="float32", lod_level=0) -> Variable:
    """Declare a feed variable in the current program
    (reference: python/paddle/static/input.py data)."""
    from ..core import dtype as dtype_mod

    prog = _sg.current_program()
    return prog.global_block().create_var(
        shape, dtype_mod.convert_dtype(dtype), name=name, is_feed=True)


class BuildStrategy:
    """Pass-selection knobs (reference: BuildStrategy pybind). Fields map onto
    static/passes.py passes instead of ParallelExecutor graph passes."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True  # XLA fuses; kept for API parity
        self.constant_folding = True
        self.cse = True
        self.dead_code_elimination = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """Program + optimization pipeline (reference: python/paddle/static/
    compiler.py CompiledProgram). Passes run once, lazily, on first use."""

    def __init__(self, program: Program, build_strategy: BuildStrategy = None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()
        self._optimized = False

    def _ensure_optimized(self, targets=None):
        """Run the strategy-selected passes once (invoked by Executor.run).
        DCE is skipped without explicit targets — the Executor's replay builder
        applies run-time liveness pruning per fetch set anyway."""
        if self._optimized:
            return
        from .passes import (CommonSubexpressionEliminationPass,
                             ConstantFoldingPass, DeadCodeEliminationPass,
                             PassManager)

        bs = self._build_strategy
        pm = PassManager()
        if bs.cse:
            pm.add_pass(CommonSubexpressionEliminationPass())
        if bs.constant_folding:
            pm.add_pass(ConstantFoldingPass())
        if bs.dead_code_elimination and targets:
            pm.add_pass(DeadCodeEliminationPass(targets))
        pm.run(self._program)
        self._optimized = True


def append_backward(loss: Variable, parameter_list=None, no_grad_set=None):
    """Static-mode backward marker (reference: python/paddle/base/backward.py
    append_backward). Gradients are computed by the Executor via value_and_grad
    over the replay trace; this records which parameters require them and
    returns (param, grad_handle) pairs whose grads appear as ``param.grad``
    after each ``Executor.run``."""
    prog = loss.block.program
    prog._loss = loss
    params = list(parameter_list or prog.all_parameters())
    skip = set(map(id, no_grad_set or []))
    params = [p for p in params if getattr(p, "trainable", True)
              and id(p) not in skip]
    if prog._optimizer is None:
        class _GradOnly:
            """Sentinel optimizer: compute grads, apply no update."""

            _static_params = params

            @staticmethod
            def step():
                pass

            @staticmethod
            def clear_grad():
                pass

        prog._optimizer = _GradOnly()
    return [(p, None) for p in params]


class name_scope:
    """API-parity no-op scoping (names feed profiler annotations only)."""

    def __init__(self, prefix=""):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
