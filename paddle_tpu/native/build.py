"""Lazy builder for the paddle_tpu native runtime library.

Compiles ``src/*.cc`` into one shared object with g++ the first time it is
needed, keyed by a hash of the sources + compiler version, cached under
``~/.cache/paddle_tpu`` (or ``PT_NATIVE_CACHE``). This mirrors the reference's
"native core + Python shell" split (paddle/CMakeLists.txt superbuild) without
requiring a build step at install time: the toolchain requirement is just g++.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import tempfile
from pathlib import Path

_SRC_DIR = Path(__file__).resolve().parent / "src"
_LIB_BASENAME = "libptnative"


def _cache_dir() -> Path:
    d = os.environ.get("PT_NATIVE_CACHE")
    if d:
        return Path(d)
    return Path(os.path.expanduser("~")) / ".cache" / "paddle_tpu"


def _source_files():
    return sorted(_SRC_DIR.glob("*.cc")) + sorted(_SRC_DIR.glob("*.h"))


def _build_key(cxx: str) -> str:
    h = hashlib.sha256()
    for f in _source_files():
        h.update(f.name.encode())
        h.update(f.read_bytes())
    try:
        ver = subprocess.run([cxx, "--version"], capture_output=True, text=True,
                             timeout=30).stdout.splitlines()[:1]
        h.update("".join(ver).encode())
    except Exception:
        pass
    return h.hexdigest()[:16]


def build(verbose: bool = False) -> str:
    """Compile (or reuse cached) libptnative.so; returns its path.

    Raises RuntimeError when no working C++ toolchain is available — callers
    fall back to pure-Python implementations.
    """
    cxx = os.environ.get("CXX", "g++")
    key = _build_key(cxx)
    cache = _cache_dir()
    out = cache / f"{_LIB_BASENAME}-{key}.so"
    if out.exists():
        return str(out)
    cache.mkdir(parents=True, exist_ok=True)

    sources = [str(f) for f in _source_files() if f.suffix == ".cc"]
    # Build into a temp file then atomic-rename so concurrent builders are safe.
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(cache))
    os.close(fd)
    cmd = [
        cxx, "-O2", "-g", "-fPIC", "-shared", "-std=c++17", "-pthread",
        "-fvisibility=hidden", "-o", tmp, *sources, "-lrt",
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as e:
        os.unlink(tmp)
        raise RuntimeError(f"native build failed to run {cxx}: {e}") from e
    if proc.returncode != 0:
        os.unlink(tmp)
        raise RuntimeError(f"native build failed:\n{proc.stderr[-4000:]}")
    os.replace(tmp, out)
    if verbose:
        print(f"[paddle_tpu.native] built {out}")
    return str(out)


if __name__ == "__main__":
    print(build(verbose=True))
