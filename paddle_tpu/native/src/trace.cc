// Host-side profiler event collector with chrome://tracing JSON export.
//
// Native equivalent of the reference's HostTracer + ChromeTracingLogger
// (paddle/fluid/platform/profiler/host_tracer.cc, chrometracing_logger.cc).
// Device-side tracing on TPU is XLA/XPlane via jax.profiler; this collector
// records host op scopes (RecordEvent), instants, and counters with
// near-zero overhead (per-thread buffers, lock only on registration/flush).

#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <cstdio>
#include <mutex>
#include <string>
#include <vector>

#include "common.h"

namespace ptnative {
namespace {

struct Event {
  std::string name;
  char ph;  // 'X' complete, 'i' instant, 'C' counter
  int64_t ts_us;
  int64_t dur_us;
  double value;
  int tid;
};

struct ThreadBuf {
  std::vector<Event> events;
  std::vector<std::pair<std::string, int64_t>> open;  // begin() stack
  int tid;
};

std::mutex g_mu;
std::vector<ThreadBuf*> g_bufs;
std::atomic<bool> g_enabled{false};
std::atomic<int64_t> g_generation{0};  // bumps on every start; stale scopes skip end
int64_t g_epoch_us = 0;

ThreadBuf* tls() {
  thread_local ThreadBuf* buf = [] {
    auto* b = new ThreadBuf();
    b->tid = static_cast<int>(::syscall(SYS_gettid));
    std::lock_guard<std::mutex> lk(g_mu);
    g_bufs.push_back(b);
    return b;
  }();
  return buf;
}

void json_escape(FILE* f, const std::string& s) {
  for (char c : s) {
    if (c == '"' || c == '\\')
      std::fprintf(f, "\\%c", c);
    else if (static_cast<unsigned char>(c) < 0x20)
      std::fprintf(f, "\\u%04x", c);
    else
      std::fputc(c, f);
  }
}

}  // namespace
}  // namespace ptnative

using namespace ptnative;

PT_EXPORT void pt_trace_start() {
  std::lock_guard<std::mutex> lk(g_mu);
  for (auto* b : g_bufs) {
    b->events.clear();
    b->open.clear();
  }
  g_epoch_us = now_us();
  g_generation.fetch_add(1);
  g_enabled = true;
}

PT_EXPORT void pt_trace_stop() { g_enabled = false; }

PT_EXPORT int pt_trace_enabled() { return g_enabled ? 1 : 0; }

PT_EXPORT long long pt_trace_generation() { return g_generation.load(); }

PT_EXPORT void pt_trace_begin(const char* name) {
  if (!g_enabled) return;
  tls()->open.emplace_back(name, now_us());
}

PT_EXPORT void pt_trace_end() {
  if (!g_enabled) return;
  auto* b = tls();
  if (b->open.empty()) return;
  auto [name, t0] = std::move(b->open.back());
  b->open.pop_back();
  b->events.push_back({std::move(name), 'X', t0 - g_epoch_us, now_us() - t0, 0.0, b->tid});
}

PT_EXPORT void pt_trace_instant(const char* name) {
  if (!g_enabled) return;
  auto* b = tls();
  b->events.push_back({name, 'i', now_us() - g_epoch_us, 0, 0.0, b->tid});
}

PT_EXPORT void pt_trace_counter(const char* name, double value) {
  if (!g_enabled) return;
  auto* b = tls();
  b->events.push_back({name, 'C', now_us() - g_epoch_us, 0, value, b->tid});
}

PT_EXPORT long long pt_trace_event_count() {
  std::lock_guard<std::mutex> lk(g_mu);
  long long n = 0;
  for (auto* b : g_bufs) n += static_cast<long long>(b->events.size());
  return n;
}

PT_EXPORT int pt_trace_dump(const char* path, const char* process_name) {
  std::lock_guard<std::mutex> lk(g_mu);
  FILE* f = std::fopen(path, "w");
  if (!f) return -1;
  std::fprintf(f, "{\"traceEvents\":[\n");
  std::fprintf(f,
               "{\"ph\":\"M\",\"pid\":%d,\"name\":\"process_name\",\"args\":{\"name\":\"",
               ::getpid());
  json_escape(f, process_name ? process_name : "paddle_tpu");
  std::fprintf(f, "\"}}");
  for (auto* b : g_bufs) {
    for (const auto& e : b->events) {
      std::fprintf(f, ",\n{\"ph\":\"%c\",\"pid\":%d,\"tid\":%d,\"ts\":%lld,\"name\":\"",
                   e.ph, ::getpid(), e.tid, static_cast<long long>(e.ts_us));
      json_escape(f, e.name);
      std::fprintf(f, "\"");
      if (e.ph == 'X') std::fprintf(f, ",\"dur\":%lld", static_cast<long long>(e.dur_us));
      if (e.ph == 'C') std::fprintf(f, ",\"args\":{\"value\":%g}", e.value);
      if (e.ph == 'i') std::fprintf(f, ",\"s\":\"t\"");
      std::fprintf(f, "}");
    }
  }
  std::fprintf(f, "\n]}\n");
  std::fclose(f);
  return 0;
}
