// capi_runner — C-ABI shared library over the StableHLO artifact interpreter.
//
// Parity anchor: the reference ships R and Go inference clients
// (/root/reference/r/README.md, goapi) over a C API into its C++ predictor
// (paddle/fluid/inference/capi_exp/pd_inference_api.h). The TPU-native
// equivalent: jit.save emits a StableHLO module; THIS library exposes it to
// any FFI-capable language (C, Go via cgo, Rust via bindgen, R via .Call,
// Python ctypes) with a dozen plain-C entry points and no Python, JAX, or
// framework dependency in the process.
//
// Build:  g++ -O2 -std=c++17 -shared -fPIC -o libpaddle_tpu_infer.so capi_runner.cc
//
// Contract (all functions thread-compatible per handle, not thread-safe on
// one handle):
//   ptpu_load(path, err, errlen)          -> handle or NULL (err filled)
//   ptpu_num_inputs / ptpu_num_outputs(h) -> counts (outputs known at load)
//   ptpu_input_rank / ptpu_input_shape / ptpu_input_numel(h, i)
//   ptpu_run(h, inputs[], err, errlen)    -> 0 ok / -1 error; inputs are
//       caller-owned f32 buffers matching the signature order and sizes
//   ptpu_output_numel(h, k)               -> element count of output k
//   ptpu_get_output(h, k, buf)            -> copy output k into caller buf
//   ptpu_free(h)

#include <map>
#include <memory>
#include <set>

#include "stablehlo_interp.h"

namespace {

struct Handle {
  shlo::Program program;
  std::vector<std::string> rets;
  std::map<std::string, int> ret_count;  // duplicate-return occurrence count
  std::set<std::string> arg_names;   // membership test for env cleanup
  std::vector<shlo::Tensor> outputs;
  // persistent per-run environment: input tensors are allocated once and
  // overwritten in place each run (no per-call map rebuild / realloc); a
  // caller that knows its leading inputs are frozen weights can also skip
  // re-uploading them via ptpu_run_partial's `first_input`
  std::map<std::string, shlo::Tensor> env;
  bool env_ready = false;
};

void set_err(char* err, int errlen, const std::string& msg) {
  if (err && errlen > 0) {
    std::snprintf(err, (size_t)errlen, "%s", msg.c_str());
  }
}

}  // namespace

extern "C" {

void* ptpu_load(const char* mlir_path, char* err, int errlen) {
  try {
    auto h = std::make_unique<Handle>();
    h->program = shlo::parse(shlo::slurp(mlir_path));
    h->rets = shlo::parse_operands(h->program.ret_line);
    for (const auto& name : h->rets) ++h->ret_count[name];
    for (const auto& arg : h->program.args) h->arg_names.insert(arg.first);
    return h.release();
  } catch (const std::exception& e) {
    set_err(err, errlen, e.what());
    return nullptr;
  }
}

int ptpu_num_inputs(const void* h) {
  return (int)static_cast<const Handle*>(h)->program.args.size();
}

int ptpu_num_outputs(const void* h) {
  return (int)static_cast<const Handle*>(h)->rets.size();
}

int ptpu_input_rank(const void* h, int i) {
  return (int)static_cast<const Handle*>(h)->program.args[(size_t)i].second.size();
}

void ptpu_input_shape(const void* h, int i, long long* dims) {
  const auto& s = static_cast<const Handle*>(h)->program.args[(size_t)i].second;
  for (size_t d = 0; d < s.size(); ++d) dims[d] = (long long)s[d];
}

long long ptpu_input_numel(const void* h, int i) {
  const auto& s = static_cast<const Handle*>(h)->program.args[(size_t)i].second;
  long long n = 1;
  for (long long d : s) n *= d;
  return n;
}

static int run_impl(Handle* h, const float* const* inputs, int first_input,
                    char* err, int errlen) {
  try {
    if (first_input < 0 || (size_t)first_input > h->program.args.size()) {
      set_err(err, errlen, "first_input out of range");
      return -1;
    }
    if (!h->env_ready && first_input > 0) {
      // reject BEFORE allocating: a retry must still upload everything
      set_err(err, errlen, "first run must upload all inputs");
      return -1;
    }
    if (!h->env_ready) {
      for (const auto& arg : h->program.args) {
        shlo::Tensor t;
        t.shape = arg.second;
        t.data.assign((size_t)t.numel(), 0.f);
        h->env[arg.first] = std::move(t);
      }
      h->env_ready = true;
    }
    // overwrite in place from first_input on (weights uploaded once can be
    // skipped on later runs); inputs persist across runs
    for (size_t i = (size_t)first_input; i < h->program.args.size(); ++i) {
      shlo::Tensor& t = h->env[h->program.args[i].first];
      std::memcpy(t.data.data(), inputs[i - (size_t)first_input],
                  t.data.size() * sizeof(float));
    }
    shlo::run(h->program, h->env);
    // extract outputs and drop every non-input intermediate: steady-state
    // memory is weights + inputs + outputs, not the whole value graph.
    // COPY (don't move) when a return aliases an argument or repeats — a
    // moved-from arg tensor would silently drop that input on later runs,
    // and moving the first of N duplicate returns would leave the later
    // occurrences copying an empty husk.
    h->outputs.clear();
    std::map<std::string, int> remaining = h->ret_count;
    for (const auto& name : h->rets) {
      if (h->arg_names.count(name) || --remaining[name] > 0) {
        h->outputs.push_back(h->env.at(name));
      } else {
        h->outputs.push_back(std::move(h->env.at(name)));
      }
    }
    for (auto it = h->env.begin(); it != h->env.end();)
      it = h->arg_names.count(it->first) ? std::next(it) : h->env.erase(it);
    return 0;
  } catch (const std::exception& e) {
    set_err(err, errlen, e.what());
    return -1;
  }
}

int ptpu_run(void* hp, const float* const* inputs, char* err, int errlen) {
  return run_impl(static_cast<Handle*>(hp), inputs, 0, err, errlen);
}

// Re-run uploading only inputs [first_input:] (earlier ones — typically the
// frozen weight tensors — keep their previously uploaded values).
int ptpu_run_partial(void* hp, const float* const* inputs, int first_input,
                     char* err, int errlen) {
  return run_impl(static_cast<Handle*>(hp), inputs, first_input, err, errlen);
}

// output accessors are valid only AFTER a successful ptpu_run (output
// shapes are runtime values in this interpreter); out-of-range or
// run-before queries return -1 / leave buffers untouched instead of UB
long long ptpu_output_numel(const void* h, int k) {
  const auto& outs = static_cast<const Handle*>(h)->outputs;
  if (k < 0 || (size_t)k >= outs.size()) return -1;
  return outs[(size_t)k].numel();
}

int ptpu_output_rank(const void* h, int k) {
  const auto& outs = static_cast<const Handle*>(h)->outputs;
  if (k < 0 || (size_t)k >= outs.size()) return -1;
  return (int)outs[(size_t)k].shape.size();
}

void ptpu_output_shape(const void* h, int k, long long* dims) {
  const auto& outs = static_cast<const Handle*>(h)->outputs;
  if (k < 0 || (size_t)k >= outs.size()) return;
  const auto& s = outs[(size_t)k].shape;
  for (size_t d = 0; d < s.size(); ++d) dims[d] = (long long)s[d];
}

void ptpu_get_output(const void* h, int k, float* buf) {
  const auto& outs = static_cast<const Handle*>(h)->outputs;
  if (k < 0 || (size_t)k >= outs.size()) return;
  const auto& t = outs[(size_t)k];
  std::memcpy(buf, t.data.data(), t.data.size() * sizeof(float));
}

void ptpu_free(void* h) { delete static_cast<Handle*>(h); }

}  // extern "C"
