// stablehlo_interp.h — the restricted StableHLO text interpreter shared by
// the standalone stablehlo_runner binary and the C-ABI library
// (capi_runner.cc). See stablehlo_runner.cc for the op-coverage contract.
// Errors throw std::runtime_error (the binary catches and exits; the C API
// catches and returns an error string).
#pragma once

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <functional>
#include <map>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace shlo {

struct Tensor {
  std::vector<int64_t> shape;
  std::vector<float> data;
  int64_t numel() const {
    int64_t n = 1;
    for (int64_t d : shape) n *= d;
    return n;
  }
};

[[noreturn]] inline void fail(const std::string& msg) {
  throw std::runtime_error(msg);
}

// ---- tiny text utilities -------------------------------------------------

inline std::string slurp(const std::string& path) {
  std::ifstream f(path);
  if (!f) fail("cannot open " + path);
  std::stringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

// parse "tensor<2x8xf32>" (or "tensor<f32>" scalar) starting at s[pos]=='t'
inline std::vector<int64_t> parse_tensor_type(const std::string& s, size_t pos) {
  size_t lt = s.find('<', pos), gt = s.find('>', pos);
  if (lt == std::string::npos || gt == std::string::npos) fail("bad tensor type");
  std::string inner = s.substr(lt + 1, gt - lt - 1);
  if (inner.find("f32") == std::string::npos)
    fail("only f32 tensors supported, got tensor<" + inner + ">");
  std::vector<int64_t> shape;
  size_t p = 0;
  while (p < inner.size()) {
    size_t x = inner.find('x', p);
    std::string tok = inner.substr(p, x == std::string::npos ? x : x - p);
    if (tok == "f32") break;
    shape.push_back(std::stoll(tok));
    if (x == std::string::npos) break;
    p = x + 1;
  }
  return shape;
}

// parse "[1, 0]" integer list at s[pos]=='['
inline std::vector<int64_t> parse_int_list(const std::string& s, size_t pos) {
  size_t rb = s.find(']', pos);
  std::string inner = s.substr(pos + 1, rb - pos - 1);
  std::vector<int64_t> out;
  std::stringstream ss(inner);
  std::string tok;
  while (std::getline(ss, tok, ',')) {
    if (!tok.empty()) out.push_back(std::stoll(tok));
  }
  return out;
}

inline std::string strip(const std::string& s) {
  size_t a = s.find_first_not_of(" \t\r\n");
  size_t b = s.find_last_not_of(" \t\r\n");
  return a == std::string::npos ? "" : s.substr(a, b - a + 1);
}

// operand list "%4, %arg2" -> names, stopping at an attribute or " : "
inline std::vector<std::string> parse_operands(const std::string& s) {
  std::vector<std::string> out;
  size_t p = 0;
  while ((p = s.find('%', p)) != std::string::npos) {
    size_t e = p + 1;
    while (e < s.size() && (std::isalnum(s[e]) || s[e] == '_')) e++;
    out.push_back(s.substr(p, e - p));
    // stop scanning once the type section starts
    size_t colon = s.find(" : ");
    p = e;
    if (colon != std::string::npos && p > colon) break;
  }
  return out;
}

// ---- op implementations --------------------------------------------------

inline std::vector<int64_t> strides_of(const std::vector<int64_t>& shape) {
  std::vector<int64_t> st(shape.size(), 1);
  for (int i = (int)shape.size() - 2; i >= 0; --i)
    st[i] = st[i + 1] * shape[i + 1];
  return st;
}

inline Tensor broadcast_in_dim(const Tensor& x, const std::vector<int64_t>& dims,
                        const std::vector<int64_t>& out_shape) {
  Tensor out{out_shape, std::vector<float>((size_t)1, 0.f)};
  out.data.assign((size_t)out.numel(), 0.f);
  auto ost = strides_of(out_shape);
  auto xst = strides_of(x.shape);
  std::vector<int64_t> idx(out_shape.size(), 0);
  for (int64_t lin = 0; lin < out.numel(); ++lin) {
    int64_t rem = lin;
    for (size_t d = 0; d < out_shape.size(); ++d) {
      idx[d] = rem / ost[d];
      rem %= ost[d];
    }
    int64_t xi = 0;
    for (size_t j = 0; j < dims.size(); ++j)
      xi += (x.shape[j] == 1 ? 0 : idx[(size_t)dims[j]]) * xst[j];
    out.data[(size_t)lin] = x.data[(size_t)xi];
  }
  return out;
}

inline Tensor transpose(const Tensor& x, const std::vector<int64_t>& perm) {
  std::vector<int64_t> out_shape(perm.size());
  for (size_t i = 0; i < perm.size(); ++i) out_shape[i] = x.shape[(size_t)perm[i]];
  Tensor out{out_shape, std::vector<float>((size_t)x.numel())};
  auto xst = strides_of(x.shape);
  auto ost = strides_of(out_shape);
  std::vector<int64_t> idx(perm.size(), 0);
  for (int64_t lin = 0; lin < x.numel(); ++lin) {
    int64_t rem = lin;
    int64_t xi = 0;
    for (size_t d = 0; d < out_shape.size(); ++d) {
      idx[d] = rem / ost[d];
      rem %= ost[d];
      xi += idx[d] * xst[(size_t)perm[d]];
    }
    out.data[(size_t)lin] = x.data[(size_t)xi];
  }
  return out;
}

// general dot_general: reorder both sides to [B, M, K] / [B, K, N]
inline Tensor dot_general(const Tensor& lhs, const Tensor& rhs,
                   std::vector<int64_t> lb, std::vector<int64_t> rb,
                   std::vector<int64_t> lc, std::vector<int64_t> rc) {
  auto free_dims = [](const Tensor& t, const std::vector<int64_t>& b,
                      const std::vector<int64_t>& c) {
    std::vector<int64_t> f;
    for (int64_t d = 0; d < (int64_t)t.shape.size(); ++d) {
      bool used = false;
      for (int64_t x : b) used |= (x == d);
      for (int64_t x : c) used |= (x == d);
      if (!used) f.push_back(d);
    }
    return f;
  };
  auto lf = free_dims(lhs, lb, lc), rf = free_dims(rhs, rb, rc);
  auto pack = [](const Tensor& t, std::vector<int64_t> order) {
    return transpose(t, order);
  };
  std::vector<int64_t> lorder(lb), rorder(rb);
  lorder.insert(lorder.end(), lf.begin(), lf.end());
  lorder.insert(lorder.end(), lc.begin(), lc.end());
  rorder.insert(rorder.end(), rc.begin(), rc.end());
  rorder.insert(rorder.end(), rf.begin(), rf.end());
  Tensor L = pack(lhs, lorder);   // [batch..., M..., K...]
  Tensor R = pack(rhs, rorder);   // [batch..., K..., N...]
  int64_t B = 1, M = 1, K = 1, N = 1;
  for (size_t i = 0; i < lb.size(); ++i) B *= lhs.shape[(size_t)lb[i]];
  for (int64_t d : lf) M *= lhs.shape[(size_t)d];
  for (int64_t d : lc) K *= lhs.shape[(size_t)d];
  for (int64_t d : rf) N *= rhs.shape[(size_t)d];
  std::vector<int64_t> out_shape;
  for (int64_t d : lb) out_shape.push_back(lhs.shape[(size_t)d]);
  for (int64_t d : lf) out_shape.push_back(lhs.shape[(size_t)d]);
  for (int64_t d : rf) out_shape.push_back(rhs.shape[(size_t)d]);
  if (out_shape.empty()) out_shape.push_back(1);  // scalar-ish
  Tensor out{out_shape, std::vector<float>((size_t)(B * M * N), 0.f)};
  for (int64_t b = 0; b < B; ++b)
    for (int64_t m = 0; m < M; ++m)
      for (int64_t k = 0; k < K; ++k) {
        float lv = L.data[(size_t)((b * M + m) * K + k)];
        if (lv == 0.f) continue;
        const float* rrow = &R.data[(size_t)((b * K + k) * N)];
        float* orow = &out.data[(size_t)((b * M + m) * N)];
        for (int64_t n = 0; n < N; ++n) orow[(size_t)n] += lv * rrow[(size_t)n];
      }
  if (out.shape.size() == 1 && out.shape[0] == 1 && lb.empty() && lf.empty() &&
      rf.empty())
    out.shape.clear();
  return out;
}

// ---- interpreter ---------------------------------------------------------

struct Program {
  std::vector<std::pair<std::string, std::vector<int64_t>>> args;
  std::vector<std::string> body;   // op lines, in order
  std::string ret_line;
  // every OTHER func.func in the module, by name — `call @fn(...)` lines
  // (jax emits private helper functions for nested jits, e.g. relu) execute
  // these recursively. Populated on the module's @main Program only.
  std::map<std::string, Program> subfuncs;
};

inline Program parse_one(const std::string& text, size_t fpos,
                         const std::string& fname) {
  Program p;
  // signature runs until the '{' that opens the body
  size_t open = text.find('{', fpos);
  std::string sig = text.substr(fpos, open - fpos);
  size_t ap = 0;
  while ((ap = sig.find("%arg", ap)) != std::string::npos) {
    size_t e = ap + 4;
    while (e < sig.size() && std::isdigit(sig[e])) e++;
    std::string name = sig.substr(ap, e - ap);
    size_t tpos = sig.find("tensor<", e);
    if (tpos == std::string::npos) fail("arg without tensor type");
    // only record each %argN once (result attrs can repeat names)
    if (p.args.empty() || p.args.back().first != name)
      p.args.emplace_back(name, parse_tensor_type(sig, tpos));
    ap = e;
  }
  // body: lines up to the matching close of the block
  size_t pos = open + 1;
  std::stringstream ss(text.substr(pos));
  std::string line;
  while (std::getline(ss, line)) {
    std::string t = strip(line);
    if (t.rfind("return", 0) == 0 || t.rfind("func.return", 0) == 0) {
      p.ret_line = t;
      break;
    }
    if (t.find("= stablehlo.") != std::string::npos ||
        t.find("= mhlo.") != std::string::npos ||
        t.find("= call @") != std::string::npos ||
        t.find("= func.call @") != std::string::npos)
      p.body.push_back(t);
  }
  if (p.ret_line.empty()) fail("no return found in @" + fname);
  return p;
}

inline Program parse(const std::string& text) {
  size_t fpos = text.find("func.func public @main(");
  if (fpos == std::string::npos) fpos = text.find("func.func @main(");
  if (fpos == std::string::npos) fail("no @main function found");
  Program p = parse_one(text, fpos, "main");
  // collect every other function for call-site resolution
  size_t q = 0;
  while ((q = text.find("func.func", q)) != std::string::npos) {
    size_t at = text.find('@', q);
    size_t lp = at == std::string::npos ? std::string::npos
                                        : text.find('(', at);
    if (at == std::string::npos || lp == std::string::npos) break;
    std::string name = text.substr(at + 1, lp - at - 1);
    if (name != "main") p.subfuncs[name] = parse_one(text, q, name);
    q = lp;
  }
  return p;
}

inline void run_impl(const Program& p, std::map<std::string, Tensor>& env,
                     const std::map<std::string, Program>& funcs);

// public entry: @main executes with its module's function table in scope
inline void run(const Program& p, std::map<std::string, Tensor>& env) {
  run_impl(p, env, p.subfuncs);
}

inline void run_impl(const Program& p, std::map<std::string, Tensor>& env,
                     const std::map<std::string, Program>& funcs) {
  auto ew1 = [&](const std::string& lhs, const Tensor& a,
                 float (*f)(float)) {
    Tensor out = a;
    for (auto& v : out.data) v = f(v);
    env[lhs] = std::move(out);
  };
  auto ew2 = [&](const std::string& lhs, const Tensor& a, const Tensor& b,
                 const std::function<float(float, float)>& f) {
    if (a.numel() != b.numel()) fail("elementwise shape mismatch");
    Tensor out = a;
    for (size_t i = 0; i < out.data.size(); ++i)
      out.data[i] = f(a.data[i], b.data[i]);
    env[lhs] = std::move(out);
  };

  for (const std::string& line : p.body) {
    size_t eq = line.find(" = ");
    std::string lhs = strip(line.substr(0, eq));
    std::string rest = line.substr(eq + 3);
    if (rest.rfind("call @", 0) == 0 || rest.rfind("func.call @", 0) == 0) {
      // nested-jit helper function (e.g. jax's private @relu): execute the
      // callee with a fresh env over the SAME module function table
      if (lhs.find(':') != std::string::npos)
        fail("multi-result call unsupported (restricted interpreter)");
      size_t at = rest.find('@');
      size_t lp = rest.find('(', at);
      std::string callee = rest.substr(at + 1, lp - at - 1);
      auto fit = funcs.find(callee);
      if (fit == funcs.end()) fail("call to unknown function @" + callee);
      const Program& cp = fit->second;
      auto cops = parse_operands(rest.substr(lp));
      if (cops.size() != cp.args.size())
        fail("call arity mismatch @" + callee);
      std::map<std::string, Tensor> sub;
      for (size_t i = 0; i < cops.size(); ++i) {
        auto it = env.find(cops[i]);
        if (it == env.end()) fail("undefined value " + cops[i]);
        sub[cp.args[i].first] = it->second;
      }
      run_impl(cp, sub, funcs);
      auto rets = parse_operands(cp.ret_line);
      if (rets.size() != 1)
        fail("multi-result call unsupported @" + callee);
      auto rit = sub.find(rets[0]);
      if (rit == sub.end()) fail("undefined return " + rets[0]);
      env[lhs] = std::move(rit->second);
      continue;
    }
    size_t dot = rest.find('.');
    size_t sp = rest.find_first_of(" (", dot);
    std::string op = rest.substr(dot + 1, sp - dot - 1);
    std::string after = rest.substr(sp);
    auto ops = parse_operands(after);
    auto get = [&](size_t i) -> const Tensor& {
      auto it = env.find(ops.at(i));
      if (it == env.end()) fail("undefined value " + ops.at(i));
      return it->second;
    };

    if (op == "add") ew2(lhs, get(0), get(1), [](float x, float y) { return x + y; });
    else if (op == "subtract") ew2(lhs, get(0), get(1), [](float x, float y) { return x - y; });
    else if (op == "multiply") ew2(lhs, get(0), get(1), [](float x, float y) { return x * y; });
    else if (op == "divide") ew2(lhs, get(0), get(1), [](float x, float y) { return x / y; });
    else if (op == "maximum") ew2(lhs, get(0), get(1), [](float x, float y) { return x > y ? x : y; });
    else if (op == "minimum") ew2(lhs, get(0), get(1), [](float x, float y) { return x < y ? x : y; });
    else if (op == "negate") ew1(lhs, get(0), [](float x) { return -x; });
    else if (op == "tanh") ew1(lhs, get(0), [](float x) { return std::tanh(x); });
    else if (op == "logistic") ew1(lhs, get(0), [](float x) { return 1.f / (1.f + std::exp(-x)); });
    else if (op == "exponential") ew1(lhs, get(0), [](float x) { return std::exp(x); });
    else if (op == "sqrt") ew1(lhs, get(0), [](float x) { return std::sqrt(x); });
    else if (op == "rsqrt") ew1(lhs, get(0), [](float x) { return 1.f / std::sqrt(x); });
    else if (op == "convert") {
      env[lhs] = get(0);  // f32->f32 only (type gate in parse_tensor_type)
    } else if (op == "reshape") {
      size_t arrow = after.rfind("-> tensor<");
      Tensor out = get(0);
      out.shape = parse_tensor_type(after, arrow + 3);
      env[lhs] = std::move(out);
    } else if (op == "transpose") {
      size_t dp = after.find("dims = [");
      env[lhs] = transpose(get(0), parse_int_list(after, dp + 7));
    } else if (op == "broadcast_in_dim") {
      size_t dp = after.find("dims = [");
      size_t arrow = after.rfind("-> tensor<");
      env[lhs] = broadcast_in_dim(get(0), parse_int_list(after, dp + 7),
                                  parse_tensor_type(after, arrow + 3));
    } else if (op == "dot_general") {
      std::vector<int64_t> lb, rb, lc, rc;
      size_t bp = after.find("batching_dims = [");
      if (bp != std::string::npos) {
        lb = parse_int_list(after, after.find('[', bp));
        size_t x = after.find(" x ", bp);
        rb = parse_int_list(after, after.find('[', x));
      }
      size_t cp = after.find("contracting_dims = [");
      if (cp != std::string::npos) {
        lc = parse_int_list(after, after.find('[', cp));
        size_t x = after.find(" x ", cp);
        rc = parse_int_list(after, after.find('[', x));
      }
      env[lhs] = dot_general(get(0), get(1), lb, rb, lc, rc);
    } else if (op == "constant") {
      size_t dp = after.find("dense<");
      size_t close = after.find("> :", dp);
      std::string val = after.substr(dp + 6, close - dp - 6);
      size_t tpos = after.find("tensor<", close);
      Tensor out;
      out.shape = parse_tensor_type(after, tpos);
      int64_t n = out.numel();
      out.data.reserve((size_t)n);
      if (val.find('[') == std::string::npos) {
        out.data.assign((size_t)n, std::stof(val));  // splat
      } else {
        for (char& c : val)
          if (c == '[' || c == ']' || c == ',') c = ' ';
        std::stringstream vs(val);
        float f;
        while (vs >> f) out.data.push_back(f);
        if ((int64_t)out.data.size() != n) fail("constant element count mismatch");
      }
      env[lhs] = std::move(out);
    } else {
      fail("unsupported op stablehlo." + op +
           " (restricted interpreter — extend the op table)");
    }
  }
}

}  // namespace shlo
