// TCPStore: rank-rendezvous key/value store over TCP sockets.
//
// Functional equivalent of the reference's TCPStore
// (paddle/phi/core/distributed/store/tcp_store.h:121, socket.cpp): a master
// daemon owns an in-memory map; clients connect and issue SET/GET/ADD/WAIT/
// CHECK/DELETE. WAIT and WAIT_GE block server-side on a condition variable, so
// barriers need no client polling. Thread-per-connection — rendezvous traffic
// is tiny (tens of clients, few hundred ops per job).
//
// Wire format (little-endian):
//   request:  u8 cmd | u32 klen | key | u32 vlen | val | i64 arg
//   response: u8 status | u32 len | payload | i64 num
// status: 0 ok, 1 not-found, 2 timeout, 3 error.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdio>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

#include "common.h"

namespace ptnative {
namespace {

enum Cmd : uint8_t {
  kSet = 1,
  kGet = 2,
  kAdd = 3,
  kCheck = 4,
  kDelete = 5,
  kWait = 6,     // block until key exists; arg = timeout ms (<0 = forever)
  kNumKeys = 7,
  kPing = 8,
  kWaitGe = 9,   // block until int64-decoded value >= arg (timeout via i64 in val)
  kCompareSet = 10,  // val = expected \x00 desired; sets iff current == expected
};

enum Status : uint8_t { kOk = 0, kNotFound = 1, kTimeout = 2, kError = 3 };

bool read_full(int fd, void* buf, size_t n) {
  auto* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool read_blob(int fd, std::string* out) {
  uint32_t len;
  if (!read_full(fd, &len, 4)) return false;
  if (len > (256u << 20)) return false;  // 256 MB sanity cap
  out->resize(len);
  return len == 0 || read_full(fd, &(*out)[0], len);
}

bool write_resp(int fd, uint8_t status, const std::string& payload, int64_t num) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  std::string buf;
  buf.reserve(13 + payload.size());
  buf.push_back(static_cast<char>(status));
  buf.append(reinterpret_cast<char*>(&len), 4);
  buf.append(payload);
  buf.append(reinterpret_cast<char*>(&num), 8);
  return write_full(fd, buf.data(), buf.size());
}

int64_t decode_i64(const std::string& v) {
  if (v.size() == 8) {
    int64_t x;
    std::memcpy(&x, v.data(), 8);
    return x;
  }
  // Also accept ASCII ints (reference stores counters as strings).
  try {
    return std::stoll(v);
  } catch (...) {
    return 0;
  }
}

std::string encode_i64(int64_t x) {
  return std::string(reinterpret_cast<char*>(&x), 8);
}

class MasterDaemon {
 public:
  explicit MasterDaemon(int port) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
        ::listen(listen_fd_, 128) != 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return;
    }
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    port_ = ntohs(addr.sin_port);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
  }

  ~MasterDaemon() { Stop(); }

  int port() const { return port_; }
  bool ok() const { return listen_fd_ >= 0; }

  void Stop() {
    bool expected = false;
    if (!stopped_.compare_exchange_strong(expected, true)) return;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    std::vector<std::thread> workers;
    {
      std::lock_guard<std::mutex> lk(workers_mu_);
      workers.swap(workers_);
    }
    for (auto& t : workers)
      if (t.joinable()) t.join();
  }

 private:
  void AcceptLoop() {
    while (!stopped_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(workers_mu_);
      workers_.emplace_back([this, fd] { Serve(fd); });
    }
  }

  void Serve(int fd) {
    while (!stopped_) {
      uint8_t cmd;
      if (!read_full(fd, &cmd, 1)) break;
      std::string key, val;
      int64_t arg;
      if (!read_blob(fd, &key) || !read_blob(fd, &val) || !read_full(fd, &arg, 8)) break;
      if (!Dispatch(fd, cmd, key, val, arg)) break;
    }
    ::close(fd);
  }

  bool Dispatch(int fd, uint8_t cmd, const std::string& key, const std::string& val,
                int64_t arg) {
    std::unique_lock<std::mutex> lk(mu_);
    switch (cmd) {
      case kSet:
        data_[key] = val;
        cv_.notify_all();
        return Unlocked(&lk), write_resp(fd, kOk, "", 0);
      case kGet: {
        auto it = data_.find(key);
        if (it == data_.end()) return Unlocked(&lk), write_resp(fd, kNotFound, "", 0);
        std::string v = it->second;
        return Unlocked(&lk), write_resp(fd, kOk, v, 0);
      }
      case kAdd: {
        int64_t cur = 0;
        auto it = data_.find(key);
        if (it != data_.end()) cur = decode_i64(it->second);
        cur += arg;
        data_[key] = encode_i64(cur);
        cv_.notify_all();
        return Unlocked(&lk), write_resp(fd, kOk, "", cur);
      }
      case kCheck:
        return Unlocked(&lk), write_resp(fd, kOk, "", data_.count(key) ? 1 : 0);
      case kDelete: {
        int64_t n = static_cast<int64_t>(data_.erase(key));
        return Unlocked(&lk), write_resp(fd, kOk, "", n);
      }
      case kWait: {
        if (!WaitFor(lk, arg, [&] { return data_.count(key) > 0; }))
          return Unlocked(&lk), write_resp(fd, kTimeout, "", 0);
        return Unlocked(&lk), write_resp(fd, kOk, "", 0);
      }
      case kWaitGe: {
        int64_t timeout_ms = val.empty() ? -1 : decode_i64(val);
        auto pred = [&] {
          auto it = data_.find(key);
          return it != data_.end() && decode_i64(it->second) >= arg;
        };
        if (!WaitFor(lk, timeout_ms, pred))
          return Unlocked(&lk), write_resp(fd, kTimeout, "", 0);
        int64_t cur = decode_i64(data_[key]);
        return Unlocked(&lk), write_resp(fd, kOk, "", cur);
      }
      case kNumKeys:
        return Unlocked(&lk), write_resp(fd, kOk, "", static_cast<int64_t>(data_.size()));
      case kPing:
        return Unlocked(&lk), write_resp(fd, kOk, "", arg);
      case kCompareSet: {
        size_t sep = val.find('\0');
        std::string expected = sep == std::string::npos ? val : val.substr(0, sep);
        std::string desired = sep == std::string::npos ? "" : val.substr(sep + 1);
        auto it = data_.find(key);
        bool matched = (it == data_.end() && expected.empty()) ||
                       (it != data_.end() && it->second == expected);
        if (matched) {
          data_[key] = desired;
          cv_.notify_all();
        }
        std::string cur = data_.count(key) ? data_[key] : "";
        return Unlocked(&lk), write_resp(fd, matched ? kOk : kError, cur, matched);
      }
      default:
        return Unlocked(&lk), write_resp(fd, kError, "", 0);
    }
  }

  template <typename Pred>
  bool WaitFor(std::unique_lock<std::mutex>& lk, int64_t timeout_ms, Pred pred) {
    if (timeout_ms < 0) {
      cv_.wait(lk, [&] { return stopped_ || pred(); });
      return pred();
    }
    return cv_.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                        [&] { return stopped_ || pred(); }) &&
           pred();
  }

  // Release the map lock before socket IO so a slow client can't block the store.
  static void Unlocked(std::unique_lock<std::mutex>* lk) { lk->unlock(); }

  int listen_fd_ = -1;
  int port_ = -1;
  std::atomic<bool> stopped_{false};
  std::thread accept_thread_;
  std::mutex workers_mu_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> data_;
};

class Client {
 public:
  Client(const char* host, int port, int timeout_ms) {
    int64_t deadline = now_us() + static_cast<int64_t>(timeout_ms) * 1000;
    // Retry connect until the daemon is up (ranks race the master at bootstrap).
    while (fd_ < 0) {
      fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons(static_cast<uint16_t>(port));
      if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
        ::close(fd_);
        fd_ = -1;
        return;  // caller resolves hostnames to IPs in Python
      }
      if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) break;
      ::close(fd_);
      fd_ = -1;
      if (now_us() > deadline) return;
      ::usleep(50 * 1000);
    }
    int one = 1;
    ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  }

  ~Client() {
    if (fd_ >= 0) ::close(fd_);
  }

  bool ok() const { return fd_ >= 0; }

  bool Request(uint8_t cmd, const std::string& key, const std::string& val, int64_t arg,
               uint8_t* status, std::string* payload, int64_t* num) {
    std::lock_guard<std::mutex> lk(mu_);
    uint32_t klen = static_cast<uint32_t>(key.size());
    uint32_t vlen = static_cast<uint32_t>(val.size());
    std::string buf;
    buf.reserve(17 + key.size() + val.size());
    buf.push_back(static_cast<char>(cmd));
    buf.append(reinterpret_cast<char*>(&klen), 4);
    buf.append(key);
    buf.append(reinterpret_cast<char*>(&vlen), 4);
    buf.append(val);
    buf.append(reinterpret_cast<char*>(&arg), 8);
    if (!write_full(fd_, buf.data(), buf.size())) return false;
    if (!read_full(fd_, status, 1)) return false;
    if (!read_blob(fd_, payload)) return false;
    return read_full(fd_, num, 8);
  }

 private:
  int fd_ = -1;
  std::mutex mu_;
};

}  // namespace
}  // namespace ptnative

using ptnative::Client;
using ptnative::MasterDaemon;

PT_EXPORT void* pt_store_master_start(int port) {
  auto* d = new MasterDaemon(port);
  if (!d->ok()) {
    delete d;
    return nullptr;
  }
  return d;
}

PT_EXPORT int pt_store_master_port(void* d) {
  return static_cast<MasterDaemon*>(d)->port();
}

PT_EXPORT void pt_store_master_stop(void* d) {
  auto* m = static_cast<MasterDaemon*>(d);
  m->Stop();
  delete m;
}

PT_EXPORT void* pt_store_client_new(const char* host, int port, int timeout_ms) {
  auto* c = new Client(host, port, timeout_ms);
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}

PT_EXPORT void pt_store_client_free(void* c) { delete static_cast<Client*>(c); }

PT_EXPORT void pt_free(void* p) { std::free(p); }

namespace {
// -1 io error, else server status (0 ok / 1 notfound / 2 timeout / 3 error).
int do_req(void* c, uint8_t cmd, const char* key, const uint8_t* val, int vlen,
           int64_t arg, uint8_t** out, int* out_len, int64_t* num) {
  uint8_t status;
  std::string payload;
  int64_t n = 0;
  std::string v(reinterpret_cast<const char*>(val), val ? vlen : 0);
  if (!static_cast<Client*>(c)->Request(cmd, key ? key : "", v, arg, &status, &payload, &n))
    return -1;
  if (out) {
    *out = static_cast<uint8_t*>(std::malloc(payload.size() ? payload.size() : 1));
    std::memcpy(*out, payload.data(), payload.size());
    *out_len = static_cast<int>(payload.size());
  }
  if (num) *num = n;
  return status;
}
}  // namespace

PT_EXPORT int pt_store_set(void* c, const char* key, const uint8_t* val, int len) {
  return do_req(c, ptnative::kSet, key, val, len, 0, nullptr, nullptr, nullptr);
}

PT_EXPORT int pt_store_get(void* c, const char* key, uint8_t** out, int* out_len) {
  return do_req(c, ptnative::kGet, key, nullptr, 0, 0, out, out_len, nullptr);
}

PT_EXPORT long long pt_store_add(void* c, const char* key, long long delta) {
  int64_t num = 0;
  int st = do_req(c, ptnative::kAdd, key, nullptr, 0, delta, nullptr, nullptr, &num);
  return st == 0 ? num : -1;
}

PT_EXPORT int pt_store_check(void* c, const char* key) {
  int64_t num = 0;
  int st = do_req(c, ptnative::kCheck, key, nullptr, 0, 0, nullptr, nullptr, &num);
  return st == 0 ? static_cast<int>(num) : -1;
}

PT_EXPORT int pt_store_delete(void* c, const char* key) {
  int64_t num = 0;
  int st = do_req(c, ptnative::kDelete, key, nullptr, 0, 0, nullptr, nullptr, &num);
  return st == 0 ? static_cast<int>(num) : -1;
}

PT_EXPORT int pt_store_wait(void* c, const char* key, long long timeout_ms) {
  return do_req(c, ptnative::kWait, key, nullptr, 0, timeout_ms, nullptr, nullptr, nullptr);
}

// Blocks until int64(value[key]) >= target; returns current value or -1/-2.
PT_EXPORT long long pt_store_wait_ge(void* c, const char* key, long long target,
                                     long long timeout_ms) {
  int64_t num = 0;
  std::string t = ptnative::encode_i64(timeout_ms);
  int st = do_req(c, ptnative::kWaitGe, key,
                  reinterpret_cast<const uint8_t*>(t.data()), 8, target, nullptr,
                  nullptr, &num);
  if (st == 0) return num;
  return st == ptnative::kTimeout ? -2 : -1;
}

PT_EXPORT long long pt_store_num_keys(void* c) {
  int64_t num = 0;
  int st = do_req(c, ptnative::kNumKeys, "", nullptr, 0, 0, nullptr, nullptr, &num);
  return st == 0 ? num : -1;
}

PT_EXPORT int pt_store_compare_set(void* c, const char* key, const uint8_t* expected,
                                   int elen, const uint8_t* desired, int dlen,
                                   uint8_t** cur, int* cur_len) {
  std::string v(reinterpret_cast<const char*>(expected), elen);
  v.push_back('\0');
  v.append(reinterpret_cast<const char*>(desired), dlen);
  int64_t num = 0;
  int st = do_req(c, ptnative::kCompareSet, key,
                  reinterpret_cast<const uint8_t*>(v.data()),
                  static_cast<int>(v.size()), 0, cur, cur_len, &num);
  if (st < 0) return -1;
  return static_cast<int>(num);  // 1 = swapped, 0 = mismatch
}
