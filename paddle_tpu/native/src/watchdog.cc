// Hang watchdog: background monitor for collective/compute tasks.
//
// Native equivalent of the reference's comm watchdog
// (paddle/phi/core/distributed/comm_task_manager.h:37, comm_task.h:127
// CommTask::IsTimeout + trace dump on timeout). On TPU there are no NCCL
// streams to poll; instead the framework registers a task around each blocking
// region (collective barrier, device_get fence, pipeline step) and the monitor
// thread reports tasks that outlive their deadline to a report file and an
// atomic counter Python can poll. PT_WATCHDOG_FATAL=1 aborts the process on
// timeout (matching FLAGS_enable_async_trace_wait hard-failure behavior).

#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdio>
#include <map>
#include <mutex>
#include <string>
#include <thread>

#include "common.h"

namespace ptnative {
namespace {

struct Task {
  std::string name;
  int64_t start_us;
  int64_t deadline_us;  // <0: no timeout
  bool reported;
};

class Watchdog {
 public:
  Watchdog(int64_t interval_ms, const std::string& report_path)
      : interval_ms_(interval_ms), report_path_(report_path) {
    thread_ = std::thread([this] { Loop(); });
  }

  ~Watchdog() { Stop(); }

  void Stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (stopped_) return;
      stopped_ = true;
    }
    cv_.notify_all();
    if (thread_.joinable()) thread_.join();
  }

  int64_t Begin(const char* name, int64_t timeout_ms) {
    std::lock_guard<std::mutex> lk(mu_);
    int64_t id = next_id_++;
    int64_t now = now_us();
    tasks_[id] = {name, now, timeout_ms < 0 ? -1 : now + timeout_ms * 1000, false};
    return id;
  }

  void End(int64_t id) {
    std::lock_guard<std::mutex> lk(mu_);
    tasks_.erase(id);
  }

  int64_t TimeoutCount() { return timeout_count_.load(); }

  int64_t ActiveCount() {
    std::lock_guard<std::mutex> lk(mu_);
    return static_cast<int64_t>(tasks_.size());
  }

 private:
  void Loop() {
    std::unique_lock<std::mutex> lk(mu_);
    while (!stopped_) {
      cv_.wait_for(lk, std::chrono::milliseconds(interval_ms_),
                   [this] { return stopped_; });
      if (stopped_) break;
      int64_t now = now_us();
      for (auto& [id, t] : tasks_) {
        if (t.reported || t.deadline_us < 0 || now < t.deadline_us) continue;
        t.reported = true;
        timeout_count_.fetch_add(1);
        Report(t, now);
        if (const char* fatal = ::getenv("PT_WATCHDOG_FATAL");
            fatal && fatal[0] == '1') {
          std::fprintf(stderr, "[paddle_tpu watchdog] FATAL: task '%s' timed out\n",
                       t.name.c_str());
          std::abort();
        }
      }
    }
  }

  void Report(const Task& t, int64_t now) {
    FILE* f = std::fopen(report_path_.c_str(), "a");
    if (!f) return;
    std::fprintf(f,
                 "{\"event\":\"watchdog_timeout\",\"task\":\"%s\",\"pid\":%d,"
                 "\"elapsed_ms\":%lld,\"active_tasks\":%zu}\n",
                 t.name.c_str(), ::getpid(),
                 static_cast<long long>((now - t.start_us) / 1000), tasks_.size());
    std::fclose(f);
  }

  int64_t interval_ms_;
  std::string report_path_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<int64_t, Task> tasks_;
  int64_t next_id_ = 1;
  std::atomic<int64_t> timeout_count_{0};
  bool stopped_ = false;
  std::thread thread_;
};

}  // namespace
}  // namespace ptnative

using ptnative::Watchdog;

PT_EXPORT void* pt_watchdog_start(long long interval_ms, const char* report_path) {
  return new Watchdog(interval_ms, report_path ? report_path : "/dev/null");
}

PT_EXPORT void pt_watchdog_stop(void* w) {
  auto* wd = static_cast<Watchdog*>(w);
  wd->Stop();
  delete wd;
}

PT_EXPORT long long pt_watchdog_begin(void* w, const char* name, long long timeout_ms) {
  return static_cast<Watchdog*>(w)->Begin(name, timeout_ms);
}

PT_EXPORT void pt_watchdog_end(void* w, long long id) {
  static_cast<Watchdog*>(w)->End(id);
}

PT_EXPORT long long pt_watchdog_timeout_count(void* w) {
  return static_cast<Watchdog*>(w)->TimeoutCount();
}

PT_EXPORT long long pt_watchdog_active_count(void* w) {
  return static_cast<Watchdog*>(w)->ActiveCount();
}
