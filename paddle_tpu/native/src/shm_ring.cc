// Shared-memory MPMC ring buffer for DataLoader batch transport.
//
// Native equivalent of the reference's shared-memory tensor pipe between
// DataLoader worker processes and the trainer
// (python/paddle/io/dataloader/dataloader_iter.py:370 uses
// core.LoDTensorBlockingQueue + mmap'd tensors; the queue itself is C++).
// Here: POSIX shm_open + mmap region holding a process-shared
// mutex/condvar-guarded byte ring of length-prefixed records. Workers push
// pickled-header + raw numpy payload; the parent pops without a Python-level
// pickle of the bulk data.

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <cstdio>

#include "common.h"

namespace ptnative {
namespace {

constexpr uint64_t kMagic = 0x70745F72696E6701ULL;  // "pt_ring\1"

struct RingHdr {
  uint64_t magic;
  int64_t capacity;  // payload region bytes
  int64_t head;      // monotonically increasing write offset
  int64_t tail;      // monotonically increasing read offset
  int32_t closed;
  int32_t _pad;
  pthread_mutex_t mu;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
};

struct Ring {
  RingHdr* hdr;
  uint8_t* data;
  size_t map_len;
  std::string name;
  bool owner;
};

int64_t used(const RingHdr* h) { return h->head - h->tail; }

void copy_in(Ring* r, int64_t offset, const uint8_t* src, int64_t len) {
  int64_t cap = r->hdr->capacity;
  int64_t pos = offset % cap;
  int64_t first = std::min(len, cap - pos);
  std::memcpy(r->data + pos, src, static_cast<size_t>(first));
  if (first < len) std::memcpy(r->data, src + first, static_cast<size_t>(len - first));
}

void copy_out(Ring* r, int64_t offset, uint8_t* dst, int64_t len) {
  int64_t cap = r->hdr->capacity;
  int64_t pos = offset % cap;
  int64_t first = std::min(len, cap - pos);
  std::memcpy(dst, r->data + pos, static_cast<size_t>(first));
  if (first < len) std::memcpy(dst + first, r->data, static_cast<size_t>(len - first));
}

bool timed_wait(pthread_cond_t* cv, pthread_mutex_t* mu, int timeout_ms) {
  if (timeout_ms < 0) {
    pthread_cond_wait(cv, mu);
    return true;
  }
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) {
    ts.tv_sec += 1;
    ts.tv_nsec -= 1000000000L;
  }
  return pthread_cond_timedwait(cv, mu, &ts) == 0;
}

}  // namespace
}  // namespace ptnative

using ptnative::Ring;
using ptnative::RingHdr;

PT_EXPORT void* pt_shmring_create(const char* name, long long capacity) {
  ::shm_unlink(name);  // stale segment from a crashed run
  int fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t map_len = sizeof(RingHdr) + static_cast<size_t>(capacity);
  if (::ftruncate(fd, static_cast<off_t>(map_len)) != 0) {
    ::close(fd);
    ::shm_unlink(name);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) {
    ::shm_unlink(name);
    return nullptr;
  }
  auto* hdr = static_cast<RingHdr*>(mem);
  std::memset(hdr, 0, sizeof(RingHdr));
  hdr->capacity = capacity;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&hdr->not_empty, &ca);
  pthread_cond_init(&hdr->not_full, &ca);
  hdr->magic = ptnative::kMagic;

  auto* r = new Ring{hdr, static_cast<uint8_t*>(mem) + sizeof(RingHdr), map_len, name, true};
  return r;
}

PT_EXPORT void* pt_shmring_attach(const char* name) {
  int fd = ::shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* mem = ::mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ | PROT_WRITE,
                     MAP_SHARED, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* hdr = static_cast<RingHdr*>(mem);
  if (hdr->magic != ptnative::kMagic) {
    ::munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  auto* r = new Ring{hdr, static_cast<uint8_t*>(mem) + sizeof(RingHdr),
                     static_cast<size_t>(st.st_size), name, false};
  return r;
}

// 0 ok, -1 timeout/closed, -2 record larger than capacity.
PT_EXPORT int pt_shmring_push(void* rv, const uint8_t* payload, long long len,
                              int timeout_ms) {
  auto* r = static_cast<Ring*>(rv);
  RingHdr* h = r->hdr;
  int64_t need = 8 + len;
  if (need > h->capacity) return -2;
  if (pthread_mutex_lock(&h->mu) == EOWNERDEAD) pthread_mutex_consistent(&h->mu);
  while (!h->closed && h->capacity - ptnative::used(h) < need) {
    if (!ptnative::timed_wait(&h->not_full, &h->mu, timeout_ms)) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  if (h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -1;
  }
  int64_t len64 = len;
  ptnative::copy_in(r, h->head, reinterpret_cast<uint8_t*>(&len64), 8);
  ptnative::copy_in(r, h->head + 8, payload, len);
  h->head += need;
  pthread_cond_signal(&h->not_empty);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Returns payload length (>=0, buffer malloc'd into *out — free with pt_free),
// -1 on timeout, -3 when closed and drained.
PT_EXPORT long long pt_shmring_pop(void* rv, uint8_t** out, int timeout_ms) {
  auto* r = static_cast<Ring*>(rv);
  RingHdr* h = r->hdr;
  if (pthread_mutex_lock(&h->mu) == EOWNERDEAD) pthread_mutex_consistent(&h->mu);
  while (ptnative::used(h) == 0) {
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return -3;
    }
    if (!ptnative::timed_wait(&h->not_empty, &h->mu, timeout_ms)) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
  }
  int64_t len;
  ptnative::copy_out(r, h->tail, reinterpret_cast<uint8_t*>(&len), 8);
  *out = static_cast<uint8_t*>(std::malloc(len > 0 ? static_cast<size_t>(len) : 1));
  ptnative::copy_out(r, h->tail + 8, *out, len);
  h->tail += 8 + len;
  pthread_cond_signal(&h->not_full);
  pthread_mutex_unlock(&h->mu);
  return len;
}

PT_EXPORT long long pt_shmring_size(void* rv) {
  auto* r = static_cast<Ring*>(rv);
  return ptnative::used(r->hdr);
}

PT_EXPORT void pt_shmring_close(void* rv) {
  // Mark closed and wake waiters; detach mapping. Does not unlink the segment.
  auto* r = static_cast<Ring*>(rv);
  RingHdr* h = r->hdr;
  if (pthread_mutex_lock(&h->mu) == EOWNERDEAD) pthread_mutex_consistent(&h->mu);
  h->closed = 1;
  pthread_cond_broadcast(&h->not_empty);
  pthread_cond_broadcast(&h->not_full);
  pthread_mutex_unlock(&h->mu);
  ::munmap(h, r->map_len);
  delete r;
}

PT_EXPORT void pt_shmring_detach(void* rv) {
  auto* r = static_cast<Ring*>(rv);
  ::munmap(r->hdr, r->map_len);
  delete r;
}

PT_EXPORT void pt_shmring_unlink(const char* name) { ::shm_unlink(name); }
