// stablehlo_runner — native (no-Python) consumer of jit.save artifacts.
//
// Parity anchor: the reference's C++ jit::Layer executes jit.save'd programs
// from C++ (/root/reference/paddle/fluid/jit/layer.h:1) and ships R/Go
// inference clients (/root/reference/r/README.md). Here the saved artifact is
// a StableHLO module (path.mlir text, emitted next to path.pdmodel by
// paddle_tpu.jit.save); this program parses a restricted-but-real subset of
// the StableHLO text format and executes it with plain C++ — proving the
// artifact is consumable with zero Python in the process. (A production
// deployment would hand the same module to a PJRT C-API plugin; this image
// ships no such plugin .so, so the demo interpreter IS the native path.)
//
// Build:  g++ -O2 -std=c++17 -o stablehlo_runner stablehlo_runner.cc
// Run:    stablehlo_runner model.mlir in0.bin in1.bin ... [--out prefix]
//         inputs are raw little-endian f32 buffers matching @main's
//         signature order; each output k is written to <prefix><k>.bin and a
//         digest is printed.
//
// Supported ops (f32): add subtract multiply divide maximum minimum negate
// tanh logistic exponential sqrt rsqrt convert dot_general broadcast_in_dim
// reshape transpose constant(dense splat/list) return.

#include "stablehlo_interp.h"

int main(int argc, char** argv) {
  using namespace shlo;
  try {
  if (argc < 2) fail("usage: stablehlo_runner model.mlir in0.bin ... [--out prefix]");
  std::string out_prefix = "out";
  std::vector<std::string> inputs;
  for (int i = 2; i < argc; ++i) {
    if (std::string(argv[i]) == "--out" && i + 1 < argc) {
      out_prefix = argv[++i];
    } else {
      inputs.push_back(argv[i]);
    }
  }
  Program p = parse(slurp(argv[1]));
  if (inputs.size() != p.args.size())
    fail("signature expects " + std::to_string(p.args.size()) + " inputs, got " +
         std::to_string(inputs.size()));

  std::map<std::string, Tensor> env;
  for (size_t i = 0; i < inputs.size(); ++i) {
    Tensor t;
    t.shape = p.args[i].second;
    std::ifstream f(inputs[i], std::ios::binary);
    if (!f) fail("cannot open input " + inputs[i]);
    t.data.assign((size_t)t.numel(), 0.f);
    f.read(reinterpret_cast<char*>(t.data.data()),
           (std::streamsize)(t.data.size() * sizeof(float)));
    if ((size_t)f.gcount() != t.data.size() * sizeof(float))
      fail("input " + inputs[i] + " has wrong byte count");
    env[p.args[i].first] = std::move(t);
  }

  run(p, env);

  auto rets = parse_operands(p.ret_line);
  for (size_t k = 0; k < rets.size(); ++k) {
    const Tensor& t = env.at(rets[k]);
    std::string path = out_prefix + std::to_string(k) + ".bin";
    std::ofstream f(path, std::ios::binary);
    f.write(reinterpret_cast<const char*>(t.data.data()),
            (std::streamsize)(t.data.size() * sizeof(float)));
    double sum = 0;
    for (float v : t.data) sum += v;
    std::printf("out%zu: %lld elems, sum=%.6f -> %s\n", k,
                (long long)t.numel(), sum, path.c_str());
  }
  return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "stablehlo_runner: %s\n", e.what());
    return 1;
  }
}
