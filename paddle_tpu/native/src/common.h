// Common helpers for the paddle_tpu native runtime library.
//
// Native-runtime parity layer (reference: paddle/phi/core/distributed/store/
// tcp_store.h, fluid/platform/profiler, phi/core/distributed/comm_task_manager.h).
// The TPU compute path is JAX/XLA; this library provides the host-side runtime
// services that the reference implements in C++: rendezvous KV store, shared
// memory batch transport for the DataLoader, a chrome-trace event collector,
// and a hang watchdog. Exposed via a C ABI consumed from Python with ctypes.
#pragma once

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>

#define PT_EXPORT extern "C" __attribute__((visibility("default")))

namespace ptnative {

inline int64_t now_us() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<int64_t>(ts.tv_sec) * 1000000 + ts.tv_nsec / 1000;
}

}  // namespace ptnative
