"""paddle_tpu.native — C++ host-runtime services behind a ctypes C ABI.

Native-parity layer for the runtime pieces the reference implements in C++
(SURVEY §2 #24 TCPStore, #26 comm watchdog, #35 profiler host tracer, #41's
C++ blocking-queue transport). The TPU *compute* path stays JAX/XLA; these are
the host-side services around it.

Import is safe everywhere: if compilation is impossible the module degrades to
``available() == False`` and the Python fallbacks in each subsystem take over.
Set ``PT_DISABLE_NATIVE=1`` to force the fallbacks (used in tests).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

_lib = None
_lib_err: Optional[str] = None
_lock = threading.Lock()


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    sigs = {
        # tcp_store.cc
        "pt_store_master_start": (c.c_void_p, [c.c_int]),
        "pt_store_master_port": (c.c_int, [c.c_void_p]),
        "pt_store_master_stop": (None, [c.c_void_p]),
        "pt_store_client_new": (c.c_void_p, [c.c_char_p, c.c_int, c.c_int]),
        "pt_store_client_free": (None, [c.c_void_p]),
        "pt_store_set": (c.c_int, [c.c_void_p, c.c_char_p, c.c_char_p, c.c_int]),
        "pt_store_get": (c.c_int, [c.c_void_p, c.c_char_p,
                                   c.POINTER(c.POINTER(c.c_uint8)), c.POINTER(c.c_int)]),
        "pt_store_add": (c.c_longlong, [c.c_void_p, c.c_char_p, c.c_longlong]),
        "pt_store_check": (c.c_int, [c.c_void_p, c.c_char_p]),
        "pt_store_delete": (c.c_int, [c.c_void_p, c.c_char_p]),
        "pt_store_wait": (c.c_int, [c.c_void_p, c.c_char_p, c.c_longlong]),
        "pt_store_wait_ge": (c.c_longlong,
                             [c.c_void_p, c.c_char_p, c.c_longlong, c.c_longlong]),
        "pt_store_num_keys": (c.c_longlong, [c.c_void_p]),
        "pt_store_compare_set": (c.c_int, [c.c_void_p, c.c_char_p, c.c_char_p, c.c_int,
                                           c.c_char_p, c.c_int,
                                           c.POINTER(c.POINTER(c.c_uint8)),
                                           c.POINTER(c.c_int)]),
        "pt_free": (None, [c.c_void_p]),
        # shm_ring.cc
        "pt_shmring_create": (c.c_void_p, [c.c_char_p, c.c_longlong]),
        "pt_shmring_attach": (c.c_void_p, [c.c_char_p]),
        "pt_shmring_push": (c.c_int, [c.c_void_p, c.c_char_p, c.c_longlong, c.c_int]),
        "pt_shmring_pop": (c.c_longlong,
                           [c.c_void_p, c.POINTER(c.POINTER(c.c_uint8)), c.c_int]),
        "pt_shmring_size": (c.c_longlong, [c.c_void_p]),
        "pt_shmring_close": (None, [c.c_void_p]),
        "pt_shmring_detach": (None, [c.c_void_p]),
        "pt_shmring_unlink": (None, [c.c_char_p]),
        # trace.cc
        "pt_trace_start": (None, []),
        "pt_trace_stop": (None, []),
        "pt_trace_enabled": (c.c_int, []),
        "pt_trace_generation": (c.c_longlong, []),
        "pt_trace_begin": (None, [c.c_char_p]),
        "pt_trace_end": (None, []),
        "pt_trace_instant": (None, [c.c_char_p]),
        "pt_trace_counter": (None, [c.c_char_p, c.c_double]),
        "pt_trace_event_count": (c.c_longlong, []),
        "pt_trace_dump": (c.c_int, [c.c_char_p, c.c_char_p]),
        # watchdog.cc
        "pt_watchdog_start": (c.c_void_p, [c.c_longlong, c.c_char_p]),
        "pt_watchdog_stop": (None, [c.c_void_p]),
        "pt_watchdog_begin": (c.c_longlong, [c.c_void_p, c.c_char_p, c.c_longlong]),
        "pt_watchdog_end": (None, [c.c_void_p, c.c_longlong]),
        "pt_watchdog_timeout_count": (c.c_longlong, [c.c_void_p]),
        "pt_watchdog_active_count": (c.c_longlong, [c.c_void_p]),
    }
    for name, (restype, argtypes) in sigs.items():
        fn = getattr(lib, name)
        fn.restype = restype
        fn.argtypes = argtypes
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Build-if-needed and dlopen the native library; None when unavailable."""
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    with _lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        if os.environ.get("PT_DISABLE_NATIVE") == "1":
            _lib_err = "disabled via PT_DISABLE_NATIVE"
            return None
        try:
            from .build import build

            _lib = _bind(ctypes.CDLL(build()))
        except Exception as e:  # noqa: BLE001 — any failure → Python fallback
            _lib_err = str(e)
            return None
        return _lib


def available() -> bool:
    return load() is not None


def peek() -> Optional[ctypes.CDLL]:
    """The library if it is ALREADY loaded — never triggers a build.

    Hot paths (RecordEvent) use this so untraced runs never pay the first-call
    g++ compile; the Profiler's start() performs the real load().
    """
    return _lib


def load_error() -> Optional[str]:
    load()
    return _lib_err


def take_bytes(lib, out_ptr, out_len) -> bytes:
    """Copy a malloc'd (ptr,len) result into Python bytes and free it."""
    try:
        if not out_ptr or out_len.value <= 0:
            return b""
        return ctypes.string_at(out_ptr, out_len.value)
    finally:
        if out_ptr:
            lib.pt_free(out_ptr)
