/* Minimal C inference client over libpaddle_tpu_infer.so.
 *
 * Parity anchor: the reference's C clients over
 * paddle/fluid/inference/capi_exp/pd_inference_api.h. Here the artifact is
 * the StableHLO .mlir that paddle.jit.save emits; the weights ship in the
 * companion .pdiparams (this demo reads them from a raw .bin the exporter
 * writes — see tests/test_capi_examples.py — since pickle is a Python
 * format).
 *
 * Build:
 *   gcc -O2 -o predict predict.c -L. -lpaddle_tpu_infer -lm
 * Run:
 *   ./predict model.mlir weights.bin  < input.f32 > output.f32
 * where weights.bin is the concatenation of every signature input except
 * the last (f32, row-major, signature order) and stdin carries the final
 * (activation) input.
 */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

/* --- the full C surface of libpaddle_tpu_infer.so --- */
void* ptpu_load(const char* mlir_path, char* err, int errlen);
int ptpu_num_inputs(const void* h);
int ptpu_num_outputs(const void* h);
int ptpu_input_rank(const void* h, int i);
void ptpu_input_shape(const void* h, int i, long long* dims);
long long ptpu_input_numel(const void* h, int i);
int ptpu_run(void* h, const float* const* inputs, char* err, int errlen);
int ptpu_run_partial(void* h, const float* const* inputs, int first_input,
                     char* err, int errlen);
long long ptpu_output_numel(const void* h, int k);
int ptpu_output_rank(const void* h, int k);
void ptpu_output_shape(const void* h, int k, long long* dims);
void ptpu_get_output(const void* h, int k, float* buf);
void ptpu_free(void* h);

int main(int argc, char** argv) {
  if (argc != 3) {
    fprintf(stderr, "usage: %s model.mlir weights.bin\n", argv[0]);
    return 2;
  }
  char err[256] = {0};
  void* h = ptpu_load(argv[1], err, sizeof(err));
  if (!h) {
    fprintf(stderr, "load failed: %s\n", err);
    return 1;
  }
  int n_in = ptpu_num_inputs(h);

  /* weights.bin = inputs [0, n_in-1) concatenated; stdin = input n_in-1 */
  FILE* wf = fopen(argv[2], "rb");
  if (!wf) {
    fprintf(stderr, "cannot open %s\n", argv[2]);
    return 1;
  }
  const float** bufs = (const float**)malloc(sizeof(float*) * n_in);
  for (int i = 0; i < n_in; ++i) {
    long long n = ptpu_input_numel(h, i);
    float* b = (float*)malloc(sizeof(float) * n);
    size_t got = fread(b, sizeof(float), (size_t)n,
                       i + 1 < n_in ? wf : stdin);
    if ((long long)got != n) {
      fprintf(stderr, "input %d: expected %lld floats, got %zu\n", i, n, got);
      return 1;
    }
    bufs[i] = b;
  }
  fclose(wf);

  if (ptpu_run(h, bufs, err, sizeof(err)) != 0) {
    fprintf(stderr, "run failed: %s\n", err);
    return 1;
  }
  for (int k = 0; k < ptpu_num_outputs(h); ++k) {
    long long n = ptpu_output_numel(h, k);
    float* out = (float*)malloc(sizeof(float) * n);
    ptpu_get_output(h, k, out);
    fwrite(out, sizeof(float), (size_t)n, stdout);
    free(out);
  }
  for (int i = 0; i < n_in; ++i) free((void*)bufs[i]);
  free(bufs);
  ptpu_free(h);
  return 0;
}
