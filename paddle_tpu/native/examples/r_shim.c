/* .Call shim exposing libpaddle_tpu_infer.so to R (predict.R).
 *
 * Base-R .C cannot carry opaque handles; this shim wraps the C ABI in
 * SEXP externalptr + numeric vectors. Build:
 *   R CMD SHLIB r_shim.c -L. -lpaddle_tpu_infer
 */

#include <R.h>
#include <Rinternals.h>
#include <stdlib.h>
#include <string.h>

void* ptpu_load(const char* mlir_path, char* err, int errlen);
int ptpu_num_inputs(const void* h);
int ptpu_num_outputs(const void* h);
long long ptpu_input_numel(const void* h, int i);
int ptpu_run(void* h, const float* const* inputs, char* err, int errlen);
long long ptpu_output_numel(const void* h, int k);
void ptpu_get_output(const void* h, int k, float* buf);
void ptpu_free(void* h);

SEXP R_ptpu_load(SEXP path) {
  char err[256] = {0};
  void* h = ptpu_load(CHAR(STRING_ELT(path, 0)), err, sizeof(err));
  if (!h) error("ptpu_load: %s", err);
  return R_MakeExternalPtr(h, R_NilValue, R_NilValue);
}

SEXP R_ptpu_num_inputs(SEXP hp) {
  return ScalarInteger(ptpu_num_inputs(R_ExternalPtrAddr(hp)));
}

SEXP R_ptpu_input_numel(SEXP hp, SEXP i) {
  return ScalarReal(
      (double)ptpu_input_numel(R_ExternalPtrAddr(hp), asInteger(i)));
}

SEXP R_ptpu_run(SEXP hp, SEXP inputs) {
  void* h = R_ExternalPtrAddr(hp);
  int n_in = ptpu_num_inputs(h);
  if (LENGTH(inputs) != n_in) error("expected %d inputs", n_in);
  const float** bufs = (const float**)malloc(sizeof(float*) * n_in);
  for (int i = 0; i < n_in; ++i) {
    long long n = ptpu_input_numel(h, i);
    SEXP v = VECTOR_ELT(inputs, i);
    if (LENGTH(v) != (int)n) error("input %d: expected %lld elements", i, n);
    float* b = (float*)malloc(sizeof(float) * n);
    for (long long j = 0; j < n; ++j) b[j] = (float)REAL(v)[j];
    bufs[i] = b;
  }
  char err[256] = {0};
  int rc = ptpu_run(h, bufs, err, sizeof(err));
  for (int i = 0; i < n_in; ++i) free((void*)bufs[i]);
  free(bufs);
  if (rc != 0) error("ptpu_run: %s", err);
  int n_out = ptpu_num_outputs(h);
  SEXP out = PROTECT(allocVector(VECSXP, n_out));
  for (int k = 0; k < n_out; ++k) {
    long long n = ptpu_output_numel(h, k);
    float* buf = (float*)malloc(sizeof(float) * n);
    ptpu_get_output(h, k, buf);
    SEXP v = allocVector(REALSXP, (R_xlen_t)n);
    for (long long j = 0; j < n; ++j) REAL(v)[j] = buf[j];
    free(buf);
    SET_VECTOR_ELT(out, k, v);
  }
  UNPROTECT(1);
  return out;
}

SEXP R_ptpu_free(SEXP hp) {
  void* h = R_ExternalPtrAddr(hp);
  if (h) ptpu_free(h);
  R_ClearExternalPtr(hp);
  return R_NilValue;
}
