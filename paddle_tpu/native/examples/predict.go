// Go inference client over libpaddle_tpu_infer.so via cgo.
//
// Parity anchor: the reference's Go API (fluid/inference/goapi) over its C
// predictor. Here the artifact is the StableHLO .mlir from paddle.jit.save;
// weights load from the raw .bin companion (see predict.c for the layout).
//
// Build:
//   CGO_LDFLAGS="-L. -lpaddle_tpu_infer" go build -o predict_go predict.go
// Run:
//   LD_LIBRARY_PATH=. ./predict_go model.mlir weights.bin < in.f32 > out.f32

package main

/*
#cgo LDFLAGS: -lpaddle_tpu_infer
#include <stdlib.h>

void* ptpu_load(const char* mlir_path, char* err, int errlen);
int ptpu_num_inputs(const void* h);
int ptpu_num_outputs(const void* h);
long long ptpu_input_numel(const void* h, int i);
int ptpu_run(void* h, const float* const* inputs, char* err, int errlen);
long long ptpu_output_numel(const void* h, int k);
void ptpu_get_output(const void* h, int k, float* buf);
void ptpu_free(void* h);
*/
import "C"

import (
	"encoding/binary"
	"fmt"
	"io"
	"math"
	"os"
	"unsafe"
)

func readFloats(r io.Reader, n int64) ([]float32, error) {
	raw := make([]byte, 4*n)
	if _, err := io.ReadFull(r, raw); err != nil {
		return nil, err
	}
	out := make([]float32, n)
	for i := range out {
		out[i] = math.Float32frombits(
			binary.LittleEndian.Uint32(raw[4*i : 4*i+4]))
	}
	return out, nil
}

func main() {
	if len(os.Args) != 3 {
		fmt.Fprintf(os.Stderr, "usage: %s model.mlir weights.bin\n", os.Args[0])
		os.Exit(2)
	}
	errBuf := make([]byte, 256)
	cpath := C.CString(os.Args[1])
	defer C.free(unsafe.Pointer(cpath))
	h := C.ptpu_load(cpath, (*C.char)(unsafe.Pointer(&errBuf[0])), 256)
	if h == nil {
		fmt.Fprintf(os.Stderr, "load failed: %s\n", errBuf)
		os.Exit(1)
	}
	defer C.ptpu_free(h)

	nIn := int(C.ptpu_num_inputs(h))
	wf, err := os.Open(os.Args[2])
	if err != nil {
		fmt.Fprintln(os.Stderr, err)
		os.Exit(1)
	}
	defer wf.Close()

	// cgo pointer rules: the pointer ARRAY passed to C may not live in Go
	// memory while holding Go pointers — C-allocate both the array and the
	// input buffers
	ptrs := (**C.float)(C.malloc(C.size_t(nIn) * C.size_t(unsafe.Sizeof(uintptr(0)))))
	defer C.free(unsafe.Pointer(ptrs))
	ptrSlice := unsafe.Slice((**C.float)(unsafe.Pointer(ptrs)), nIn)
	for i := 0; i < nIn; i++ {
		n := int64(C.ptpu_input_numel(h, C.int(i)))
		src := io.Reader(wf)
		if i == nIn-1 {
			src = os.Stdin
		}
		b, err := readFloats(src, n)
		if err != nil {
			fmt.Fprintf(os.Stderr, "input %d: %v\n", i, err)
			os.Exit(1)
		}
		cbuf := (*C.float)(C.malloc(C.size_t(4 * n)))
		defer C.free(unsafe.Pointer(cbuf))
		cs := unsafe.Slice((*float32)(unsafe.Pointer(cbuf)), n)
		copy(cs, b)
		ptrSlice[i] = cbuf
	}
	rc := C.ptpu_run(h, ptrs, (*C.char)(unsafe.Pointer(&errBuf[0])), 256)
	if rc != 0 {
		fmt.Fprintf(os.Stderr, "run failed: %s\n", errBuf)
		os.Exit(1)
	}
	for k := 0; k < int(C.ptpu_num_outputs(h)); k++ {
		n := int64(C.ptpu_output_numel(h, C.int(k)))
		out := make([]float32, n)
		C.ptpu_get_output(h, C.int(k), (*C.float)(unsafe.Pointer(&out[0])))
		raw := make([]byte, 4*n)
		for i, v := range out {
			binary.LittleEndian.PutUint32(raw[4*i:], math.Float32bits(v))
		}
		os.Stdout.Write(raw)
	}
}
