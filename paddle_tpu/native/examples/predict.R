# R inference client over libpaddle_tpu_infer.so.
#
# Parity anchor: the reference's R client (r/example/mobilenet.r) over its
# C predictor API. Here the artifact is the StableHLO .mlir from
# paddle.jit.save; weights load from the raw .bin companion (see predict.c
# for the layout). The handle-passing entry points go through the tiny
# .Call shim (r_shim.c) because base-R .C cannot carry opaque pointers.
#
# Build the shim against the inference library:
#   R CMD SHLIB r_shim.c -L. -lpaddle_tpu_infer
# Run:
#   Rscript predict.R model.mlir weights.bin input.f32 output.f32

args <- commandArgs(trailingOnly = TRUE)
if (length(args) != 4) {
  stop("usage: Rscript predict.R model.mlir weights.bin input.f32 output.f32")
}
# shim next to the working directory by default; override via PTPU_R_SHIM
shim <- Sys.getenv("PTPU_R_SHIM", "r_shim.so")
dyn.load(shim)

h <- .Call("R_ptpu_load", args[1])
n_in <- .Call("R_ptpu_num_inputs", h)

wf <- file(args[2], "rb")
inputs <- vector("list", n_in)
for (i in seq_len(n_in)) {
  n <- .Call("R_ptpu_input_numel", h, i - 1L)
  src <- if (i < n_in) wf else file(args[3], "rb")
  inputs[[i]] <- readBin(src, what = "numeric", n = n, size = 4,
                         endian = "little")
  if (i == n_in) close(src)
}
close(wf)

out <- .Call("R_ptpu_run", h, inputs)   # list of f32 output vectors
con <- file(args[4], "wb")
for (o in out) writeBin(o, con, size = 4, endian = "little")
close(con)
.Call("R_ptpu_free", h)
cat("wrote", length(out), "output tensor(s)\n")
