"""Audio datasets (reference: python/paddle/audio/datasets — ESC50/TESS).

Zero-egress environment: waveform data is synthesized deterministically with
the documented shapes/labels, mirroring how vision.datasets handles the
download-free case."""

from __future__ import annotations

import numpy as np

from ..io.dataset import Dataset

__all__ = ["ESC50", "TESS"]


class _SyntheticAudio(Dataset):
    sample_rate = 16000
    n_classes = 2
    duration = 1.0

    def __init__(self, mode: str = "train", feat_type: str = "raw", size=200,
                 **kwargs):
        self.mode = mode
        self.feat_type = feat_type
        self.size = size
        self._rng = np.random.default_rng(0 if mode == "train" else 1)
        self._labels = self._rng.integers(0, self.n_classes, size)

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        n = int(self.sample_rate * self.duration)
        rng = np.random.default_rng((0 if self.mode == "train" else 1, idx))
        label = int(self._labels[idx])
        freq = 200.0 + 50.0 * label
        t = np.arange(n) / self.sample_rate
        wave = (np.sin(2 * np.pi * freq * t)
                + 0.1 * rng.standard_normal(n)).astype(np.float32)
        return wave, label


class ESC50(_SyntheticAudio):
    """ESC-50 environmental sounds (50 classes, 5s @ 44.1k in the reference)."""

    sample_rate = 44100
    n_classes = 50
    duration = 5.0


class TESS(_SyntheticAudio):
    """TESS emotional speech (7 classes in the reference)."""

    sample_rate = 24414
    n_classes = 7
    duration = 2.0
