"""paddle_tpu.audio — audio feature suite (reference: python/paddle/audio). Round-1 stub."""
