"""paddle_tpu.audio (reference: python/paddle/audio — functional/, features/,
datasets/). Real DSP over the framework stft/fft path."""

from . import datasets, features, functional  # noqa: F401
from .functional import (  # noqa: F401
    compute_fbank_matrix,
    create_dct,
    fft_frequencies,
    get_window,
    hz_to_mel,
    mel_frequencies,
    mel_to_hz,
    power_to_db,
)

__all__ = ["functional", "features", "datasets"]
