"""Audio feature layers (reference: python/paddle/audio/features/layers.py:
Spectrogram:45, MelSpectrogram:130, LogMelSpectrogram:237, MFCC:344)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, unwrap
from ..nn.layer.layers import Layer
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length=None, win_length=None,
                 window: str = "hann", power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.register_buffer(
            "fft_window", AF.get_window(window, self.win_length, dtype=dtype))

    def forward(self, x):
        from .. import signal

        spec = signal.stft(x, self.n_fft, self.hop_length, self.win_length,
                           window=self.fft_window, center=self.center,
                           pad_mode=self.pad_mode)
        return Tensor(jnp.abs(unwrap(spec)) ** self.power)


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 2048, hop_length=None,
                 win_length=None, window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0, f_max=None,
                 htk: bool = False, norm="slaney", dtype: str = "float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                        power, center, pad_mode, dtype)
        self.register_buffer("fbank_matrix", AF.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype))

    def forward(self, x):
        spec = unwrap(self._spectrogram(x))  # [..., freq, time]
        mel = jnp.einsum("mf,...ft->...mt", unwrap(self.fbank_matrix), spec)
        return Tensor(mel)


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 2048, hop_length=None,
                 win_length=None, window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0, f_max=None,
                 htk: bool = False, norm="slaney", ref_value: float = 1.0,
                 amin: float = 1e-10, top_db=None, dtype: str = "float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x):
        return AF.power_to_db(self._melspectrogram(x), self.ref_value,
                              self.amin, self.top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 2048,
                 hop_length=None, win_length=None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max=None, htk: bool = False,
                 norm="slaney", ref_value: float = 1.0, amin: float = 1e-10,
                 top_db=None, dtype: str = "float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.register_buffer("dct_matrix", AF.create_dct(n_mfcc, n_mels,
                                                         dtype=dtype))

    def forward(self, x):
        logmel = unwrap(self._log_melspectrogram(x))  # [..., n_mels, time]
        return Tensor(jnp.einsum("mk,...mt->...kt",
                                 unwrap(self.dct_matrix), logmel))
