"""Audio functional ops (reference: python/paddle/audio/functional/
functional.py + window.py — librosa-compatible mel/fbank/dct/window math)."""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, unwrap

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct", "get_window"]


def hz_to_mel(freq, htk: bool = False):
    """Hz -> mel (reference: functional.py:29; slaney scale by default)."""
    scalar = not isinstance(freq, Tensor)
    f = jnp.asarray(unwrap(freq), jnp.float32)
    if htk:
        out = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = jnp.where(f >= min_log_hz,
                        min_log_mel + jnp.log(jnp.maximum(f, 1e-10) / min_log_hz) / logstep,
                        mels)
    return float(out) if scalar and out.ndim == 0 else Tensor(out)


def mel_to_hz(mel, htk: bool = False):
    scalar = not isinstance(mel, Tensor)
    m = jnp.asarray(unwrap(mel), jnp.float32)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = jnp.where(m >= min_log_mel,
                        min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                        freqs)
    return float(out) if scalar and out.ndim == 0 else Tensor(out)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0, f_max: float = 11025.0,
                    htk: bool = False, dtype: str = "float32"):
    lo = hz_to_mel(f_min, htk)
    hi = hz_to_mel(f_max, htk)
    mels = jnp.linspace(lo, hi, n_mels)
    return Tensor(unwrap(mel_to_hz(Tensor(mels), htk)).astype(dtype))


def fft_frequencies(sr: int, n_fft: int, dtype: str = "float32"):
    return Tensor(jnp.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype))


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max=None, htk: bool = False,
                         norm="slaney", dtype: str = "float32"):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2]
    (reference: functional.py:189; librosa.filters.mel math)."""
    f_max = f_max or sr / 2.0
    fft_f = unwrap(fft_frequencies(sr, n_fft))
    mel_f = unwrap(mel_frequencies(n_mels + 2, f_min, f_max, htk))
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2: n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    return Tensor(weights.astype(dtype))


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: float = 80.0):
    """Power spectrogram -> dB (reference: functional.py:262)."""
    s = unwrap(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return Tensor(log_spec)


def create_dct(n_mfcc: int, n_mels: int, norm="ortho", dtype: str = "float32"):
    """DCT-II basis [n_mels, n_mfcc] (reference: functional.py:306)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[None, :]
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct = dct * math.sqrt(2.0 / n_mels)
        dct = dct.at[:, 0].multiply(1.0 / math.sqrt(2.0))
    else:
        dct = dct * 2.0
    return Tensor(dct.astype(dtype))


_WINDOWS = {
    "hann": lambda n: 0.5 - 0.5 * jnp.cos(2 * math.pi * jnp.arange(n) / n),
    "hamming": lambda n: 0.54 - 0.46 * jnp.cos(2 * math.pi * jnp.arange(n) / n),
    "blackman": lambda n: (0.42 - 0.5 * jnp.cos(2 * math.pi * jnp.arange(n) / n)
                           + 0.08 * jnp.cos(4 * math.pi * jnp.arange(n) / n)),
    "bohman": lambda n: _bohman(n),
    "triang": lambda n: 1 - jnp.abs(2 * jnp.arange(n) - (n - 1)) / n,
    "bartlett": lambda n: 1 - jnp.abs(2 * jnp.arange(n) - (n - 1)) / (n - 1),
    "rect": lambda n: jnp.ones(n),
    "cosine": lambda n: jnp.sin(math.pi / n * (jnp.arange(n) + 0.5)),
}


def _bohman(n):
    x = jnp.abs(jnp.linspace(-1, 1, n + 2)[1:-1])
    return (1 - x) * jnp.cos(math.pi * x) + jnp.sin(math.pi * x) / math.pi


def get_window(window, win_length: int, fftbins: bool = True,
               dtype: str = "float32"):
    """Window function by name (reference: window.py get_window).
    ``('kaiser', beta)`` / ``('gaussian', std)`` / ``('exponential', None, tau)``
    tuples supported like scipy."""
    if isinstance(window, (tuple, list)):
        name, *params = window
        if name == "kaiser":
            # periodic (fftbins=True): sample the symmetric N+1 window's
            # first N points; symmetric: plain np.kaiser(N)
            w = jnp.asarray(np.kaiser(win_length + (1 if fftbins else 0),
                                      params[0]))
            w = w[:win_length]
        elif name == "gaussian":
            half = (win_length - 1) / 2
            x = jnp.arange(win_length) - half
            w = jnp.exp(-0.5 * (x / params[0]) ** 2)
        elif name == "exponential":
            tau = params[-1]
            x = jnp.abs(jnp.arange(win_length) - (win_length - 1) / 2)
            w = jnp.exp(-x / tau)
        else:
            raise ValueError(f"unknown window {name}")
        return Tensor(w.astype(dtype))
    if window not in _WINDOWS:
        raise ValueError(f"unknown window {window}; options: {sorted(_WINDOWS)}")
    if fftbins:
        w = _WINDOWS[window](win_length)  # periodic: denominators use N
    else:
        # symmetric: the N-point symmetric window equals the first N points
        # of the (N)-denominator... i.e. evaluate with n = N-1 denominators
        w = _WINDOWS[window](win_length - 1)
        w = jnp.concatenate([jnp.asarray(w), jnp.asarray(w)[:1]])
    return Tensor(jnp.asarray(w, jnp.float32)[:win_length].astype(dtype))
