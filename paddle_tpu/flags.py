"""Global flag registry.

TPU-native analogue of the reference's flag system
(paddle/common/flags.cc:31 ``PHI_DEFINE_EXPORTED_*`` + python/paddle/base/framework.py:132
``set_flags``): a single process-wide registry, env-overridable via ``FLAGS_<name>``,
settable at runtime from Python.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict

_REGISTRY: Dict[str, "_Flag"] = {}


class _Flag:
    __slots__ = ("name", "default", "value", "doc", "parser")

    def __init__(self, name: str, default: Any, doc: str, parser: Callable[[str], Any]):
        self.name = name
        self.default = default
        self.doc = doc
        self.parser = parser
        env = os.environ.get("FLAGS_" + name)
        self.value = parser(env) if env is not None else default


def _parse_bool(s: str) -> bool:
    return str(s).strip().lower() in ("1", "true", "yes", "on")


def define_flag(name: str, default: Any, doc: str = "") -> None:
    if name in _REGISTRY:
        return
    if isinstance(default, bool):
        parser: Callable[[str], Any] = _parse_bool
    elif isinstance(default, int):
        parser = int
    elif isinstance(default, float):
        parser = float
    else:
        parser = str
    _REGISTRY[name] = _Flag(name, default, doc, parser)


def get_flags(names=None) -> Dict[str, Any]:
    """Return current flag values (all flags, or the requested subset)."""
    if names is None:
        return {k: f.value for k, f in _REGISTRY.items()}
    if isinstance(names, str):
        names = [names]
    return {n: _REGISTRY[n].value for n in names}


def set_flags(flags: Dict[str, Any]) -> None:
    """Set flags at runtime, mirroring ``paddle.set_flags``."""
    for name, value in flags.items():
        name = name[len("FLAGS_"):] if name.startswith("FLAGS_") else name
        if name not in _REGISTRY:
            define_flag(name, value)
        else:
            f = _REGISTRY[name]
            f.value = f.parser(value) if isinstance(value, str) and not isinstance(f.default, str) else value


def get_flag(name: str) -> Any:
    return _REGISTRY[name].value


# ---- core flags (subset of paddle/common/flags.cc relevant to the TPU build) ----
define_flag("check_nan_inf", False, "Check every op output for NaN/Inf in eager mode.")
define_flag("check_nan_inf_level", 0, "0: raise on nan/inf; >=1: warn only.")
define_flag("low_precision_op_list", 0, "Collect ops executed in low precision under AMP.")
define_flag("use_pallas_attention", True, "Use the Pallas flash-attention kernel when on TPU.")
define_flag("eager_delete_tensor_gb", 0.0, "Kept for API parity; XLA owns memory on TPU.")
define_flag("benchmark", False, "Synchronize after each op (eager) for timing.")
define_flag("paddle_tpu_log_level", 0, "Framework verbose log level (VLOG analogue).")
define_flag("cudnn_deterministic", False, "Parity alias: request deterministic XLA reductions.")
define_flag("embedding_deterministic", 0, "Parity alias for deterministic embedding grads.")
define_flag("use_autotune", True, "Let XLA autotune (latency-hiding scheduler etc.).")
