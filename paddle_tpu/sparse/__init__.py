"""paddle_tpu.sparse (reference: python/paddle/sparse).

TPU-native note: XLA has no native sparse tensors; the reference's SparseCooTensor /
SparseCsrTensor (phi/core/sparse_coo_tensor.h) are represented here as
(indices, values, shape) triples with ops implemented via scatter/gather — dense on
the MXU where it matters (sparse @ dense lowers to a gather + dense matmul).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, unwrap


class SparseCooTensor:
    def __init__(self, indices, values, shape):
        self.indices = indices  # [ndim, nnz]
        self.values = values  # [nnz, ...]
        self._shape = list(shape)

    @property
    def shape(self):
        return list(self._shape)

    def to_dense(self):
        idx = unwrap(self.indices)
        vals = unwrap(self.values)
        dense = jnp.zeros(tuple(self._shape[: idx.shape[0]]) + tuple(vals.shape[1:]), vals.dtype)
        return Tensor(dense.at[tuple(idx)].add(vals))

    def values_tensor(self):
        return self.values

    def nnz(self):
        return unwrap(self.values).shape[0]


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None, stop_gradient=True):
    indices = indices if isinstance(indices, Tensor) else Tensor(np.asarray(indices))
    values = values if isinstance(values, Tensor) else Tensor(np.asarray(values), dtype=dtype)
    if shape is None:
        idx = np.asarray(unwrap(indices))
        shape = (idx.max(axis=1) + 1).tolist() + list(np.asarray(unwrap(values)).shape[1:])
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None, stop_gradient=True):
    crows_np = np.asarray(unwrap(crows) if isinstance(crows, Tensor) else crows)
    cols_np = np.asarray(unwrap(cols) if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    indices = Tensor(np.stack([rows, cols_np]))
    vals = values if isinstance(values, Tensor) else Tensor(np.asarray(values), dtype=dtype)
    return SparseCooTensor(indices, vals, shape)


def matmul(x, y):
    """sparse @ dense -> dense (values-gather + segment-sum)."""
    if isinstance(x, SparseCooTensor):
        return x.to_dense().matmul(y)
    return x.matmul(y)


def add(x, y):
    xd = x.to_dense() if isinstance(x, SparseCooTensor) else x
    yd = y.to_dense() if isinstance(y, SparseCooTensor) else y
    return xd + yd


class nn:
    """Sparse NN layers land with the GNN suite; conv3d/subm_conv3d tracked in docs/PARITY.md."""
