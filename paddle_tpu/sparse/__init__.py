"""paddle_tpu.sparse (reference: python/paddle/sparse — SparseCooTensor /
SparseCsrTensor over phi/core/sparse_coo_tensor.h, unary.py ~30 value-wise ops,
binary.py add/subtract/multiply/divide + matmul/masked_matmul, nn/ ReLU etc.).

TPU-native design: COO tensors are backed by ``jax.experimental.sparse.BCOO``
— XLA lowers sparse@dense to gather + dense dot (MXU) and keeps everything
jit-compatible. Value-wise ops that preserve the zero pattern (sin, relu, …)
run on the values buffer only, like the reference's sparse unary kernels
(phi/kernels/sparse/unary_kernel.h). CSR is stored as (crows, cols, values)
and converts through COO for compute, mirroring the reference's
SparseCsrTensor -> SparseCooTensor casts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor, unwrap

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "matmul", "masked_matmul", "add", "subtract",
    "multiply", "divide", "is_same_shape", "transpose", "coalesce",
    "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh", "atanh",
    "sqrt", "square", "log1p", "abs", "pow", "neg", "expm1", "relu",
    "relu6", "leaky_relu", "softmax", "cast", "nn",
]


class SparseCooTensor:
    """COO sparse tensor (reference: phi/core/sparse_coo_tensor.h).
    ``indices``: [sparse_ndim, nnz]; ``values``: [nnz, ...dense dims]."""

    def __init__(self, indices, values, shape):
        self.indices = indices if isinstance(indices, Tensor) else Tensor(indices)
        self.values = values if isinstance(values, Tensor) else Tensor(values)
        self._shape = [int(s) for s in shape]

    # -- properties --
    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def ndim(self):
        return len(self._shape)

    def nnz(self):
        return int(unwrap(self.values).shape[0])

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    # -- converters --
    def _bcoo(self) -> jsparse.BCOO:
        idx = unwrap(self.indices).T  # BCOO wants [nnz, ndim]
        return jsparse.BCOO((unwrap(self.values), idx),
                            shape=tuple(self._shape))

    @classmethod
    def _from_bcoo(cls, m: jsparse.BCOO) -> "SparseCooTensor":
        return cls(Tensor(m.indices.T), Tensor(m.data), m.shape)

    def to_dense(self) -> Tensor:
        from ..core.op_registry import apply_fn

        shape = tuple(self._shape)
        sparse_nd = unwrap(self.indices).shape[0]

        def fn(idx, vals):
            dense = jnp.zeros(shape[:sparse_nd] + vals.shape[1:], vals.dtype)
            return dense.at[tuple(idx)].add(vals)

        return apply_fn("sparse_to_dense", fn, self.indices, self.values)

    def to_sparse_csr(self) -> "SparseCsrTensor":
        if len(self._shape) != 2:
            raise ValueError("to_sparse_csr supports 2-D tensors")
        t = coalesce(self)
        idx = np.asarray(unwrap(t.indices))
        vals = unwrap(t.values)
        n_rows = self._shape[0]
        crows = np.zeros(n_rows + 1, np.int64)
        np.add.at(crows[1:], idx[0], 1)
        crows = np.cumsum(crows)
        return SparseCsrTensor(Tensor(crows), Tensor(idx[1]), Tensor(vals),
                               self._shape)

    def values_tensor(self):
        return self.values

    def _replace_values(self, new_values) -> "SparseCooTensor":
        return SparseCooTensor(self.indices, new_values, self._shape)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self._shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse tensor (reference: phi/core/sparse_csr_tensor.h)."""

    def __init__(self, crows, cols, values, shape):
        self.crows = crows if isinstance(crows, Tensor) else Tensor(crows)
        self.cols = cols if isinstance(cols, Tensor) else Tensor(cols)
        self.values = values if isinstance(values, Tensor) else Tensor(values)
        self._shape = [int(s) for s in shape]

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self.values.dtype

    def nnz(self):
        return int(unwrap(self.values).shape[0])

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def to_sparse_coo(self, sparse_dim=2) -> SparseCooTensor:
        crows = np.asarray(unwrap(self.crows))
        rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
        indices = Tensor(np.stack([rows, np.asarray(unwrap(self.cols))]))
        return SparseCooTensor(indices, self.values, self._shape)

    def to_dense(self) -> Tensor:
        return self.to_sparse_coo().to_dense()

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self._shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


# ---------------------------------------------------------------------------
# constructors
# ---------------------------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    indices = indices if isinstance(indices, Tensor) else Tensor(np.asarray(indices))
    values = values if isinstance(values, Tensor) else Tensor(np.asarray(values), dtype=dtype)
    if shape is None:
        idx = np.asarray(unwrap(indices))
        shape = (idx.max(axis=1) + 1).tolist() + list(np.asarray(unwrap(values)).shape[1:])
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    vals = values if isinstance(values, Tensor) else Tensor(np.asarray(values), dtype=dtype)
    return SparseCsrTensor(crows, cols, vals, shape)


def _coo(x) -> SparseCooTensor:
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


# ---------------------------------------------------------------------------
# structure ops
# ---------------------------------------------------------------------------

def coalesce(x: SparseCooTensor) -> SparseCooTensor:
    """Merge duplicate indices (reference: sparse/coalesce kernel)."""
    x = _coo(x)
    m = x._bcoo().sum_duplicates(remove_zeros=False)
    return SparseCooTensor._from_bcoo(m)


def transpose(x, perm):
    x = _coo(x)
    idx = unwrap(x.indices)
    new_idx = jnp.stack([idx[p] for p in perm])
    new_shape = [x.shape[p] for p in perm]
    return SparseCooTensor(Tensor(new_idx), x.values, new_shape)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


def cast(x, index_dtype=None, value_dtype=None):
    x = _coo(x)
    idx = x.indices if index_dtype is None else Tensor(unwrap(x.indices).astype(index_dtype))
    vals = x.values if value_dtype is None else x.values.astype(value_dtype)
    return SparseCooTensor(idx, vals, x.shape)


# ---------------------------------------------------------------------------
# matmul
# ---------------------------------------------------------------------------

def matmul(x, y):
    """sparse @ dense -> dense via BCOO dot_general (XLA: gather + MXU dot);
    dense @ sparse and sparse @ sparse supported through the same path."""
    from ..core.op_registry import apply_fn

    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        xc = _coo(x)
        shape = tuple(xc.shape)

        def fn(idx, vals, d):
            m = jsparse.BCOO((vals, idx.T), shape=shape)
            return jsparse.bcoo_dot_general(
                m, d, dimension_numbers=(((1,), (0,)), ((), ())))

        yv = y.to_dense() if isinstance(y, (SparseCooTensor, SparseCsrTensor)) else y
        return apply_fn("sparse_matmul", fn, xc.indices, xc.values, yv)
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        # dense @ sparse == (sparse.T @ dense.T).T
        yt = transpose(_coo(y), [1, 0])
        xt = x.t() if hasattr(x, "t") else Tensor(unwrap(x).T)
        return matmul(yt, xt).t()
    return x.matmul(y)


def masked_matmul(x, y, mask):
    """(dense @ dense) sampled at mask's sparsity pattern
    (reference: sparse/binary.py masked_matmul — the SDDMM kernel)."""
    from ..core.op_registry import apply_fn

    mask = _coo(mask)

    def fn(idx, xd, yd):
        rows, cols = idx[0], idx[1]
        # gather the needed rows/cols, contract feature dim: one fused gather+dot
        vals = jnp.einsum("nk,nk->n", xd[rows], yd[:, cols].T)
        return vals

    vals = apply_fn("masked_matmul", fn, mask.indices, x, y)
    return SparseCooTensor(mask.indices, vals, mask.shape)


# ---------------------------------------------------------------------------
# binary value ops
# ---------------------------------------------------------------------------

def _union_binary(name, negate):
    """add/subtract: pattern union via BCOO sum_duplicates. The result keeps a
    fixed nse = nnz(x)+nnz(y) (duplicates padded with out-of-range indices,
    which scatter drops in to_dense); ``coalesce()`` compacts eagerly.
    Autograd flows through the values (apply_fn tape)."""

    def f(x, y):
        from ..core.op_registry import apply_fn

        if isinstance(x, (SparseCooTensor, SparseCsrTensor)) and isinstance(
                y, (SparseCooTensor, SparseCsrTensor)):
            xc, yc = _coo(x), _coo(y)
            shape = tuple(xc.shape)

            def fn(xi, xv, yi, yv):
                sv = -yv if negate else yv
                idx = jnp.concatenate([xi, yi], axis=1)
                vals = jnp.concatenate([xv, sv], axis=0)
                m = jsparse.BCOO((vals, idx.T), shape=shape).sum_duplicates(
                    nse=xv.shape[0] + yv.shape[0])
                return m.indices.T, m.data

            idx_t, vals_t = apply_fn(f"sparse_{name}", fn, xc.indices,
                                     xc.values, yc.indices, yc.values)
            return SparseCooTensor(idx_t, vals_t, shape)
        xd = x.to_dense() if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x
        yd = y.to_dense() if isinstance(y, (SparseCooTensor, SparseCsrTensor)) else y
        from ..core.op_registry import apply_fn as af

        return af(name, (lambda a, b: a - b) if negate else (lambda a, b: a + b),
                  xd, yd)

    f.__name__ = name
    return f


def _pattern_binary(name, op):
    """multiply/divide: evaluated on x's sparsity pattern (the intersection
    semantics of the reference's sparse elementwise kernels — positions outside
    x's pattern are structural zeros of the result). y is gathered at x's
    indices, so no NaN/Inf appears at structural zeros."""

    def f(x, y):
        from ..core.op_registry import apply_fn

        if isinstance(x, (SparseCooTensor, SparseCsrTensor)) and isinstance(
                y, (SparseCooTensor, SparseCsrTensor)):
            xc, yc = _coo(x), _coo(y)
            shape = tuple(xc.shape)

            def fn(xi, xv, yi, yv):
                yd = jsparse.BCOO((yv, yi.T), shape=shape).todense()
                return op(xv, yd[tuple(xi)])

            vals = apply_fn(f"sparse_{name}", fn, xc.indices, xc.values,
                            yc.indices, yc.values)
            return SparseCooTensor(xc.indices, vals, shape)
        xd = x.to_dense() if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else x
        yd = y.to_dense() if isinstance(y, (SparseCooTensor, SparseCsrTensor)) else y
        from ..core.op_registry import apply_fn as af

        return af(name, op, xd, yd)

    f.__name__ = name
    return f


add = _union_binary("add", negate=False)
subtract = _union_binary("subtract", negate=True)
multiply = _pattern_binary("multiply", lambda a, b: a * b)
divide = _pattern_binary("divide", lambda a, b: a / b)


# ---------------------------------------------------------------------------
# unary value ops (zero-preserving => operate on values only)
# ---------------------------------------------------------------------------

def _unary(name, fn):
    def f(x):
        from ..core.op_registry import apply_fn

        if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
            # zero-preserving: the op touches values only; CSR stays CSR
            new_vals = apply_fn(f"sparse_{name}", fn, x.values)
            if isinstance(x, SparseCsrTensor):
                return SparseCsrTensor(x.crows, x.cols, new_vals, x.shape)
            return x._replace_values(new_vals)
        return apply_fn(name, fn, x)

    f.__name__ = name
    return f


sin = _unary("sin", jnp.sin)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
log1p = _unary("log1p", jnp.log1p)
abs = _unary("abs", jnp.abs)
neg = _unary("neg", jnp.negative)
expm1 = _unary("expm1", jnp.expm1)
relu = _unary("relu", jax.nn.relu)
relu6 = _unary("relu6", lambda a: jnp.clip(a, 0, 6))


def leaky_relu(x, negative_slope=0.01):
    return _unary("leaky_relu",
                  lambda a: jnp.where(a >= 0, a, negative_slope * a))(x)


def pow(x, factor):
    return _unary("pow", lambda a: jnp.power(a, factor))(x)


def softmax(x, axis=-1):
    """Row-wise softmax over the sparsity pattern (reference:
    sparse/nn/functional softmax — used for sparse attention)."""
    from ..core.op_registry import apply_fn

    xc = _coo(x) if isinstance(x, (SparseCooTensor, SparseCsrTensor)) else None
    if xc is None:
        from ..nn import functional as F

        return F.softmax(x, axis=axis)
    if len(xc.shape) != 2 or axis not in (-1, 1):
        raise ValueError("sparse softmax supports 2-D tensors over the last axis")
    n_rows = xc.shape[0]

    def fn(idx, vals):
        rows = idx[0]
        row_max = jax.ops.segment_max(vals, rows, num_segments=n_rows)
        e = jnp.exp(vals - row_max[rows])
        denom = jax.ops.segment_sum(e, rows, num_segments=n_rows)
        return e / denom[rows]

    return xc._replace_values(apply_fn("sparse_softmax", fn, xc.indices, xc.values))


class nn:
    """sparse.nn layers (reference: python/paddle/sparse/nn)."""

    class ReLU:
        def __call__(self, x):
            return relu(x)

    class LeakyReLU:
        def __init__(self, negative_slope=0.01):
            self.negative_slope = negative_slope

        def __call__(self, x):
            return leaky_relu(x, self.negative_slope)

        # conv3d/subm_conv3d (point-cloud path) intentionally not implemented:
        # no MXU-friendly lowering without a gather-scatter conv engine.

    class Softmax:
        def __init__(self, axis=-1):
            self.axis = axis

        def __call__(self, x):
            return softmax(x, self.axis)
