from .modeling import UNet2DConditionModel, UNetConfig, timestep_embedding  # noqa: F401
