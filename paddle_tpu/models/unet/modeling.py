"""Diffusion UNet (bench config #5: Stable-Diffusion-class UNet through the
compiler path).

Reference anchor: the reference's bench target exercises conv + cross-
attention through CINN (/root/reference/paddle/fluid/pir/transforms/
build_cinn_pass.cc:31); here the whole UNet is one XLA program — conv (lax),
GroupNorm, SiLU, timestep embeddings, self+cross attention mid-blocks.

Compact UNet2DConditionModel shape: down blocks (res+attn, downsample),
mid (res, cross-attn, res), up blocks with skip concats.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as _np

from ...core.tensor import Tensor
from ...nn import initializer as I
from ...nn.layer.layers import Layer, LayerList


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


class UNetConfig:
    def __init__(self, in_channels=4, out_channels=4,
                 block_channels=(128, 256, 512), layers_per_block=2,
                 num_heads=8, cross_attention_dim=768, groups=32,
                 dtype="float32", recompute=False):
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.block_channels = tuple(block_channels)
        self.layers_per_block = layers_per_block
        self.num_heads = num_heads
        self.cross_attention_dim = cross_attention_dim
        self.groups = groups
        self.dtype = dtype
        self.recompute = recompute

    @classmethod
    def tiny(cls, **over):
        d = dict(in_channels=4, out_channels=4, block_channels=(32, 64),
                 layers_per_block=1, num_heads=4, cross_attention_dim=32,
                 groups=8)
        d.update(over)
        return cls(**d)


def timestep_embedding(t, dim: int):
    """Sinusoidal timestep embedding [b] -> [b, dim] (DDPM convention)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(10000.0) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def _conv(x, w, b, stride=1, padding=1):
    out = jax.lax.conv_general_dilated(
        x, w, (stride, stride), [(padding, padding)] * 2,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return out + b[None, :, None, None]


def _group_norm(x, w, b, groups, eps=1e-5):
    n, c, h, wd = x.shape
    g = min(groups, c)
    xf = x.astype(jnp.float32).reshape(n, g, c // g, h, wd)
    mu = xf.mean((2, 3, 4), keepdims=True)
    var = xf.var((2, 3, 4), keepdims=True)
    xf = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (xf.reshape(n, c, h, wd).astype(x.dtype) * w[None, :, None, None]
            + b[None, :, None, None])


class ResBlock(Layer):
    def __init__(self, cfg: UNetConfig, cin: int, cout: int, temb_dim: int):
        super().__init__()
        self.cfg = cfg
        init = I.KaimingNormal()
        mk = lambda shape, ini=init: self.create_parameter(
            shape, dtype=cfg.dtype, default_initializer=ini)
        self.norm1_w = mk([cin], I.Constant(1.0))
        self.norm1_b = mk([cin], I.Constant(0.0))
        self.conv1_w = mk([cout, cin, 3, 3])
        self.conv1_b = mk([cout], I.Constant(0.0))
        self.temb_w = mk([temb_dim, cout])
        self.temb_b = mk([cout], I.Constant(0.0))
        self.norm2_w = mk([cout], I.Constant(1.0))
        self.norm2_b = mk([cout], I.Constant(0.0))
        self.conv2_w = mk([cout, cout, 3, 3])
        self.conv2_b = mk([cout], I.Constant(0.0))
        self.skip_w = mk([cout, cin, 1, 1]) if cin != cout else None

    def forward(self, x, temb):
        x = _unwrap(x)
        h = _group_norm(x, self.norm1_w._data, self.norm1_b._data, self.cfg.groups)
        h = _conv(jax.nn.silu(h), self.conv1_w._data, self.conv1_b._data)
        t = jnp.matmul(jax.nn.silu(temb), self.temb_w._data) + self.temb_b._data
        h = h + t[:, :, None, None]
        h = _group_norm(h, self.norm2_w._data, self.norm2_b._data, self.cfg.groups)
        h = _conv(jax.nn.silu(h), self.conv2_w._data, self.conv2_b._data)
        if self.skip_w is not None:
            x = jax.lax.conv_general_dilated(
                x, self.skip_w._data, (1, 1), [(0, 0)] * 2,
                dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return x + h


class CrossAttnBlock(Layer):
    """Spatial self-attention + cross-attention to the text context."""

    def __init__(self, cfg: UNetConfig, channels: int):
        super().__init__()
        self.cfg = cfg
        self.channels = channels
        init = I.XavierUniform()
        mk = lambda shape, ini=init: self.create_parameter(
            shape, dtype=cfg.dtype, default_initializer=ini)
        self.norm_w = mk([channels], I.Constant(1.0))
        self.norm_b = mk([channels], I.Constant(0.0))
        self.q_self = mk([channels, channels])
        self.kv_self = mk([channels, 2 * channels])
        self.proj_self = mk([channels, channels])
        self.q_cross = mk([channels, channels])
        self.k_cross = mk([cfg.cross_attention_dim, channels])
        self.v_cross = mk([cfg.cross_attention_dim, channels])
        self.proj_cross = mk([channels, channels])

    def _attn(self, q, k, v):
        nh = self.cfg.num_heads
        b, nq, c = q.shape
        hd = c // nh
        q = q.reshape(b, nq, nh, hd)
        k = k.reshape(b, k.shape[1], nh, hd)
        v = v.reshape(b, v.shape[1], nh, hd)
        from ...nn.functional.flash_attention import _xla_attention

        return _xla_attention(q, k, v, causal=False).reshape(b, nq, c)

    def forward(self, x, context):
        x = _unwrap(x)
        b, c, h, w = x.shape
        y = _group_norm(x, self.norm_w._data, self.norm_b._data, self.cfg.groups)
        y = y.reshape(b, c, h * w).transpose(0, 2, 1)  # [b, hw, c]
        # self-attention
        q = jnp.matmul(y, self.q_self._data)
        kv = jnp.matmul(y, self.kv_self._data)
        k, v = jnp.split(kv, 2, axis=-1)
        y = y + jnp.matmul(self._attn(q, k, v), self.proj_self._data)
        # cross-attention to context [b, n_ctx, cross_dim]
        ctx = _unwrap(context)
        q = jnp.matmul(y, self.q_cross._data)
        k = jnp.matmul(ctx, self.k_cross._data)
        v = jnp.matmul(ctx, self.v_cross._data)
        y = y + jnp.matmul(self._attn(q, k, v), self.proj_cross._data)
        y = y.transpose(0, 2, 1).reshape(b, c, h, w)
        return x + y


class UNet2DConditionModel(Layer):
    def __init__(self, cfg: UNetConfig):
        super().__init__()
        self.config = cfg
        chs = cfg.block_channels
        temb_dim = chs[0] * 4
        self.temb_dim0 = chs[0]
        mk = lambda shape, ini: self.create_parameter(
            shape, dtype=cfg.dtype, default_initializer=ini)
        init = I.KaimingNormal()
        self.temb_w1 = mk([chs[0], temb_dim], init)
        self.temb_b1 = mk([temb_dim], I.Constant(0.0))
        self.temb_w2 = mk([temb_dim, temb_dim], init)
        self.temb_b2 = mk([temb_dim], I.Constant(0.0))
        self.conv_in_w = mk([chs[0], cfg.in_channels, 3, 3], init)
        self.conv_in_b = mk([chs[0]], I.Constant(0.0))

        self.down_res = LayerList()
        self.down_attn = LayerList()
        self.downsamplers = []
        cin = chs[0]
        for i, ch in enumerate(chs):
            for _ in range(cfg.layers_per_block):
                self.down_res.append(ResBlock(cfg, cin, ch, temb_dim))
                self.down_attn.append(CrossAttnBlock(cfg, ch))
                cin = ch
            self.downsamplers.append(i < len(chs) - 1)

        self.mid1 = ResBlock(cfg, chs[-1], chs[-1], temb_dim)
        self.mid_attn = CrossAttnBlock(cfg, chs[-1])
        self.mid2 = ResBlock(cfg, chs[-1], chs[-1], temb_dim)

        self.up_res = LayerList()
        self.up_attn = LayerList()
        for i, ch in enumerate(reversed(chs)):
            for _ in range(cfg.layers_per_block):
                self.up_res.append(ResBlock(cfg, cin + ch, ch, temb_dim))
                self.up_attn.append(CrossAttnBlock(cfg, ch))
                cin = ch

        self.norm_out_w = mk([chs[0]], I.Constant(1.0))
        self.norm_out_b = mk([chs[0]], I.Constant(0.0))
        self.conv_out_w = mk([cfg.out_channels, chs[0], 3, 3], init)
        self.conv_out_b = mk([cfg.out_channels], I.Constant(0.0))

    def forward(self, sample, timesteps, encoder_hidden_states):
        cfg = self.config
        dt = self.conv_in_w._data.dtype
        # activations follow the parameter dtype (bf16 training runs the
        # convs/matmuls on the MXU bf16 path; groupnorm stays fp32 inside)
        x = _unwrap(sample).astype(dt)
        t = _unwrap(timesteps)
        ctx = _unwrap(encoder_hidden_states).astype(dt)
        temb = timestep_embedding(t, self.temb_dim0).astype(dt)
        temb = jnp.matmul(jax.nn.silu(
            jnp.matmul(temb, self.temb_w1._data) + self.temb_b1._data),
            self.temb_w2._data) + self.temb_b2._data

        x = _conv(x, self.conv_in_w._data, self.conv_in_b._data)
        skips = []
        li = 0
        for i, ch in enumerate(cfg.block_channels):
            for _ in range(cfg.layers_per_block):
                x = self.down_res[li](x, temb)
                x = self.down_attn[li](x, ctx)
                skips.append(x)
                li += 1
            if self.downsamplers[i]:
                # init must be a CONCRETE scalar (reduce_window's vjp
                # rejects traced inits) of the activation dtype
                x = jax.lax.reduce_window(
                    x, _np.zeros((), x.dtype)[()], jax.lax.add, (1, 1, 2, 2),
                    (1, 1, 2, 2), "VALID") / jnp.asarray(4.0, x.dtype)

        x = self.mid1(x, temb)
        x = self.mid_attn(x, ctx)
        x = self.mid2(x, temb)

        li = 0
        for i, ch in enumerate(reversed(cfg.block_channels)):
            for _ in range(cfg.layers_per_block):
                skip = skips.pop()
                if skip.shape[2] != x.shape[2]:
                    # nearest-neighbor 2x upsample to the skip's resolution
                    x = jnp.repeat(jnp.repeat(x, 2, axis=2), 2, axis=3)
                x = jnp.concatenate([x, skip], axis=1)
                x = self.up_res[li](x, temb)
                x = self.up_attn[li](x, ctx)
                li += 1

        x = jax.nn.silu(_group_norm(x, self.norm_out_w._data,
                                    self.norm_out_b._data, cfg.groups))
        return _conv(x, self.conv_out_w._data, self.conv_out_b._data)

    def loss_fn(self, batch, labels=None):
        """ε-prediction MSE (DDPM training objective). ``batch`` is a dict of
        arrays {sample, timesteps, context, noise}."""
        eps = self.forward(batch["sample"], batch["timesteps"], batch["context"])
        target = _unwrap(batch["noise"])
        return jnp.mean((eps.astype(jnp.float32) - target.astype(jnp.float32)) ** 2)
