"""paddle_tpu.models — flagship model families.

The reference ships model zoos via PaddleNLP/vision; in-tree it exercises Llama/GPT
through distributed tests (/root/reference/test/auto_parallel/hybrid_strategy/
semi_auto_llama.py:33, test/auto_parallel GPT tests). Here the model families are
first-class: mesh-aware (logical-axis sharding), remat-capable, jit-first.
"""

from . import bert  # noqa: F401
from . import gpt  # noqa: F401
from . import llama  # noqa: F401
from . import unet  # noqa: F401
from .bert import BertConfig, BertForMaskedLM, BertForSequenceClassification  # noqa: F401
from .gpt import GPTConfig, GPTForCausalLM  # noqa: F401
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel  # noqa: F401
from .unet import UNet2DConditionModel, UNetConfig  # noqa: F401
