"""BERT family — bidirectional encoder (bench config #2: BERT/ERNIE fine-tune).

Parity anchor: the reference exercises BERT/ERNIE through its AMP + fleet
tests (cf. /root/reference/python/paddle/amp/auto_cast.py:1014 usage docs,
test/collective/fleet hybrid tests); architecture follows the canonical
encoder: learned positions + token types, post-LN transformer, gelu FFN,
pooler, MLM + sequence-classification heads.

Same TPU-native convention as llama/modeling.py: plain Layers with logical
axis annotations; tp/fsdp/sep sharding comes from mesh rules + GSPMD.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...distributed.auto_parallel.logical_sharding import annotate, constrain, current_mesh
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer.layers import Layer, LayerList


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, max_position_embeddings=512,
                 type_vocab_size=2, layer_norm_eps=1e-12,
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 initializer_range=0.02, dtype="float32", recompute=False,
                 num_labels=2):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.layer_norm_eps = layer_norm_eps
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.initializer_range = initializer_range
        self.dtype = dtype
        self.recompute = recompute
        self.num_labels = num_labels

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    def num_params(self) -> int:
        h, v = self.hidden_size, self.vocab_size
        per_layer = 4 * h * h + 2 * h * self.intermediate_size + 13 * h
        emb = (v + self.max_position_embeddings + self.type_vocab_size) * h
        return emb + self.num_hidden_layers * per_layer + 2 * h * h

    @classmethod
    def tiny(cls, **over):
        d = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                 num_attention_heads=4, intermediate_size=128,
                 max_position_embeddings=128, hidden_dropout_prob=0.0,
                 attention_probs_dropout_prob=0.0)
        d.update(over)
        return cls(**d)


def _mk(layer, shape, config, init=None):
    init = init or I.Normal(std=config.initializer_range)
    return layer.create_parameter(shape, dtype=config.dtype,
                                  default_initializer=init)


class BertEmbeddings(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        self.word_embeddings_weight = annotate(
            _mk(self, [config.vocab_size, h], config), "vocab", "embed")
        self.position_embeddings_weight = annotate(
            _mk(self, [config.max_position_embeddings, h], config), None, "embed")
        self.token_type_embeddings_weight = annotate(
            _mk(self, [config.type_vocab_size, h], config), None, "embed")
        self.ln_weight = _mk(self, [h], config, I.Constant(1.0))
        self.ln_bias = _mk(self, [h], config, I.Constant(0.0))

    def forward(self, input_ids, token_type_ids=None):
        ids = input_ids._data if isinstance(input_ids, Tensor) else input_ids
        s = ids.shape[1]
        x = jnp.take(self.word_embeddings_weight._data, ids, axis=0)
        x = x + self.position_embeddings_weight._data[:s][None]
        if token_type_ids is not None:
            tt = token_type_ids._data if isinstance(token_type_ids, Tensor) else token_type_ids
            x = x + jnp.take(self.token_type_embeddings_weight._data, tt, axis=0)
        x = _layer_norm(x, self.ln_weight._data, self.ln_bias._data,
                        self.config.layer_norm_eps)
        x = _maybe_dropout(x, self.config.hidden_dropout_prob, self.training)
        return constrain(x, "batch", "seq", "embed")


def _layer_norm(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    return ((xf - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w + b


def _maybe_dropout(x, p, training):
    if not training or p == 0.0:
        return x
    from ...framework.random import next_key

    keep = jax.random.bernoulli(next_key(), 1.0 - p, x.shape)
    return jnp.where(keep, x / (1.0 - p), jnp.zeros_like(x))


def _encoder_attention(q, k, v, config):
    """Bidirectional SDPA; Pallas flash kernel on a bare TPU, XLA path
    otherwise (mesh sharding handled by GSPMD through constrain specs)."""
    from ...nn.functional.flash_attention import _xla_attention

    mesh = current_mesh()
    if (mesh is None or mesh.size == 1) and jax.devices()[0].platform == "tpu":
        from ...ops.flash_attention import flash_attention as fa

        return fa(q, k, v, causal=False)
    return _xla_attention(q, k, v, causal=False)


class BertSelfAttention(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        h, nh, hd = config.hidden_size, config.num_attention_heads, config.head_dim
        self.num_heads, self.hd = nh, hd
        self.q_weight = annotate(_mk(self, [h, h], config), "embed", "heads")
        self.q_bias = _mk(self, [h], config, I.Constant(0.0))
        self.k_weight = annotate(_mk(self, [h, h], config), "embed", "heads")
        self.k_bias = _mk(self, [h], config, I.Constant(0.0))
        self.v_weight = annotate(_mk(self, [h, h], config), "embed", "heads")
        self.v_bias = _mk(self, [h], config, I.Constant(0.0))
        self.out_weight = annotate(_mk(self, [h, h], config), "heads", "embed")
        self.out_bias = _mk(self, [h], config, I.Constant(0.0))

    def forward(self, x):
        x = x._data if isinstance(x, Tensor) else x
        b, s, h = x.shape
        nh, hd = self.num_heads, self.hd
        q = (jnp.matmul(x, self.q_weight._data) + self.q_bias._data).reshape(b, s, nh, hd)
        k = (jnp.matmul(x, self.k_weight._data) + self.k_bias._data).reshape(b, s, nh, hd)
        v = (jnp.matmul(x, self.v_weight._data) + self.v_bias._data).reshape(b, s, nh, hd)
        q = constrain(q, "batch", "seq", "heads", "head_dim")
        k = constrain(k, "batch", "seq", "heads", "head_dim")
        v = constrain(v, "batch", "seq", "heads", "head_dim")
        out = _encoder_attention(q, k, v, self.config)
        out = out.reshape(b, s, h)
        out = jnp.matmul(out, self.out_weight._data) + self.out_bias._data
        return constrain(out, "batch", "seq", "embed")


class BertLayer(Layer):
    """Post-LN encoder block (original BERT ordering)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        h, m = config.hidden_size, config.intermediate_size
        self.attention = BertSelfAttention(config)
        self.attn_ln_weight = _mk(self, [h], config, I.Constant(1.0))
        self.attn_ln_bias = _mk(self, [h], config, I.Constant(0.0))
        self.inter_weight = annotate(_mk(self, [h, m], config), "embed", "mlp")
        self.inter_bias = _mk(self, [m], config, I.Constant(0.0))
        self.out_weight = annotate(_mk(self, [m, h], config), "mlp", "embed")
        self.out_bias = _mk(self, [h], config, I.Constant(0.0))
        self.out_ln_weight = _mk(self, [h], config, I.Constant(1.0))
        self.out_ln_bias = _mk(self, [h], config, I.Constant(0.0))

    def forward(self, x):
        x = x._data if isinstance(x, Tensor) else x
        eps = self.config.layer_norm_eps
        a = self.attention(x)
        a = _maybe_dropout(a, self.config.hidden_dropout_prob, self.training)
        x = _layer_norm(x + a, self.attn_ln_weight._data,
                        self.attn_ln_bias._data, eps)
        f = jnp.matmul(x, self.inter_weight._data) + self.inter_bias._data
        f = jax.nn.gelu(f, approximate=False)
        f = constrain(f, "batch", "seq", "mlp")
        f = jnp.matmul(f, self.out_weight._data) + self.out_bias._data
        f = _maybe_dropout(f, self.config.hidden_dropout_prob, self.training)
        x = _layer_norm(x + f, self.out_ln_weight._data,
                        self.out_ln_bias._data, eps)
        return constrain(x, "batch", "seq", "embed")


class BertModel(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.layers = LayerList([BertLayer(config)
                                 for _ in range(config.num_hidden_layers)])
        h = config.hidden_size
        self.pooler_weight = annotate(_mk(self, [h, h], config), "embed", None)
        self.pooler_bias = _mk(self, [h], config, I.Constant(0.0))

    def forward(self, input_ids, token_type_ids=None):
        x = self.embeddings(input_ids, token_type_ids)
        x = x._data if isinstance(x, Tensor) else x
        for layer in self.layers:
            if self.config.recompute and self.training:
                x = jax.checkpoint(lambda a, _l=layer: _unwrap(_l(a)))(x)
            else:
                x = _unwrap(layer(x))
        pooled = jnp.tanh(jnp.matmul(x[:, 0], self.pooler_weight._data)
                          + self.pooler_bias._data)
        return x, pooled


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


class BertForMaskedLM(Layer):
    """MLM head tied to the word embeddings (bench config #2 pretrain-style)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        self.mlm_bias = _mk(self, [config.vocab_size], config, I.Constant(0.0))

    def forward(self, input_ids, token_type_ids=None):
        x, _ = self.bert(input_ids, token_type_ids)
        logits = jnp.matmul(x, self.bert.embeddings.word_embeddings_weight._data.T)
        return logits + self.mlm_bias._data

    def loss_fn(self, input_ids, labels):
        """Masked-LM CE; label -100 positions are ignored (HF convention)."""
        logits = self.forward(input_ids)
        logits = _unwrap(logits).astype(jnp.float32)
        lbl = _unwrap(labels)
        mask = (lbl != -100)
        safe = jnp.where(mask, lbl, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        denom = jnp.maximum(mask.sum(), 1)
        return jnp.where(mask, nll, 0.0).sum() / denom


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        self.classifier_weight = _mk(self, [config.hidden_size,
                                            config.num_labels], config)
        self.classifier_bias = _mk(self, [config.num_labels], config,
                                   I.Constant(0.0))

    def forward(self, input_ids, token_type_ids=None):
        _, pooled = self.bert(input_ids, token_type_ids)
        pooled = _maybe_dropout(pooled, self.config.hidden_dropout_prob,
                                self.training)
        return jnp.matmul(pooled, self.classifier_weight._data) + self.classifier_bias._data

    def loss_fn(self, input_ids, labels):
        logits = _unwrap(self.forward(input_ids)).astype(jnp.float32)
        lbl = _unwrap(labels)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, lbl[..., None], axis=-1).mean()
