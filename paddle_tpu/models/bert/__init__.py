from .modeling import (  # noqa: F401
    BertConfig,
    BertForMaskedLM,
    BertForSequenceClassification,
    BertModel,
)
