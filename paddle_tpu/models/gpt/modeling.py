"""GPT family (parity anchor: the reference's 3D-hybrid GPT tests,
/root/reference/test/auto_parallel/ GPT cases; architecture = pre-LN GPT-2/3:
learned positions, LayerNorm, GELU MLP, MHA).

Same mesh-aware design as Llama: logical-axis-annotated params, GSPMD sharding.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...distributed.auto_parallel.logical_sharding import annotate, constrain
from ...nn import initializer as I
from ...nn.layer.layers import Layer, LayerList
from ..generation_utils import GenerationMixin
from ..llama.modeling import _attention, _raw


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, intermediate_size=None,
                 num_hidden_layers=12, num_attention_heads=12,
                 max_position_embeddings=1024, layer_norm_eps=1e-5,
                 initializer_range=0.02, dtype="float32", recompute=False,
                 use_flash_attention=True):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.num_key_value_heads = num_attention_heads
        self.max_position_embeddings = max_position_embeddings
        self.layer_norm_eps = layer_norm_eps
        self.initializer_range = initializer_range
        self.dtype = dtype
        self.recompute = recompute
        self.use_flash_attention = use_flash_attention

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def tiny(cls, **over):
        d = dict(vocab_size=256, hidden_size=64, num_hidden_layers=2,
                 num_attention_heads=4, max_position_embeddings=128)
        d.update(over)
        return cls(**d)


class GPTLayerNorm(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.eps = config.layer_norm_eps
        self.weight = annotate(self.create_parameter(
            [config.hidden_size], dtype=config.dtype,
            default_initializer=I.Constant(1.0)), "norm")
        self.bias = annotate(self.create_parameter(
            [config.hidden_size], dtype=config.dtype, is_bias=True), "norm")

    def forward(self, x):
        x = _raw(x)
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        out = ((xf - mu) * jax.lax.rsqrt(var + self.eps)).astype(x.dtype)
        return out * self.weight._data + self.bias._data


class GPTAttention(Layer):
    def decode_step(self, x, k_cache, v_cache, pos, pad_bias=None):
        """KV-cache attention for generation (prefill AND decode)."""
        from ..generation_utils import causal_cache_bias
        from ...nn.functional.flash_attention import _xla_attention

        x = _raw(x)
        b, s, h = x.shape
        hd = self.config.head_dim
        qkv = jnp.matmul(x, self.qkv_weight._data) + self.qkv_bias._data
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, self.num_heads, hd)
        k = k.reshape(b, s, self.num_heads, hd)
        v = v.reshape(b, s, self.num_heads, hd)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, pos, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, pos, 0, 0))
        bias = causal_cache_bias(k_cache, pos, s, pad_bias)
        out = _xla_attention(q, k_cache, v_cache, bias=bias, causal=False)
        out = out.reshape(b, s, h)
        return (jnp.matmul(out, self.out_weight._data)
                + self.out_bias._data, k_cache, v_cache)

    def paged_decode_step(self, x, k_pages, v_pages, tables, pos):
        """Paged-KV generation step (serving suite) — see the llama analogue."""
        from ...ops.flash_attention import flash_attention
        from ...ops.paged_attention import append_paged_kv, paged_decode_attention

        x = _raw(x)
        b, s, h = x.shape
        hd = self.config.head_dim
        qkv = jnp.matmul(x, self.qkv_weight._data) + self.qkv_bias._data
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, self.num_heads, hd)
        k = k.reshape(b, s, self.num_heads, hd)
        v = v.reshape(b, s, self.num_heads, hd)
        seq_ids = jnp.repeat(jnp.arange(b, dtype=jnp.int32), s)
        positions = jnp.tile(pos + jnp.arange(s, dtype=jnp.int32), b)
        k_pages, v_pages = append_paged_kv(
            k_pages, v_pages, k.reshape(b * s, self.num_heads, hd),
            v.reshape(b * s, self.num_heads, hd), tables, positions, seq_ids)
        if s == 1:
            ctx = jnp.full((b,), pos + 1, jnp.int32)
            out = paged_decode_attention(q[:, 0], k_pages, v_pages, tables,
                                         ctx)[:, None]
        else:
            out = flash_attention(q, k, v, causal=True)
        out = out.reshape(b, s, h)
        return (jnp.matmul(out, self.out_weight._data)
                + self.out_bias._data, k_pages, v_pages)

    def paged_prefill_chunk(self, x, k_pages, v_pages, tables, starts):
        """Prefill CHUNK at per-row absolute offsets over cached history
        (prefix-cache / chunked-prefill serving path) — llama analogue."""
        from ...ops.paged_attention import (append_paged_kv,
                                            paged_prefill_attention)

        x = _raw(x)
        b, s, h = x.shape
        hd = self.config.head_dim
        page = k_pages.shape[2]
        max_len = tables.shape[1] * page
        qkv = jnp.matmul(x, self.qkv_weight._data) + self.qkv_bias._data
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, self.num_heads, hd)
        k = k.reshape(b, s, self.num_heads, hd)
        v = v.reshape(b, s, self.num_heads, hd)
        seq_ids = jnp.repeat(jnp.arange(b, dtype=jnp.int32), s)
        positions = jnp.clip(starts[:, None] + jnp.arange(s, dtype=jnp.int32),
                             0, max_len - 1).reshape(-1)
        k_pages, v_pages = append_paged_kv(
            k_pages, v_pages, k.reshape(b * s, self.num_heads, hd),
            v.reshape(b * s, self.num_heads, hd), tables, positions, seq_ids)
        out = paged_prefill_attention(q, k_pages, v_pages, tables, starts)
        out = out.reshape(b, s, h)
        return (jnp.matmul(out, self.out_weight._data)
                + self.out_bias._data, k_pages, v_pages)

    def paged_token_step(self, x, k_pages, v_pages, tables, pos_vec):
        """ONE token per row at PER-ROW positions (continuous batching)."""
        from ...ops.paged_attention import append_paged_kv, paged_decode_attention

        x = _raw(x)
        b = x.shape[0]
        h = x.shape[-1]
        hd = self.config.head_dim
        qkv = jnp.matmul(x, self.qkv_weight._data) + self.qkv_bias._data
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, 1, self.num_heads, hd)
        k = k.reshape(b, 1, self.num_heads, hd)
        v = v.reshape(b, 1, self.num_heads, hd)
        k_pages, v_pages = append_paged_kv(
            k_pages, v_pages, k[:, 0], v[:, 0], tables, pos_vec)
        out = paged_decode_attention(q[:, 0], k_pages, v_pages, tables,
                                     pos_vec + 1)
        out = out.reshape(b, 1, h)
        return (jnp.matmul(out, self.out_weight._data)
                + self.out_bias._data, k_pages, v_pages)

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        h, hd = config.hidden_size, config.head_dim
        self.num_heads = config.num_attention_heads
        init = I.Normal(std=config.initializer_range)
        mk = lambda shape, axes: annotate(self.create_parameter(
            shape, dtype=config.dtype, default_initializer=init), *axes)
        self.qkv_weight = mk([h, 3 * h], ("embed", "heads"))
        self.qkv_bias = annotate(self.create_parameter(
            [3 * h], dtype=config.dtype, is_bias=True), "heads")
        self.out_weight = mk([h, h], ("heads", "embed"))
        self.out_bias = annotate(self.create_parameter(
            [h], dtype=config.dtype, is_bias=True), "norm")

    def forward(self, hidden):
        x = _raw(hidden)
        b, s, h = x.shape
        hd = self.config.head_dim
        qkv = jnp.matmul(x, self.qkv_weight._data) + self.qkv_bias._data
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, self.num_heads, hd)
        k = k.reshape(b, s, self.num_heads, hd)
        v = v.reshape(b, s, self.num_heads, hd)
        q = constrain(q, "batch", "seq", "heads", "head_dim")
        out = _attention(q, k, v, self.config)
        out = out.reshape(b, s, h)
        return jnp.matmul(out, self.out_weight._data) + self.out_bias._data


class GPTMLP(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        init = I.Normal(std=config.initializer_range)
        self.fc_weight = annotate(self.create_parameter(
            [h, m], dtype=config.dtype, default_initializer=init), "embed", "mlp")
        self.fc_bias = annotate(self.create_parameter(
            [m], dtype=config.dtype, is_bias=True), "mlp")
        self.proj_weight = annotate(self.create_parameter(
            [m, h], dtype=config.dtype, default_initializer=init), "mlp", "embed")
        self.proj_bias = annotate(self.create_parameter(
            [h], dtype=config.dtype, is_bias=True), "norm")

    def forward(self, x):
        x = _raw(x)
        a = jax.nn.gelu(jnp.matmul(x, self.fc_weight._data) + self.fc_bias._data)
        a = constrain(a, "batch", "seq", "mlp")
        return jnp.matmul(a, self.proj_weight._data) + self.proj_bias._data


class GPTDecoderLayer(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = GPTLayerNorm(config)
        self.attn = GPTAttention(config)
        self.ln_2 = GPTLayerNorm(config)
        self.mlp = GPTMLP(config)

    def forward(self, hidden):
        x = _raw(hidden)
        x = x + self.attn(self.ln_1(x))
        x = x + self.mlp(self.ln_2(x))
        return constrain(x, "batch", "seq", "embed")


    def paged_decode_step(self, hidden, k_pages, v_pages, tables, pos):
        x = _raw(hidden)
        a, k_pages, v_pages = self.attn.paged_decode_step(
            self.ln_1(x), k_pages, v_pages, tables, pos)
        x = x + a
        x = x + _raw(self.mlp(self.ln_2(x)))
        return x, k_pages, v_pages

    def paged_token_step(self, hidden, k_pages, v_pages, tables, pos_vec):
        x = _raw(hidden)
        a, k_pages, v_pages = self.attn.paged_token_step(
            self.ln_1(x), k_pages, v_pages, tables, pos_vec)
        x = x + a
        x = x + _raw(self.mlp(self.ln_2(x)))
        return x, k_pages, v_pages

    def paged_prefill_chunk(self, hidden, k_pages, v_pages, tables, starts):
        x = _raw(hidden)
        a, k_pages, v_pages = self.attn.paged_prefill_chunk(
            self.ln_1(x), k_pages, v_pages, tables, starts)
        x = x + a
        x = x + _raw(self.mlp(self.ln_2(x)))
        return x, k_pages, v_pages

    def decode_step(self, hidden, k_cache, v_cache, pos, pad_bias=None):
        x = _raw(hidden)
        a, k_cache, v_cache = self.attn.decode_step(
            self.ln_1(x), k_cache, v_cache, pos, pad_bias)
        x = x + a
        x = x + self.mlp(self.ln_2(x))
        return x, k_cache, v_cache


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        init = I.Normal(std=config.initializer_range)
        self.wte = annotate(self.create_parameter(
            [config.vocab_size, config.hidden_size], dtype=config.dtype,
            default_initializer=init), "vocab_in", "embed")
        self.wpe = annotate(self.create_parameter(
            [config.max_position_embeddings, config.hidden_size],
            dtype=config.dtype, default_initializer=init), "seq", "embed")
        self.layers = LayerList([GPTDecoderLayer(config)
                                 for _ in range(config.num_hidden_layers)])
        self.ln_f = GPTLayerNorm(config)

    def forward(self, input_ids):
        ids = _raw(input_ids)
        table = constrain(self.wte._data, None, None)
        x = jnp.take(table, ids, axis=0) + self.wpe._data[: ids.shape[1]]
        x = constrain(x, "batch", "seq", "embed")
        remat = self.config.recompute and isinstance(x, jax.core.Tracer)
        for layer in self.layers:
            if remat:
                x = jax.checkpoint(lambda h, lyr=layer: lyr(h))(x)
            else:
                x = layer(x)
        return self.ln_f(x)


class GPTForCausalLM(GenerationMixin, Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)

    def forward(self, input_ids, labels=None):
        from ..llama.modeling import LlamaPretrainingCriterion

        hidden = self.gpt(input_ids)
        logits = jnp.matmul(hidden, self.gpt.wte._data.T)
        logits = constrain(logits, "batch", "seq", "vocab")
        if labels is None:
            return Tensor(logits) if not isinstance(logits, jax.core.Tracer) else logits
        return LlamaPretrainingCriterion.compute(logits, _raw(labels))

    def loss_fn(self, input_ids, labels):
        return self.forward(input_ids, labels)


    # ---- generation hooks (GenerationMixin; default _init_caches) ----
    def _validate_generate(self, prompt_len, total_len):
        if total_len > self.config.max_position_embeddings:
            raise ValueError(
                f"GPT learned position table holds "
                f"{self.config.max_position_embeddings} positions; prompt + "
                f"max_new_tokens = {total_len} exceeds it")

    def paged_token_step(self, toks, caches, pos_vec):
        """Continuous-batching hook (see inference/serving.py): one token per
        slot at per-slot positions. Same parked-row contract as the llama
        hook: inactive rows run at pos_vec == 0 over a parking-page table
        (their dummy append and logits are inert), and the body stays
        shape-static in the row count — the fused mega-step scans it over
        all max_batch rows."""
        cfg = self.config
        posc = jnp.clip(pos_vec, 0, cfg.max_position_embeddings - 1)
        x = (jnp.take(self.gpt.wte._data, toks[:, None], axis=0)
             + self.gpt.wpe._data[posc][:, None])
        tables = caches["tables"]
        new_kv = []
        for layer, (kp, vp) in zip(self.gpt.layers, caches["kv"]):
            x, kp, vp = layer.paged_token_step(x, kp, vp, tables, pos_vec)
            new_kv.append((kp, vp))
        hidden = _raw(self.gpt.ln_f(x))
        logits = jnp.matmul(hidden[:, -1], self.gpt.wte._data.T)
        return logits.astype(jnp.float32), {"kv": new_kv, "tables": tables}

    def paged_prefill_chunk(self, ids, caches, starts):
        """Serving hook (see the llama analogue): one prefill chunk per row
        at per-row absolute offsets over cached history; returns caches.
        Honors the packed-rows contract (``_run_pack``): rows may share
        one sequence's table at different starts, and k/v appends land
        before any row's attention gathers per layer."""
        ids = _raw(ids)
        b, s = ids.shape
        positions = jnp.clip(starts[:, None] + jnp.arange(s)[None, :], 0,
                             self.config.max_position_embeddings - 1)
        x = (jnp.take(self.gpt.wte._data, ids, axis=0)
             + self.gpt.wpe._data[positions])
        tables = caches["tables"]
        new_kv = []
        for layer, (kp, vp) in zip(self.gpt.layers, caches["kv"]):
            x, kp, vp = layer.paged_prefill_chunk(x, kp, vp, tables, starts)
            new_kv.append((kp, vp))
        return {"kv": new_kv, "tables": tables}

    def paged_verify_step(self, toks, caches, pos_vec):
        """Speculative-decode VERIFY hook (llama analogue — see
        ``LlamaForCausalLM.paged_verify_step``): one K+1-token window per
        row at absolute positions ``pos_vec[b] + i`` through the chunk
        machinery, with logits over EVERY window position for the
        engine's in-graph accept/reject. Parked rows are inert."""
        ids = _raw(toks)
        b, s = ids.shape
        positions = jnp.clip(pos_vec[:, None] + jnp.arange(s)[None, :], 0,
                             self.config.max_position_embeddings - 1)
        x = (jnp.take(self.gpt.wte._data, ids, axis=0)
             + self.gpt.wpe._data[positions])
        tables = caches["tables"]
        new_kv = []
        for layer, (kp, vp) in zip(self.gpt.layers, caches["kv"]):
            x, kp, vp = layer.paged_prefill_chunk(x, kp, vp, tables, pos_vec)
            new_kv.append((kp, vp))
        hidden = _raw(self.gpt.ln_f(x))
        logits = jnp.matmul(hidden, self.gpt.wte._data.T)
        return logits.astype(jnp.float32), {"kv": new_kv, "tables": tables}

    def _decode_chunk(self, ids, caches, pos, pad_bias, pos_offset):
        ids = _raw(ids)
        b, s = ids.shape
        x = jnp.take(self.gpt.wte._data, ids, axis=0)
        if pos_offset is None:
            wpe = jax.lax.dynamic_slice_in_dim(self.gpt.wpe._data, pos, s, 0)
            x = x + wpe[None]
        else:
            positions = jnp.clip(pos + jnp.arange(s)[None, :]
                                 - pos_offset[:, None], 0,
                                 self.config.max_position_embeddings - 1)
            x = x + self.gpt.wpe._data[positions]
        if isinstance(caches, dict):  # paged-KV serving path
            tables = caches["tables"]
            new_kv = []
            for layer, (kp, vp) in zip(self.gpt.layers, caches["kv"]):
                x, kp, vp = layer.paged_decode_step(x, kp, vp, tables, pos)
                new_kv.append((kp, vp))
            new_caches = {"kv": new_kv, "tables": tables}
        else:
            new_caches = []
            for layer, (kc, vc) in zip(self.gpt.layers, caches):
                x, kc, vc = layer.decode_step(x, kc, vc, pos, pad_bias)
                new_caches.append((kc, vc))
        hidden = _raw(self.gpt.ln_f(x))
        logits = jnp.matmul(hidden[:, -1], self.gpt.wte._data.T)
        return logits.astype(jnp.float32), new_caches
