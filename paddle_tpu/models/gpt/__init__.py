from .modeling import GPTConfig, GPTDecoderLayer, GPTForCausalLM, GPTModel  # noqa: F401
