"""Shared KV-cache generation machinery for the causal-LM families.

The model provides two hooks:
  - ``_init_caches(batch, max_len) -> caches`` (pytree of arrays)
  - ``_decode_chunk(ids, caches, pos, pad_bias, pos_offset) ->
    (last_logits [b, vocab] f32, caches)`` — run a chunk at absolute
    positions [pos, pos+s) through the cache path

and the mixin supplies ``generate()``: jitted prefill + 16-token jitted
lax.scan decode blocks (per-call dispatch is the decode bottleneck through a
remote runtime — see the llama 35x measurement), fused sampling, LEFT-padded
batching, eos early-stop with static output shape, and cache-length bucketing
via ``max_length``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

DECODE_BLOCK = 16


def validate_sampling(temperature, top_p, top_k=0):
    """Shared range checks for sampling params (generate() + serving Request).

    Out-of-range values fail loudly here instead of silently degenerating in
    ``sample_rows`` (e.g. top_p < 0 masks every candidate, making categorical
    sample near-uniformly over the whole vocab).
    """
    # `not (x >= 0)` (vs `x < 0`) also rejects NaN
    if temperature is not None and not float(temperature) >= 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_p is not None and not 0.0 < float(top_p) <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k is not None and int(top_k) < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")


def sample_rows(logits, keys, temps, top_ps, top_ks):
    """Row-vectorized sampling: per-row temperature/top-p/top-k/key.

    THE sampling implementation — ``generate()`` and the continuous-batching
    serving engine both draw through it, so their distributions are identical
    by construction (reference sampling op: python/paddle/tensor/search.py:1362
    top_p_sampling).

    logits [b, V] f32; keys: typed PRNG key array [b]; temps/top_ps [b] f32;
    top_ks [b] int32 (0 = disabled). temperature<=0 rows take argmax.
    """
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, -1).astype(jnp.int32)
    lg = logits / jnp.maximum(temps[:, None], 1e-6)
    sort_idx = jnp.argsort(-lg, axis=-1)
    sorted_lg = jnp.take_along_axis(lg, sort_idx, -1)
    p = jax.nn.softmax(sorted_lg, -1)
    cum = jnp.cumsum(p, -1)
    keep = (cum - p) <= top_ps[:, None]
    kk = jnp.where(top_ks > 0, top_ks, V)
    keep = keep & (jnp.arange(V)[None, :] < kk[:, None])
    masked = jnp.where(keep, sorted_lg, -1e9)
    choice = jax.vmap(jax.random.categorical)(keys, masked)
    sampled = jnp.take_along_axis(sort_idx, choice[:, None], -1)[:, 0]
    return jnp.where(temps <= 0.0, greedy, sampled.astype(jnp.int32))


def fold_keys(seeds, positions):
    """Stateless per-row keys: fold the token position into the request seed."""
    return jax.vmap(
        lambda s, p: jax.random.fold_in(jax.random.key(s), p))(seeds, positions)


class GenerationMixin:
    def _init_caches(self, b, max_len):
        """Default KV caches [b, max_len, kv_heads, head_dim] per layer; a
        family with a different cache layout (paged KV, MQA) overrides this."""
        cfg = self.config
        kvh = getattr(cfg, "num_key_value_heads", cfg.num_attention_heads)
        hd = cfg.head_dim
        dtype = next(iter(p._data.dtype for _, p in self.named_parameters()))
        return [(jnp.zeros((b, max_len, kvh, hd), dtype),
                 jnp.zeros((b, max_len, kvh, hd), dtype))
                for _ in range(cfg.num_hidden_layers)]

    def _validate_generate(self, prompt_len, max_len):
        """Hook for family-specific length limits (e.g. learned position
        tables); the default (RoPE-style) has none."""

    def _decode_fns(self, temperature, top_p):
        """Jitted prefill/block closures, cached per (temperature, top_p)."""
        key = (float(temperature), top_p)
        cache = getattr(self, "_gen_fns", None)
        if cache is not None and key in cache:
            return cache[key]
        from ..core import autograd_engine
        from ..jit.api import _Swap, _collect_state

        _, tensors = _collect_state(self)

        def sample(logits, skey):
            if temperature == 0.0:
                return jnp.argmax(logits, -1).astype(jnp.int32)
            b = logits.shape[0]
            return sample_rows(
                logits, jax.random.split(skey, b),
                jnp.full((b,), temperature, jnp.float32),
                jnp.full((b,), 1.0 if top_p is None else top_p, jnp.float32),
                jnp.zeros((b,), jnp.int32))

        def run_chunk(ps, chunk, cs, pos, pad_bias, pos_offset, skey):
            with autograd_engine.no_grad(), _Swap(tensors, ps):
                logits, cs = self._decode_chunk(chunk, cs, pos, pad_bias,
                                                pos_offset)
            return sample(logits, skey), cs

        def decode_block(ps, tok, cs, pos0, pad_bias, pos_offset, skey,
                         finished, eos, n_steps):
            def body(carry, i):
                tok, cs, k, fin = carry
                k, sk = jax.random.split(k)
                nxt, cs = run_chunk(ps, tok[:, None], cs, pos0 + i,
                                    pad_bias, pos_offset, sk)
                if eos is not None:
                    nxt = jnp.where(fin, eos, nxt)
                    fin = fin | (nxt == eos)
                return (nxt, cs, k, fin), nxt

            (tok, cs, skey, finished), toks = jax.lax.scan(
                body, (tok, cs, skey, finished), jnp.arange(n_steps))
            return jnp.swapaxes(toks, 0, 1), tok, cs, skey, finished

        # no donate_argnums: buffer donation through the remote-compile tunnel
        # is a measured 10x slow path; the extra cache copy is cheap
        prefill = jax.jit(run_chunk)
        block = jax.jit(decode_block, static_argnames=("eos", "n_steps"))
        if cache is None:
            cache = self._gen_fns = {}
        cache[key] = (prefill, block)
        return prefill, block

    def _init_paged_caches(self, b, max_len, page_size=64, num_blocks=None,
                           kv_dtype=None):
        """Paged-KV pools (serving layout, ops/paged_attention.py): per-layer
        page pools + a shared block table with pages statically assigned per
        sequence. ``num_blocks`` overrides the pool size (>= b * pages_per_
        seq) for engines that manage pages dynamically — prefix caching
        needs headroom for retained cache blocks plus a parking page.
        ``kv_dtype="int8"`` builds pools in the int8 block format
        (``QuantizedKVPool``: int8 pages + per-(page, head) absmax scales,
        quantize-on-append / dequantize-in-gather — serving.KVCacheConfig).
        Families with a different cache layout override this."""
        cfg = self.config
        kvh = getattr(cfg, "num_key_value_heads", cfg.num_attention_heads)
        hd = cfg.head_dim
        dtype = next(iter(p._data.dtype for _, p in self.named_parameters()))
        maxp = -(-max_len // page_size)
        npages = b * maxp if num_blocks is None else int(num_blocks)
        if npages < b * maxp:
            raise ValueError(f"num_blocks {npages} < {b * maxp} — the pool "
                             "cannot back every slot's table")
        tables = jnp.arange(b * maxp, dtype=jnp.int32).reshape(b, maxp)
        if kv_dtype == "int8":
            from ..ops.paged_attention import QuantizedKVPool

            def pool():
                return QuantizedKVPool(
                    jnp.zeros((npages, kvh, page_size, hd), jnp.int8),
                    jnp.zeros((npages, kvh), jnp.float32))

            kv = [(pool(), pool()) for _ in range(cfg.num_hidden_layers)]
        elif kv_dtype not in (None, "param"):
            raise ValueError(f"unsupported kv_dtype {kv_dtype!r} "
                             "(supported: None/'param', 'int8')")
        else:
            kv = [(jnp.zeros((npages, kvh, page_size, hd), dtype),
                   jnp.zeros((npages, kvh, page_size, hd), dtype))
                  for _ in range(cfg.num_hidden_layers)]
        return {"kv": kv, "tables": tables}

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 1.0, top_p: float = None,
                 eos_token_id: int = None, seed: int = 0,
                 attention_mask=None, max_length: int = None,
                 cache_impl: str = "dense", page_size: int = 64):
        """KV-cache autoregressive generation (greedy / temperature / top-p).

        Batches of unequal prompt lengths use LEFT padding +
        ``attention_mask`` [b, prompt_len] (1 = real): pad columns are
        bias-masked out of attention and positions shift per row so each
        prompt starts at position 0. Always returns [b, max_new_tokens]
        (rows that hit eos early are padded out with eos). ``max_length``
        pins the KV-cache bucket so repeated calls with varying lengths hit
        the compiled-program cache.
        """
        from ..jit.api import _collect_state

        validate_sampling(temperature, top_p)
        ids = (input_ids._data if isinstance(input_ids, Tensor)
               else jnp.asarray(input_ids)).astype(jnp.int32)
        b, prompt_len = ids.shape
        max_len = (max_length if max_length is not None
                   else prompt_len + max_new_tokens)
        if max_len < prompt_len + max_new_tokens:
            raise ValueError(
                f"max_length {max_len} < prompt {prompt_len} + "
                f"max_new_tokens {max_new_tokens}")
        self._validate_generate(prompt_len, prompt_len + max_new_tokens)
        _, tensors = _collect_state(self)
        params = [t._data for t in tensors]
        if cache_impl == "paged":
            if attention_mask is not None:
                raise ValueError(
                    "cache_impl='paged' does not support attention_mask "
                    "(left padding) yet — use equal-length prompts")
            caches = self._init_paged_caches(b, max_len, page_size)
        else:
            caches = self._init_caches(b, max_len)

        if attention_mask is not None:
            m = (attention_mask._data if isinstance(attention_mask, Tensor)
                 else jnp.asarray(attention_mask)).astype(jnp.int32)
            if bool((m[:, -1] == 0).any()) or bool(
                    (jnp.diff(m, axis=1) < 0).any()):
                raise ValueError(
                    "generate() expects LEFT-padded prompts: attention_mask "
                    "must be 0...01...1 per row (pads strictly before tokens)")
            pad_cols = jnp.concatenate(
                [m == 0, jnp.zeros((b, max_len - prompt_len), bool)], axis=1)
            pad_bias = jnp.where(pad_cols, -1e9, 0.0)[:, None, None, :]
            pos_offset = (prompt_len - m.sum(-1)).astype(jnp.int32)
        else:
            pad_bias = None
            pos_offset = None

        prefill, block = self._decode_fns(temperature, top_p)
        key = jax.random.key(seed)
        key, sk = jax.random.split(key)
        tok, caches = prefill(params, ids, caches, 0, pad_bias, pos_offset, sk)
        chunks = [tok[:, None]]
        finished = jnp.zeros((b,), bool)
        if eos_token_id is not None:
            finished = finished | (tok == eos_token_id)
        done = 1
        while done < max_new_tokens:
            if eos_token_id is not None and bool(finished.all()):
                break
            n = min(DECODE_BLOCK, max_new_tokens - done)
            toks, tok, caches, key, finished = block(
                params, tok, caches, prompt_len + done - 1, pad_bias,
                pos_offset, key, finished, eos_token_id, n)
            chunks.append(toks)
            done += n
        out = jnp.concatenate(chunks, axis=1)
        if out.shape[1] < max_new_tokens:
            pad = jnp.full((b, max_new_tokens - out.shape[1]), eos_token_id,
                           jnp.int32)
            out = jnp.concatenate([out, pad], axis=1)
        return Tensor(out)


def causal_cache_bias(k_cache, pos, s, pad_bias=None):
    """[1, 1, s, max_len] additive bias: chunk row i (absolute pos+i) sees
    cache cols <= pos+i; composes with the left-pad bias."""
    max_len = k_cache.shape[1]
    cols = jnp.arange(max_len)[None, :]
    rows = pos + jnp.arange(s)[:, None]
    bias = jnp.where(cols <= rows, 0.0, -1e9)[None, None]
    if pad_bias is not None:
        bias = bias + pad_bias
    return bias
