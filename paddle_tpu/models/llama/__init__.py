from .modeling import (  # noqa: F401
    LlamaConfig,
    LlamaDecoderLayer,
    LlamaForCausalLM,
    LlamaModel,
    LlamaPretrainingCriterion,
)
